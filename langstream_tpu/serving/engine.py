"""Continuous-batching serving engine.

Execution model:

- A fixed pool of ``slots`` (the decode batch dimension). Each active slot
  owns a row of the KV cache ``(L, slots, S, K, D)``.
- **Admission**: a queued request prefilles into a free slot (prompt padded
  to a power-of-two bucket → few compiled shapes) and immediately joins the
  decode batch. No stop-the-world: decode keeps a fixed batch shape, so a
  new arrival never recompiles anything.
- **Decode**: one jitted step advances *all* active slots one token;
  sampling happens in-jit (see sampler.py), only (B,) token ids come back.
- **At-least-once friendly**: generation is driven by the agent layer's
  record loop; the engine itself is agnostic to commits.
- **Sharding**: with a mesh, params are TP-sharded (Megatron), cache shards
  KV heads on ``tp`` and slots on ``dp``; XLA places the collectives on ICI.
  An ``sp`` axis makes long prefills sequence-parallel (ring attention);
  ``ep`` shards MoE experts.
- **Paged serving schedulers** (``kv-layout: paged``): automatic prefix
  caching (shared prompt prefixes adopt content-addressed blocks; suffix-
  only prefill), chunked prefill (long prompts interleave with decode
  bursts), and prompt-lookup speculative decoding (greedy bursts verify
  drafted continuations — streams bit-identical to plain decode).

JAX calls are dispatched through a single-thread executor so the asyncio
event loop (broker I/O, gateways) never blocks on device execution —
compute/IO overlap comes free.

Parity anchor: replaces the external-HTTP ``CompletionsService`` /
``EmbeddingsService`` providers (``OpenAIServiceProvider.java:26`` etc.) with
an in-tree engine.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import re
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from functools import partial
from typing import Any, Awaitable, Callable

import jax
import jax.numpy as jnp
import numpy as np

from langstream_tpu.api.metrics import PrometheusMetricsReporter
from langstream_tpu.core.tracing import (
    TraceContext,
    current_context,
    fresh_trace_id,
    record_span,
)
from langstream_tpu.models.llama import (
    LlamaConfig,
    init_kv_cache,
    init_llama_params,
    llama_decode_step,
    llama_param_specs,
    llama_prefill,
    kv_cache_spec,
)
from langstream_tpu.models.encoder import (
    EncoderConfig,
    encode,
    encoder_param_specs,
    init_encoder_params,
)
from langstream_tpu.models.tokenizer import Tokenizer, load_tokenizer
from langstream_tpu.serving.attribution import (
    ModelShape,
    ProgramLedger,
    decode_cost,
    memory_ledger,
    prefill_cost,
    tree_device_bytes,
    verify_cost,
)
from langstream_tpu.serving.faults import (
    FaultInjector,
    FaultPlan,
    InjectedFault,
    plans_from_env,
)
from langstream_tpu.serving.flight import FlightRecorder
from langstream_tpu.serving.incident import (
    IncidentRecorder,
    adapter_eviction_storm,
    breaker_storm,
    worst_journeys,
)
from langstream_tpu.serving.handoff import (
    DeadlineExceeded,
    parse_deadline,
    remaining_s,
)
from langstream_tpu.serving.journal import RequestJournal, request_entry
from langstream_tpu.serving.journey import JOURNEYS
from langstream_tpu.serving.health import (
    EngineWatchdog,
    SloObjective,
    SloSpec,
    SloTracker,
)
from langstream_tpu.serving.streaming import STREAMS, TbtDigest
from langstream_tpu.serving.adapters import (
    AdapterStore,
    AdapterStoreSpec,
    AdapterUnavailable,
)
from langstream_tpu.serving.prefixstore import PrefixStore, PrefixStoreSpec
from langstream_tpu.serving.profiling import (
    ProfilerHooks,
    detect_generation,
    detect_hbm_capacity,
    detect_hbm_gbps,
)
from langstream_tpu.serving.qos import (
    PRIORITY_CLASSES,
    QosSpec,
    RateLimited,
    normalize_priority,
    priority_rank,
)
from langstream_tpu.serving.sampler import sample_tokens
from langstream_tpu.serving.scheduler import make_scheduler

log = logging.getLogger(__name__)

_MODEL_CONFIGS = {
    "tiny": LlamaConfig.tiny,
    "llama-1b": LlamaConfig.llama_1b,
    "llama3-8b": LlamaConfig.llama3_8b,
    "llama-3-8b": LlamaConfig.llama3_8b,
    "llama3-70b": LlamaConfig.llama3_70b,
    "llama-3-70b": LlamaConfig.llama3_70b,
}

# MoE (Mixtral-family) models serve on the same engine: identical attention
# and cache geometry, routed-expert FFN plugged into the shared layer math
# (models/moe.py `moe_serving_ffn`). Lazy: moe.py imports only when used.
_MOE_MODELS = ("moe-tiny", "moe-8x7b", "mixtral-8x7b")

#: adaptive pool-shrink (docs/RESILIENCE.md): preempt-and-retry rounds a
#: stranded (never-prefilled) request gets before its failure stops
#: being treated as transient pressure and it is shed loudly — the
#: bound that keeps a deterministically failing dispatch from
#: livelocking the loop in an admit→OOM→requeue cycle
_SHRINK_RETRY_CAP = 3

#: jaxlib/XLA allocator-failure spellings (plus the BlockManager's own
#: "pool exhausted") — the classifier behind the degrade-don't-die path
#: (docs/RESILIENCE.md). One compiled regex so every catch site agrees.
_RESOURCE_EXHAUSTED_RE = re.compile(
    r"RESOURCE_EXHAUSTED"
    r"|pool exhausted"
    r"|Out of memory"
    r"|Failed to allocate"
    r"|Allocation .* exceeds"
)


def _resolve_model_config(name: str, max_seq_len: int):
    if name in _MOE_MODELS:
        from langstream_tpu.models.moe import MoEConfig

        factory = {
            "moe-tiny": MoEConfig.tiny,
            "moe-8x7b": MoEConfig.mixtral_8x7b,
            "mixtral-8x7b": MoEConfig.mixtral_8x7b,
        }[name]
        return factory(max_seq_len=max_seq_len)
    if name not in _MODEL_CONFIGS:
        raise ValueError(
            f"unknown model {name!r}; known: "
            f"{sorted(_MODEL_CONFIGS) + sorted(_MOE_MODELS)}"
        )
    return _MODEL_CONFIGS[name](max_seq_len=max_seq_len)


def _parse_bool(v: Any) -> bool:
    """YAML/env values arrive as strings; bool("false") is True, so parse."""
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    model: str = "tiny"
    slots: int = 8
    max_seq_len: int = 512
    tokenizer: str | None = None       # None/"byte" or local HF path
    checkpoint: str | None = None      # local weights dir (gated; random init otherwise)
    mesh: tuple[tuple[str, int], ...] = ()  # e.g. (("dp",1),("tp",8)); () = single device
    default_max_tokens: int = 128
    seed: int = 0
    # decode steps fused into one jitted lax.scan per host round-trip —
    # the host sync (not device compute) dominates per-step cost, so K
    # steps per sync multiplies throughput by ~K at a K-token batching
    # cost in streaming latency
    decode_chunk: int = 16
    # adaptive decode chunking for the TTFT regime: while the active slot
    # count is <= light_load_slots (default slots // 8 — well under
    # capacity, where admission latency matters and throughput headroom is
    # free), bursts fuse only decode_chunk_light steps and dispatch them
    # SEQUENTIALLY (no speculative chunk in flight), so a newly arrived
    # request waits at most decode_chunk_light steps for prefill instead
    # of up to 2 x decode_chunk. Past the threshold the engine reverts to
    # pipelined decode_chunk bursts. 0 disables (always heavy chunks).
    decode_chunk_light: int = 8
    light_load_slots: int | None = None
    # pre-compile the serving-path jit variants on the first request (a
    # lone probe + a concurrent wave past the light-load threshold): real
    # traffic then never waits on a compile. First-compiles on TPU are
    # tens of seconds — one landing mid-traffic convoys the whole queue.
    warmup_on_start: bool = False
    # max requests prefilled in one batched call
    prefill_batch: int = 8
    # model compute/param dtype override: None keeps the model's default
    # (bf16), "float32" runs params + activations in f32. f32 makes
    # greedy streams exactly shape-independent — decode, verify, and
    # sharded paths reduce to the same argmax regardless of XLA fusion —
    # which bf16 only approximates (near-tie logits can flip between
    # differently-shaped programs, backend-dependent). Dev/CPU posture
    # and exactness tests; 2x the param+cache HBM of bf16 on chips.
    model_dtype: str | None = None
    # weight-only quantization: None (bf16) or "int8" (scales TP-shard
    # with their weights, so the mesh posture keeps the int8 default)
    quantize: str | None = None
    # KV-cache quantization (dense AND paged layouts): None (bf16) or
    # "int8" — per-(position, head)-row absmax int8 halves the cache-read
    # HBM traffic that dominates the decode roofline; the scale folds into
    # scores/probs so no bf16 cache is ever materialised (models/kvquant.py).
    # int8 reads go through the fused XLA path (Pallas kernels are bf16)
    kv_quantize: str | None = None
    # KV cache layout: "dense" reserves slots × max_seq_len rows up front;
    # "paged" shares a block pool sized kv_pool_fraction of that, with
    # worst-case admission reservations (models/paged.py)
    kv_layout: str = "dense"
    kv_block_size: int = 64
    kv_pool_fraction: float = 0.5
    kv_pool_blocks: int | None = None  # explicit pool size override
    # paged read path: "auto" (Pallas kernel on single-chip TPU, XLA gather
    # elsewhere), or force "xla" | "pallas" | "pallas-interpret"
    paged_kernel: str = "auto"
    # dense decode read path: "auto" (Pallas paged-read kernel over the
    # dense cache viewed as identity-mapped blocks on single-chip TPU; XLA
    # einsum elsewhere/under meshes), or force "xla" | "pallas" |
    # "pallas-interpret"
    dense_kernel: str = "auto"
    # automatic prefix caching (paged layout only): full prompt blocks are
    # content-addressed; requests sharing a prefix (system preambles, RAG
    # templates, chat history) adopt the cached blocks read-only and
    # prefill just the suffix — the TTFT lever for shared-prefix traffic
    prefix_cache: bool = True
    # prompt-lookup speculative decoding (paged layout, greedy bursts):
    # each step drafts N continuation tokens by matching the
    # context's last bigram earlier in the context (strong on RAG /
    # summarization / code where output copies input) and verifies them in
    # ONE forward; greedy acceptance emits only tokens the model would
    # have produced anyway, so streams are bit-identical to plain decode —
    # accepted drafts just arrive ~k tokens per step. 0 disables.
    speculative_drafts: int = 0
    # chunked prefill (paged layout only): prompts whose to-prefill length
    # exceeds this are admitted immediately but prefilled prefill_chunk
    # tokens at a time through the continuation path, INTERLEAVED with
    # decode bursts — a long prompt no longer stalls every active stream
    # for its whole prefill (head-of-line blocking). 0 disables.
    prefill_chunk: int = 0
    # multi-tenant QoS (serving/qos.py, serving/scheduler.py): None keeps
    # the FIFO admission queue (the pre-QoS engine, bit for bit); a
    # QosSpec switches admission to priority classes with WDRR dequeue,
    # bounded per-class queues, per-tenant token buckets, and preemptive
    # load shedding under KV pressure (docs/SCHEDULING.md)
    qos: QosSpec | None = None
    # depth-2 pipelined decode dispatch (docs/PIPELINE.md): heavy bursts
    # overlap the host's fetch/detokenize/stop-check of chunk N with the
    # device's execution of chunk N+1, freeze finished slots device-side
    # instead of tearing the burst down, carry the in-flight chunk across
    # the burst boundary so prefill dispatches interleave under it, and
    # report the overlapped-vs-exposed host split in the flight rollup.
    # False (or LS_TPU_PIPELINE=0 in the environment) falls back to the
    # sequential loop — the reference the equivalence tests compare
    # against. Greedy output is byte-identical across the two loops with
    # model_dtype=float32 (exactly shape-independent argmax); under the
    # bf16 default the loops legitimately run differently-shaped
    # programs (frozen-slot bursts vs teardown/re-bucket), so near-tie
    # logits can flip — the same caveat model_dtype documents above.
    pipeline: bool = True
    # engine watchdog (serving/health.py): the engine is declared WEDGED
    # (liveness probe fails, k8s reschedules the pod) when no loop-boundary
    # progress occurs for this many seconds while work is queued or in
    # flight. Must exceed the worst single loop gap — on TPU the first XLA
    # compile of a variant (tens of seconds); warmup-on-start pods, whose
    # compiles land inside the readiness window, can run it much tighter.
    wedge_window_s: float = 60.0
    # SLO objectives (serving/health.py SloSpec): targets for TTFT /
    # queue-wait quantiles, shed rate, and availability, evaluated
    # engine-side with multi-window burn rates; None disables tracking
    slo: SloSpec | None = None
    # streaming token delivery + TBT plane (docs/OBSERVABILITY.md
    # Streaming & TBT): False (the default) keeps every pre-streaming
    # surface pinned bit for bit — no new flight-event kinds, no new
    # Prometheus series, no stats() section. True activates the
    # per-chunk telemetry around on_chunk consumers: the bounded TBT
    # digest into request_timings, stream-emit/stream-stall/
    # stream-cancel flight events, stats()["streaming"], per-QoS-class
    # langstream_stream_tbt_seconds histograms, and (with qos classes
    # declaring tbt-p99-s) per-class burn trackers behind the health()
    # tbt_burn predicate. Chunk DELIVERY itself needs no flag — the
    # flag gates observability, not the API.
    streaming: bool = False
    # stall line (seconds between chunk emissions) for classes without
    # their own tbt-p99-s target: an inter-emit gap past this records a
    # stream-stall flight event
    stream_stall_s: float = 2.0
    # disaggregated prefill/decode pools (docs/DISAGG.md): "combined"
    # (default) serves both phases in one engine — every pre-existing
    # behavior, bit for bit. "prefill" runs admission/prefill (chunked,
    # prefix-cache-aware) then EXPORTS the request's KV blocks over the
    # handoff plane (serving/kvtransfer.py) instead of decoding;
    # "decode" additionally accepts imports that join the decode batch
    # directly, skipping prefill. Both split roles require kv-layout=
    # paged (the handoff serializes paged blocks). Deployed pods get the
    # role from the StatefulSet split's LS_POOL_ROLE env (from_dict
    # fallback) so both pools share one agent config secret.
    pool_role: str = "combined"
    # tiered prefix-KV store (serving/prefixstore.py, docs/PREFIX.md):
    # None keeps the single-replica HBM-only prefix cache, bit for bit.
    # A spec layers T1 (host-RAM spill under a byte budget) and T2
    # (object storage via the kvtransfer wire format) under the T0
    # cache: eviction demotes T0→T1→T2, admission promotes/hydrates on
    # hit, and cross-replica cold starts of shared system prompts
    # hydrate instead of recomputing. Requires kv-layout=paged with
    # prefix-cache on.
    prefix_store: "PrefixStoreSpec | None" = None
    # multi-LoRA adapter store (serving/adapters.py, docs/ADAPTERS.md):
    # None keeps the single-model engine, bit for bit — no stacked
    # buffers, no new jit arguments, no new surfaces. A spec gives the
    # paged decode program a stacked per-layer A/B factor buffer with
    # t0-entries device-resident adapter rows (row 0 = zeros for
    # adapter-less slots), a T1 host-RAM spill, and a T2 object-storage
    # origin; requests name adapters via the langstream-adapter header
    # and admission blocks on hydration like the prefix stash. Requires
    # kv-layout=paged; incompatible with multi-host lockstep (followers
    # replay positional descriptors that carry no adapter rows).
    adapter_store: "AdapterStoreSpec | None" = None
    # device-survival plane (docs/RESILIENCE.md): a device allocator
    # failure (RESOURCE_EXHAUSTED and its jaxlib spellings) at a
    # pool-grow/prefill/scatter seam no longer fails every in-flight
    # request — the engine SHRINKS its effective KV admission budget by
    # shrink-fraction of the configured pool, preempts the lowest-class
    # victims to free their worst-case reservations (resume is the PR 4
    # byte-identical path), and schedules a recovery probe that restores
    # one shrink quantum per quiet shrink-recovery-s window. Repeated
    # shrinks inside one window escalate to DEGRADED health.
    shrink_fraction: float = 0.125
    shrink_recovery_s: float = 30.0
    # fault injection (serving/faults.py — TESTS AND CHAOS DRILLS ONLY):
    # declared FaultPlans arm the engine's device-touching seams to
    # raise synthetic RESOURCE_EXHAUSTED errors or stall a dispatch.
    # Empty (the default) leaves the hot path bit-for-bit unchanged —
    # every seam check is one attribute test against None. The
    # LS_TPU_FAULTS env var (JSON list of plans) arms a deployed pod.
    faults: tuple = ()
    # crash-requeue journal (serving/journal.py): a directory where every
    # accepted submission is journaled at admit and retired at
    # finish/shed/fail; a restarting engine replays the live entries
    # front-of-class, so an engine death no longer silently drops
    # accepted work. None (default) disables — hot path unchanged.
    journal_dir: str | None = None
    # incident capture plane (serving/incident.py): a directory where an
    # SLO/health breach snapshots a bounded evidence bundle (flight
    # summary + event tail, worst-K journeys, attribution, streaming
    # digests, config fingerprint) the moment the predicate trips.
    # None (default) disables — observe paths unchanged.
    incident_dir: str | None = None
    # suffixes longer than this skip the cache and take the full prefill.
    # The continuation path is memory-bounded (blocked online softmax), so
    # this is a kernel-efficiency trade, not an OOM guard: the full prefill
    # rides the Pallas flash kernel / sp ring, the continuation path is XLA
    # einsums — past the cap, recomputing the prefix on the faster kernel
    # beats skipping it on the slower one
    prefix_cache_max_suffix: int = 4096

    def to_dict(self) -> dict[str, Any]:
        """Kebab-case dict that :meth:`from_dict` round-trips — the lockstep
        handshake ships this so followers build the identical engine."""
        return {
            "model": self.model,
            "slots": self.slots,
            "max-seq-len": self.max_seq_len,
            "tokenizer": self.tokenizer,
            "checkpoint": self.checkpoint,
            "mesh": dict(self.mesh),
            "max-tokens": self.default_max_tokens,
            "seed": self.seed,
            "decode-chunk": self.decode_chunk,
            "decode-chunk-light": self.decode_chunk_light,
            "light-load-slots": self.light_load_slots,
            "warmup-on-start": self.warmup_on_start,
            "prefill-batch": self.prefill_batch,
            "quantize": self.quantize,
            "kv-quantize": self.kv_quantize,
            "kv-layout": self.kv_layout,
            "kv-block-size": self.kv_block_size,
            "kv-pool-fraction": self.kv_pool_fraction,
            "kv-pool-blocks": self.kv_pool_blocks,
            "paged-kernel": self.paged_kernel,
            "dense-kernel": self.dense_kernel,
            "prefix-cache": self.prefix_cache,
            "prefix-cache-max-suffix": self.prefix_cache_max_suffix,
            "prefix-store": (
                self.prefix_store.to_dict()
                if self.prefix_store is not None
                else None
            ),
            "adapter-store": (
                self.adapter_store.to_dict()
                if self.adapter_store is not None
                else None
            ),
            "prefill-chunk": self.prefill_chunk,
            "speculative-drafts": self.speculative_drafts,
            "model-dtype": self.model_dtype,
            "qos": self.qos.to_dict() if self.qos is not None else None,
            "pool-role": self.pool_role,
            "pipeline": self.pipeline,
            "wedge-window-s": self.wedge_window_s,
            "slo": self.slo.to_dict() if self.slo is not None else None,
            "streaming": self.streaming,
            "stream-stall-s": self.stream_stall_s,
            "shrink-fraction": self.shrink_fraction,
            "shrink-recovery-s": self.shrink_recovery_s,
            "faults": [p.to_dict() for p in self.faults],
            "journal-dir": self.journal_dir,
            "incident-dir": self.incident_dir,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ServingConfig":
        mesh = tuple((k, int(v)) for k, v in (d.get("mesh") or {}).items())
        return cls(
            model_dtype=d.get("model-dtype", d.get("model_dtype")),
            quantize=d.get("quantize"),
            kv_quantize=d.get("kv-quantize", d.get("kv_quantize")),
            model=d.get("model", "tiny"),
            slots=int(d.get("slots", 8)),
            max_seq_len=int(d.get("max-seq-len", d.get("max_seq_len", 512))),
            tokenizer=d.get("tokenizer"),
            checkpoint=d.get("checkpoint"),
            mesh=mesh,
            default_max_tokens=int(d.get("max-tokens", 128)),
            seed=int(d.get("seed", 0)),
            decode_chunk=int(d.get("decode-chunk", 16)),
            decode_chunk_light=int(
                d.get("decode-chunk-light", d.get("decode_chunk_light", 8))
            ),
            light_load_slots=(
                int(lls)
                if (lls := d.get("light-load-slots", d.get("light_load_slots")))
                is not None
                else None
            ),
            warmup_on_start=_parse_bool(
                d.get("warmup-on-start", d.get("warmup_on_start", False))
            ),
            prefill_batch=int(d.get("prefill-batch", 8)),
            kv_layout=d.get("kv-layout", d.get("kv_layout", "dense")),
            kv_block_size=int(d.get("kv-block-size", d.get("kv_block_size", 64))),
            kv_pool_fraction=float(
                d.get("kv-pool-fraction", d.get("kv_pool_fraction", 0.5))
            ),
            kv_pool_blocks=(
                int(d.get("kv-pool-blocks") or d.get("kv_pool_blocks"))
                if (d.get("kv-pool-blocks") or d.get("kv_pool_blocks"))
                else None
            ),
            paged_kernel=d.get("paged-kernel", d.get("paged_kernel", "auto")),
            dense_kernel=d.get("dense-kernel", d.get("dense_kernel", "auto")),
            prefix_cache=_parse_bool(
                d.get("prefix-cache", d.get("prefix_cache", True))
            ),
            prefix_cache_max_suffix=int(
                d.get(
                    "prefix-cache-max-suffix",
                    d.get("prefix_cache_max_suffix", 4096),
                )
            ),
            prefix_store=PrefixStoreSpec.from_dict(
                d.get("prefix-store", d.get("prefix_store"))
            ),
            adapter_store=AdapterStoreSpec.from_dict(
                d.get("adapter-store", d.get("adapter_store"))
            ),
            prefill_chunk=int(
                d.get("prefill-chunk", d.get("prefill_chunk", 0))
            ),
            speculative_drafts=int(
                d.get("speculative-drafts", d.get("speculative_drafts", 0))
            ),
            qos=QosSpec.from_dict(d.get("qos")),
            pool_role=str(
                d.get(
                    "pool-role",
                    d.get(
                        "pool_role",
                        os.environ.get("LS_POOL_ROLE") or "combined",
                    ),
                )
            ),
            pipeline=_parse_bool(d.get("pipeline", True)),
            wedge_window_s=float(
                d.get("wedge-window-s", d.get("wedge_window_s", 60.0))
            ),
            slo=SloSpec.from_dict(d.get("slo")),
            streaming=_parse_bool(d.get("streaming", False)),
            stream_stall_s=float(
                d.get("stream-stall-s", d.get("stream_stall_s", 2.0))
            ),
            shrink_fraction=float(
                d.get("shrink-fraction", d.get("shrink_fraction", 0.125))
            ),
            shrink_recovery_s=float(
                d.get("shrink-recovery-s", d.get("shrink_recovery_s", 30.0))
            ),
            faults=tuple(
                FaultPlan.from_dict(p) for p in (d.get("faults") or ())
            ),
            journal_dir=(
                d.get(
                    "journal-dir",
                    d.get(
                        "journal_dir",
                        os.environ.get("LS_TPU_JOURNAL_DIR") or None,
                    ),
                )
            ),
            incident_dir=(
                d.get(
                    "incident-dir",
                    d.get(
                        "incident_dir",
                        os.environ.get("LS_TPU_INCIDENT_DIR") or None,
                    ),
                )
            ),
        )


@dataclasses.dataclass
class _Slot:
    request: "_Request | None" = None
    # chunked prefill: tokens committed so far / mid-prefill flag (the slot
    # holds its reservation but is excluded from decode until done)
    prefilling: bool = False
    prefill_done: int = 0

    @property
    def free(self) -> bool:
        return self.request is None


@dataclasses.dataclass
class _Request:
    prompt_tokens: list[int]
    max_tokens: int
    temperature: float
    top_k: int
    top_p: float
    on_token: Callable[[int, float, bool], Awaitable[None] | None] | None
    future: asyncio.Future
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    generated: list[int] = dataclasses.field(default_factory=list)
    logprobs: list[float] = dataclasses.field(default_factory=list)
    loop: asyncio.AbstractEventLoop | None = None
    enqueue_time: float = 0.0
    # TTFT decomposition: enqueue → admit (queue wait) → first token
    # (prefill); the remainder to the client's first chunk is transport
    admit_time: float | None = None
    first_token_time: float | None = None
    # prompt-lookup speculation: bigram -> most recent first-element index,
    # maintained incrementally (amortized O(1)/token; a backward rescan per
    # verify step would be O(context) on the event-loop thread)
    bigram_index: dict = dataclasses.field(default_factory=dict)
    bigram_covered: int = 0
    # stop sequences (reference: ChatCompletionsConfig.stop): generation
    # halts when any string appears in the decoded output; the final text
    # is truncated at the match (the match itself excluded, OpenAI-style)
    stop: list = dataclasses.field(default_factory=list)
    stop_matched: bool = False
    # trace context captured at enqueue (the caller's ambient per-record
    # context): parents the engine.queue/prefill/decode spans
    trace: Any = None
    # warmup probes skip the latency histograms: their TTFT is XLA compile
    # time and Prometheus histograms are cumulative — one warmup wave would
    # poison the p99 forever (trace=None alone can't tell warmup apart
    # from an untraced real request)
    warmup: bool = False
    # QoS identity (serving/qos.py): the priority class drives WDRR
    # dequeue and preemption eligibility; the tenant keys the token
    # buckets. Both default to the unprivileged middle ground so a
    # QoS-off engine behaves exactly as before.
    tenant: str = ""
    priority: str = "default"
    # preemptive load shedding: times preempted so far (capped by
    # qos.max-preemptions) and, while requeued, when the preemption
    # happened (feeds the resume-latency histogram)
    preemptions: int = 0
    preempt_time: float | None = None
    # tiered prefix store (serving/prefixstore.py): True once admission
    # has stashed this request for a T2 hydration — it never stashes
    # twice, so a failed/timed-out hydration falls back to cold compute
    hydrate_attempted: bool = False
    # multi-LoRA adapter serving (serving/adapters.py): the adapter the
    # request named (gateway-stamped langstream-adapter header, "" =
    # base model), the device row its slot decodes against, whether a
    # T2 hydration stash already happened (one stash, then cold
    # refusal — unlike a prefix miss there is no recompute fallback),
    # and whether this request holds a pin on the adapter's row
    adapter: str = ""
    adapter_row: int = 0
    adapter_hydrate_attempted: bool = False
    adapter_pinned: bool = False
    # KV handoff (docs/DISAGG.md): True for a request admitted through
    # /kv/import on a decode-pool engine — its KV state arrived over the
    # wire, so admission skipped prefill entirely (request_timings carry
    # the marker the disagg e2e asserts on)
    imported: bool = False
    # request-journey ledger key (serving/journey.py): the trace id when
    # the request is traced, a fresh trace-id-shaped local id otherwise;
    # None for warmup probes (no journey). Rides the kvtransfer header
    # so the decode pool's edges land in the SAME journey.
    journey_id: "str | None" = None
    # decode-pool marker: the first NEW token emitted after a KV import
    # closes the decode-admission/first-step journey edge exactly once;
    # import_base_tokens pins how many generated tokens ARRIVED with the
    # handoff, so the edge fires on genuinely new work
    first_step_noted: bool = False
    import_base_tokens: int = 0
    # end-to-end deadline (serving/handoff.py, docs/RESILIENCE.md):
    # absolute WALL-CLOCK epoch seconds — the one clock every replica
    # on the request's path can compare against. None = no deadline,
    # every check one attribute test (the default-config pin).
    deadline: "float | None" = None
    # streaming chunk delivery (docs/OBSERVABILITY.md Streaming & TBT):
    # on_chunk(new_token_ids, new_text, is_final) fires once per decode
    # chunk at the _flush_emits safe point (sync or async). The sent
    # counters drive delta computation (chunks tile the final text
    # byte-exactly); stream_tbt is the bounded inter-emit digest (only
    # allocated on streaming-configured engines); stream_key is the
    # gateway's langstream-stream-id, the handle disconnect-cancellation
    # grabs.
    on_chunk: "Callable[[list, str, bool], Any] | None" = None
    stream_key: "str | None" = None
    stream_sent_tokens: int = 0
    stream_sent_chars: int = 0
    stream_first_emit: "float | None" = None
    stream_last_emit: "float | None" = None
    stream_emits: int = 0
    stream_stalls: int = 0
    stream_closed: bool = False
    stream_tbt: "TbtDigest | None" = None

    @property
    def context_tokens(self) -> list[int]:
        """Full model context: prompt plus everything generated so far.
        Equals ``prompt_tokens`` until a preemption; a resumed request
        re-prefills this to rebuild its KV state, so with greedy
        sampling the continuation is bit-identical to an unpreempted
        run (the generated tokens + per-request sampling params ARE the
        snapshot — greedy decode carries no other state)."""
        if not self.generated:
            return self.prompt_tokens
        return self.prompt_tokens + self.generated


def _deadline_from_options(options: dict) -> float | None:
    """The request's absolute epoch deadline out of its options:
    ``deadline`` (epoch seconds — the forwarded ``langstream-deadline``
    header) wins over ``deadline-s`` (caller-relative budget). Malformed
    values degrade to None — a garbage deadline must never refuse work
    the budget allows (the same posture as :func:`parse_deadline`)."""
    deadline = parse_deadline(options.get("deadline"))
    if deadline is not None:
        return deadline
    rel = options.get("deadline-s")
    if rel is None:
        return None
    try:
        rel = float(rel)
    except (TypeError, ValueError):
        return None
    # a non-positive relative budget means "expired on arrival" — the
    # admission check refuses it loudly rather than dropping the field
    return time.time() + max(0.0, rel)  # graftcheck: disable=OBS501 deadlines are wall-clock by design (cross-replica epoch stamps)


def _normalize_stop(value) -> list[str]:
    """One normalization for every stop-sequence consumer (engine + stream
    adapter): a string becomes a singleton list, falsy entries drop, and
    non-string truthy entries (e.g. ``stop: [42]`` from YAML) are coerced —
    they would otherwise raise TypeError mid-request on the per-token
    ``s in tail`` hot path."""
    if not value:
        return []
    if isinstance(value, str):
        value = [value]
    return [s if isinstance(s, str) else str(s) for s in value if s]


def _pow2(n: int) -> int:
    """Smallest power of two >= n (batch-row padding: compiling one jit
    variant per exact row count is a compile per new size)."""
    p = 1
    while p < n:
        p *= 2
    return p


def _bucket(n: int, lo: int = 32, hi: int = 32768) -> int:
    b = lo
    while b < n and b < hi:
        b *= 2
    return min(b, hi)  # hi may not be a power of two (user max_seq_len)


def _dev_cache_cap() -> int:
    try:
        return max(1, int(os.environ.get("LS_TPU_DEV_CACHE_CAP", "32")))
    except ValueError:
        return 32


class _DeviceLru:
    """Content-keyed device-upload cache with an LRU bound.

    The r5 single-entry caches saved the ~70 ms upload RPC only when two
    consecutive bursts shared the exact same content; multi-tenant traffic
    alternating between a few slot populations re-uploaded on every flip.
    Keeping the last N contents fixes the flip-flop — and the bound plus
    eviction counter (``engine.stats()["device-cache"]``) keeps a
    long-lived engine from pinning one device buffer per distinct block
    table it ever saw. One instance is touched from the engine loop, the
    other from the dispatch thread, and ``stats()``/``clear()`` run on
    whichever thread asks — so the OrderedDict bookkeeping (a multi-step
    read-modify-write, not a single GIL-atomic op) sits behind a plain
    ``threading.Lock``. The lock is uncontended in steady state and never
    held across I/O or device calls, so the OBS503 hot-path discipline
    holds; graftcheck RACE801 polices exactly this shape."""

    def __init__(self, cap: int | None = None):
        from collections import OrderedDict

        self.cap = cap if cap is not None else _dev_cache_cap()
        self._lock = threading.Lock()
        self._entries: Any = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_or_put(self, key: bytes, factory: Callable[[], Any]) -> Any:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                # graftcheck: disable=RACE801 device_bytes reads via a single C-level list() snapshot (the OBS505 lock-free reader contract above); the locked writes here never leave a torn view for it to observe
                self._entries.move_to_end(key)
                self.hits += 1
                return entry
            self.misses += 1
        # the factory (a device upload RPC) runs OUTSIDE the lock; a lost
        # race uploads twice, which is the pre-LRU behavior, not a bug
        entry = factory()
        with self._lock:
            self._entries[key] = entry
            while len(self._entries) > self.cap:
                self._entries.popitem(last=False)
                self.evictions += 1
        return entry

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def device_bytes(self) -> int:
        """Device bytes pinned by the cached entries — the memory
        ledger's ``device-lru``/``sampler-state`` owners. Lock-FREE by
        design (graftcheck OBS505): the attribution read path must never
        queue behind a dispatch holding the LRU lock, so the entries are
        snapshotted with a single C-level ``list()`` copy (the same
        reader contract the flight recorder uses) and summed with
        attribute reads only."""
        entries = list(self._entries.values())
        total = 0
        for entry in entries:
            total += tree_device_bytes(entry)
        return total

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "size": len(self._entries),
                "cap": self.cap,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


class TpuServingEngine:
    """One engine per (model, mesh) — shared across agents in the process.

    Public API:
      await engine.generate(prompt, options, on_token=...) -> GenerationResult
    """

    _instances: dict[Any, "TpuServingEngine"] = {}
    _instances_lock = threading.Lock()

    @classmethod
    def get_or_create(cls, config: ServingConfig) -> "TpuServingEngine":
        with cls._instances_lock:
            if config not in cls._instances:
                cls._instances[config] = cls(config)
            return cls._instances[config]

    @classmethod
    def reset_instances(cls) -> None:
        with cls._instances_lock:
            cls._instances.clear()

    def __init__(self, config: ServingConfig, lockstep_role: str | None = None):
        self.config = config
        self.model_config = _resolve_model_config(
            config.model, config.max_seq_len
        )
        if config.model_dtype is not None:
            dtypes = {
                "float32": jnp.float32, "f32": jnp.float32,
                "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
            }
            if config.model_dtype not in dtypes:
                raise ValueError(
                    f"unknown model_dtype {config.model_dtype!r}; "
                    f"known: {sorted(dtypes)}"
                )
            self.model_config = dataclasses.replace(
                self.model_config, dtype=dtypes[config.model_dtype]
            )
        self.is_moe = config.model in _MOE_MODELS
        self.tokenizer: Tokenizer = load_tokenizer(config.tokenizer)
        if self.tokenizer.vocab_size > self.model_config.vocab_size:
            raise ValueError(
                f"tokenizer vocab {self.tokenizer.vocab_size} exceeds model "
                f"vocab {self.model_config.vocab_size}"
            )

        self.mesh = None
        if config.mesh:
            from langstream_tpu.parallel.mesh import make_mesh

            self.mesh = make_mesh(dict(config.mesh))

        # multi-host slice: process 0 leads (broadcasts every dispatch over
        # the lockstep channel, serving/lockstep.py); followers are built by
        # LockstepFollower with lockstep_role="follower" and replay them.
        # Every process then issues identical jit calls — the requirement of
        # JAX multi-controller execution (SURVEY §7 hard part (c)).
        self._lockstep = None
        if (
            lockstep_role != "follower"
            and self.mesh is not None
            and jax.process_count() > 1
        ):
            import json as _json
            import os as _os

            from langstream_tpu.serving.lockstep import LockstepLeader

            port = int(_os.environ.get("LS_LOCKSTEP_PORT", "0")) or None
            self._lockstep = LockstepLeader(
                {"config_json": _json.dumps(config.to_dict())},
                expected_followers=jax.process_count() - 1,
                port=port,
                token=_os.environ.get("LS_LOCKSTEP_TOKEN", ""),
            )
            log.info(
                "lockstep leader on :%d awaiting %d followers",
                self._lockstep.port, jax.process_count() - 1,
            )
            self._lockstep.wait_ready()

        self._init_model()

        self.slots = [_Slot() for _ in range(config.slots)]
        # admission policy: FIFO by default; a qos spec swaps in the
        # priority/WDRR/token-bucket scheduler (serving/scheduler.py)
        self.scheduler = make_scheduler(config.qos)
        self._qos_enabled = config.qos is not None and config.qos.enabled
        self._wake = asyncio.Event()
        self._stop = False
        self._loop_task: asyncio.Task | None = None
        # one dedicated thread: JAX dispatch is serialised, asyncio stays live
        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="tpu-engine")
        self._key = jax.random.PRNGKey(config.seed)
        # decode-side state mirrors (host copies, device arrays built per step)
        self._lengths = np.zeros(config.slots, dtype=np.int32)
        self._current = np.zeros(config.slots, dtype=np.int32)
        self._temps = np.zeros(config.slots, dtype=np.float32)
        self._topks = np.zeros(config.slots, dtype=np.int32)
        self._topps = np.ones(config.slots, dtype=np.float32)
        self._pres = np.zeros(config.slots, dtype=np.float32)
        self._freq = np.zeros(config.slots, dtype=np.float32)
        self._pending_emits: list = []
        self._finished_requests: list = []
        # drain-before-terminate (docs/FLEET.md): once draining, new
        # submissions shed with a Retry-After while already-accepted work
        # is preempted-and-requeued at the loop's safe point and served
        # to completion — the pod /drain endpoint and the autoscaler's
        # scale-down path both land here
        self._draining = False
        self._drain_pass_done = False
        self._drain_requeued = 0
        self._drain_shed = 0
        self._drain_base_completed = 0
        self._drain_report: dict[str, Any] | None = None
        # disaggregated pools (docs/DISAGG.md): the handoff plane's
        # engine-side state. Exports are finished-prefill payloads keyed
        # by request id, awaiting pickup via /kv/export/{request}
        # (bounded: an abandoned handoff must not pin host memory
        # forever); imports queue here and are applied by the engine
        # loop at its safe point, exactly like admission. The in-transit
        # byte counter feeds the HBM ledger's `in-transit` owner so a
        # handoff's cost is never invisible.
        self._pool_role = config.pool_role
        self._exports: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        self._export_seq = 0
        self._export_cap = max(
            8, int(os.environ.get("LS_TPU_KV_EXPORT_CAP", "256") or 256)
        )
        self._pending_imports: deque = deque()
        self._kv_in_transit_bytes = 0
        self.kv_exports_total = 0
        self.kv_exports_evicted = 0
        self.kv_imports_total = 0
        self.kv_import_sheds = 0
        self.kv_export_bytes = 0
        self.kv_import_bytes = 0
        self.completed_requests = 0
        # per-request {queue_wait, prefill, ttft} seconds, newest last —
        # the gateway bench reads this to attribute client-measured TTFT
        self.request_timings: deque[dict[str, float]] = deque(maxlen=4096)
        self.total_generated = 0
        # Prometheus serving metrics (ride the pod's /metrics endpoint next
        # to the per-agent counters; labeled by model)
        reporter = PrometheusMetricsReporter(
            prefix="langstream_serving", agent_id=config.model
        )
        self._m_tokens = reporter.counter(
            "tokens_generated_total", "tokens generated by the engine"
        )
        self._m_requests = reporter.counter(
            "requests_completed_total", "completed generation requests"
        )
        self._m_ttft = reporter.gauge(
            "last_ttft_seconds", "time to first token of the last request"
        )
        # real distributions, not counter-of-sums: p50/p99 TTFT and queue
        # wait are what the gateway bench and dashboards quantile over.
        # Exemplar-capable: traced requests stamp their journey id on the
        # bucket they land in, so a p99 scrape names a journey
        # `tools/journey.py --trace` can open (untraced traffic records
        # exactly as before — the scrape stays byte-identical)
        self._m_ttft_hist = reporter.exemplar_histogram(
            "ttft_seconds", "engine time-to-first-token (enqueue to token 1)"
        )
        self._m_queue_wait_hist = reporter.histogram(
            "queue_wait_seconds", "enqueue to slot admission"
        )
        self._m_active = reporter.gauge(
            "slots_active", "decode slots currently generating"
        )
        self._m_queued = reporter.gauge(
            "queued_requests", "requests awaiting a free slot"
        )
        self._m_prefix_hits = reporter.counter(
            "prefix_cache_hits_total",
            "admissions that adopted cached prefix blocks",
        )
        self._m_prefix_tokens = reporter.counter(
            "prefix_cache_tokens_reused_total",
            "prompt tokens served from cached prefix blocks (prefill skipped)",
        )
        self._m_spec_steps = reporter.counter(
            "speculative_steps_total", "speculative verify steps run"
        )
        self._m_spec_accepted = reporter.counter(
            "speculative_drafts_accepted_total",
            "draft tokens accepted by verify steps (free extra tokens)",
        )
        self.spec_steps = 0
        self.spec_accepted = 0
        self.spec_rejected = 0
        # device-resident speculation state (PR 20): per-slot context token
        # rows on device (lazy — allocated at the first speculative burst)
        # and the host ledger of how many leading entries per row are
        # known-correct for the slot's CURRENT request. Plain-decode paths
        # never touch the ledger, so their slots read as stale and re-sync
        # at the next burst entry; slot release resets to 0.
        self._ctx_dev = None
        self._ctx_synced = np.zeros(config.slots, dtype=np.int64)
        # fetch/dispatch conservation counters: the one-host-fetch-per-
        # chunk acceptance rides on these (stats() exposes the ratio)
        self._decode_dispatches = 0
        self._decode_fetches = 0
        self._spec_dispatches = 0
        self._spec_fetches = 0
        # measured-uplift auto-disable: rolling (tokens, seconds) windows
        # for speculative steps and plain-decode chunks. Uplift = spec
        # tok/s over plain tok/s; < 1 over a full window flips speculation
        # off with a spec-auto-disable flight event. Plain samples come
        # from the periodic in-burst calibration chunk (wall-measured at
        # matched posture) and, while disabled, from ordinary decode
        # chunks — which also count toward the re-enable probe.
        _win = int(os.environ.get("LS_TPU_SPEC_UPLIFT_WINDOW", "32"))
        self._spec_window: deque = deque(maxlen=max(_win, 1))
        self._plain_window: deque = deque(maxlen=max(_win, 1))
        self._spec_cal_every = int(
            os.environ.get("LS_TPU_SPEC_CALIBRATE_EVERY", "32")
        )
        self._spec_retry_plain = int(
            os.environ.get("LS_TPU_SPEC_RETRY_CHUNKS", "256")
        )
        self._spec_steps_since_cal = 0
        self._spec_auto_disabled = False
        self._spec_plain_since_disable = 0
        self._spec_last_uplift: float | None = None
        self._spec_flips: list[tuple[float, str]] = []
        # host mirrors of the prefix-cache counters (flight samples carry
        # them; the metric closures above are write-only)
        self.prefix_hits = 0
        self.prefix_tokens = 0
        # adaptive-chunk observability: dispatches per regime
        self._light_chunks = 0
        self._heavy_chunks = 0
        # flight recorder: one sample per dispatched burst + stall gaps +
        # discrete events; served by the pod /flight endpoints and the
        # engine_top console (serving/flight.py)
        self.flight = FlightRecorder(slots=config.slots)
        # engine watchdog: heartbeat stamped at every flight boundary,
        # judged (wait-free) by probes/stats via health() — the layer that
        # turns a wedged device into a failed k8s liveness probe
        self.watchdog = EngineWatchdog(wedge_window_s=config.wedge_window_s)
        # SLO burn-rate tracker (None without a declared slo section):
        # completions/sheds/failures recorded on the engine loop, burn
        # rates surfaced via stats()/flight and the gauges below, `alert`
        # flight events on fast-burn transitions
        self.slo = SloTracker(config.slo) if config.slo is not None else None
        self._m_slo_burn: dict[str, Any] = {}
        self._m_slo_budget: dict[str, Any] = {}
        if self.slo is not None:
            for objective in config.slo.objectives:
                self._m_slo_burn[objective.name] = reporter.gauge(
                    f"slo_burn_rate_{objective.name}",
                    f"fast-window error-budget burn rate for the "
                    f"{objective.name} objective (1.0 = budget exhausts "
                    f"exactly at the window's end)",
                )
                self._m_slo_budget[objective.name] = reporter.gauge(
                    f"slo_budget_remaining_{objective.name}",
                    f"slow-window error budget remaining for the "
                    f"{objective.name} objective (1 - slow burn; negative "
                    f"= overspent)",
                )
        # streaming + TBT plane (docs/OBSERVABILITY.md Streaming & TBT):
        # empty/zero on non-streaming engines — the default Prometheus
        # scrape surface and flight-event set stay pinned bit for bit.
        # Per-class digests/histograms are created lazily on the first
        # finished stream of each class (classes are clamped to the QoS
        # vocabulary, so the maps stay bounded); the per-class burn
        # trackers exist only for classes declaring tbt-p99-s.
        self.stream_emits_total = 0
        self.stream_stalls_total = 0
        self.stream_cancels_total = 0
        self.stream_reclaims_total = 0
        self._stream_tbt_by_class: dict[str, TbtDigest] = {}
        self._m_tbt_hist: dict[str, Any] = {}
        self._stream_slo: dict[str, SloTracker] = {}
        if config.streaming and config.qos is not None:
            for policy in config.qos.classes:
                if policy.tbt_p99_s is None:
                    continue
                # one single-objective tracker per declaring class: the
                # same multi-window burn machinery TTFT uses, windowed
                # like the engine's own slo section when one is declared
                self._stream_slo[policy.name] = SloTracker(
                    SloSpec(
                        objectives=(
                            SloObjective(
                                "tbt", 0.99, policy.tbt_p99_s * 1000.0
                            ),
                        ),
                        fast_window_s=(
                            config.slo.fast_window_s
                            if config.slo is not None
                            else 300.0
                        ),
                        slow_window_s=(
                            config.slo.slow_window_s
                            if config.slo is not None
                            else 3600.0
                        ),
                        fast_burn=(
                            config.slo.fast_burn
                            if config.slo is not None
                            else 14.4
                        ),
                    )
                )
        # shapes already compiled (jit-variant keys AND prefill bucket/row
        # shapes): a miss here is a fresh XLA compile — tens of seconds on
        # TPU, the event every recompile-storm diagnosis starts from
        self._compiled_shapes: set = set()
        self._m_step_hist = {
            "decode": reporter.histogram(
                "decode_step_seconds", "wall time per dispatched decode chunk"
            ),
            "prefill": reporter.histogram(
                "prefill_step_seconds", "wall time per dispatched prefill batch"
            ),
            "verify": reporter.histogram(
                "verify_step_seconds", "wall time per speculative verify step"
            ),
        }
        self._m_host_overhead = reporter.histogram(
            "host_overhead_seconds",
            "host-side share of each dispatched burst (wall - device wait)",
        )
        self._m_kv_used = reporter.gauge(
            "kv_pool_used_ratio",
            "paged KV block-pool RESERVED fraction (0-1): the admission "
            "pressure that produces no-kv-blocks, not physical fullness",
        )
        self._m_stall = {
            reason: reporter.counter(
                f"admission_stall_{reason.replace('-', '_')}_seconds_total",
                f"seconds admission could not proceed: {reason} (accrues "
                f"while the engine is busy decoding too — queue pressure, "
                f"not engine idleness; the flight rollup's stall_ms is "
                f"the idle component)",
            )
            for reason in (
                "no-free-slot", "no-kv-blocks", "prefill-in-flight",
                "queue-empty",
            )
        }
        self._m_spec_rejected = reporter.counter(
            "speculative_drafts_rejected_total",
            "draft tokens rejected by verify steps",
        )
        self._m_spec_ratio = reporter.gauge(
            "speculative_accept_ratio",
            "accepted / drafted ratio over the engine's life",
        )
        self._m_spec_uplift = reporter.gauge(
            "speculative_uplift",
            "rolling measured speculative-vs-plain tokens/s ratio (the "
            "auto-disable verdict input; 0 until the first full window)",
        )
        self._m_recompiles = reporter.counter(
            "recompiles_total",
            "jit program variants/shapes compiled (bucket or sampler-mode "
            "misses; each is a potential mid-traffic convoy)",
        )
        # QoS observability (created only with a qos spec so a FIFO
        # engine's /metrics surface is unchanged): per-class queue-depth
        # gauges, shed/preempt counters, preemption/resume histograms
        self._m_class_depth: dict[str, Any] = {}
        self._m_shed = None
        self._m_preempted = None
        self._m_resume_hist = None
        self._m_preempt_hist = None
        if self._qos_enabled:
            self._m_class_depth = {
                cls: reporter.gauge(
                    f"qos_queue_depth_{cls}",
                    f"requests queued in the {cls} priority class",
                )
                for cls in PRIORITY_CLASSES
            }
            self._m_shed = reporter.counter(
                "qos_shed_total",
                "requests refused by QoS policy (tenant throttle or a "
                "full class queue)",
            )
            self._m_preempted = reporter.counter(
                "qos_preempted_total",
                "running requests preempted under KV pressure (snapshot + "
                "requeue for transparent resume)",
            )
            self._m_resume_hist = reporter.histogram(
                "qos_resume_seconds",
                "preemption → re-admission wall time (how long preempted "
                "work waited to resume)",
            )
            self._m_preempt_hist = reporter.histogram(
                "qos_preempted_run_seconds",
                "how long a victim had been running when preempted (the "
                "decode progress the preemption put at risk)",
            )
        # KV handoff observability (split-pool engines only, so a
        # combined engine's /metrics surface stays unchanged): transfer
        # time histograms + byte/count totals — the handoff cost must
        # never be invisible (docs/DISAGG.md)
        self._m_kv_export_hist = None
        self._m_kv_import_hist = None
        self._m_kv_export_bytes = None
        self._m_kv_import_bytes = None
        # cross-replica failure domain (serving/handoff.py,
        # docs/RESILIENCE.md "Distributed failure domain"): handoff
        # re-offer/fallback counters fed by the chainer, deadline
        # shed/overrun counters fed by the admission and finish paths.
        # The Prometheus spellings register below for split-pool engines
        # only (retry/fallback) or lazily on first use (deadline) — a
        # combined-pool, deadline-less engine keeps the exact
        # pre-existing scrape surface (the default-config pin).
        self.handoff_retries = 0
        self.handoff_fallbacks = 0
        self.deadline_sheds = 0
        self.deadline_overruns = 0
        # exported-but-unsettled handoffs: request id -> journal id. An
        # entry retires only when the chainer confirms the decode side
        # ANSWERED (completion or terminal refusal) — a decode pod that
        # dies mid-handoff leaves the entry live, so a restart replays
        # the request as fresh work instead of losing it invisibly.
        self._handoff_journal: "OrderedDict[str, str]" = OrderedDict()
        self._reporter = reporter
        self._m_handoff_retries = None
        self._m_handoff_fallbacks = None
        self._m_deadline_shed = None
        self._m_breaker_open = None
        if self._pool_role != "combined":
            self._m_handoff_retries = reporter.counter(
                "handoff_retries_total",
                "KV handoff offers re-routed to another decode replica "
                "after a timeout/refusal/shed (serving/handoff.py)",
            )
            self._m_handoff_fallbacks = reporter.counter(
                "handoff_fallbacks_total",
                "KV handoffs decoded LOCALLY after the re-offer cap "
                "(every decode replica dead, held, or refusing)",
            )
            self._m_kv_export_hist = reporter.exemplar_histogram(
                "kv_export_seconds",
                "device gather + serialization wall time per KV handoff "
                "export (prefill pool)",
            )
            self._m_kv_import_hist = reporter.exemplar_histogram(
                "kv_import_seconds",
                "block allocation + device scatter wall time per KV "
                "handoff import (decode pool)",
            )
            self._m_kv_export_bytes = reporter.counter(
                "kv_export_bytes_total",
                "serialized KV handoff bytes exported to decode replicas",
            )
            self._m_kv_import_bytes = reporter.counter(
                "kv_import_bytes_total",
                "serialized KV handoff bytes imported from prefill replicas",
            )
        self._warmup_task: asyncio.Task | None = None
        # device-side upload caches (content-keyed, LRU-bounded): block
        # tables and the sampler/active-mask tuple change rarely between
        # chunks, and each re-upload is a synchronous ~70ms RPC over a
        # tunneled chip
        self._tables_dev_cache = _DeviceLru()
        self._sampler_dev_cache = _DeviceLru()
        # pipelined engine loop (docs/PIPELINE.md): config + env escape
        # hatch; LS_TPU_PIPELINE=0 forces the sequential reference loop
        self._pipeline_on = config.pipeline and (
            os.environ.get("LS_TPU_PIPELINE", "1") != "0"
        )
        # a dispatched-but-unprocessed decode chunk carried across the
        # burst boundary so admission prefills dispatch under its device
        # shadow: (out, active slot ids, request identities at capture, K)
        self._pending_chunk: tuple | None = None
        # inside a pipelined burst, finished slots' block releases are
        # DEFERRED to burst exit: an in-flight chunk still commits via the
        # tables captured at its dispatch, and a mid-burst re-allocation
        # of those blocks to a live slot would let the stale commit land
        # on top of live K/V (the post-burst prefill overwrite that makes
        # immediate release safe between bursts does not exist mid-burst)
        self._defer_release = False
        self._deferred_releases: list[int] = []
        # jax.profiler trace + HLO dump hooks (env-gated, off by default)
        self.profiler = ProfilerHooks()
        # device attribution plane (serving/attribution.py): the per-
        # program cost ledger fed from the loop's flight records, plus
        # the static facts the HBM memory ledger needs. Weight/cache
        # byte totals are computed ONCE here — the cache handles are
        # donated and rebound on the dispatch thread, so readers must
        # never walk the live arrays (their shapes are fixed for the
        # engine's life anyway).
        mc = self.model_config
        self.attribution = ProgramLedger()
        self._weights_bytes = tree_device_bytes(self.params)
        self._kv_cache_bytes = tree_device_bytes(
            self.cache_k
        ) + tree_device_bytes(self.cache_v)
        self._kv_block_bytes = (
            self._kv_cache_bytes // self.paged_layout.num_blocks
            if self.block_mgr is not None
            else 0
        )
        act_bytes = np.dtype(mc.dtype).itemsize
        if self.is_moe:
            # routed experts: the host can't know which experts fire, so
            # the FLOPs term estimates params from the measured bytes —
            # divided by the ACTUAL weight width (int8 → 1, else the
            # model dtype's itemsize, so model_dtype=float32 doesn't
            # double the estimate)
            n_params = self._weights_bytes // (
                1 if self.config.quantize == "int8" else act_bytes
            )
        else:
            from langstream_tpu.models.llama import param_count

            n_params = param_count(mc)
        if self.config.kv_quantize == "int8":
            kv_row_bytes = mc.head_dim + 4  # int8 row + f32 scale
        else:
            kv_row_bytes = mc.head_dim * act_bytes
        self._prog_shape = ModelShape(
            layers=mc.layers,
            hidden=mc.hidden,
            heads=mc.heads,
            kv_heads=mc.kv_heads,
            head_dim=mc.head_dim,
            intermediate=getattr(
                mc, "intermediate", getattr(mc, "moe_intermediate", 0)
            ),
            vocab=mc.vocab_size,
            weight_bytes=self._weights_bytes,
            param_count=n_params,
            kv_row_bytes=kv_row_bytes,
            act_bytes=act_bytes,
        )
        # device identity is fixed for the engine's life: capacity
        # (allocator truth or the per-generation table) and bandwidth
        # resolve once, never on the attribution read path
        self._hbm_limit, self._hbm_limit_source = detect_hbm_capacity()
        self._hbm_gbps = detect_hbm_gbps()
        self._hbm_generation = detect_generation()
        # hbm_bytes_by_owner Prometheus mirrors (refreshed whenever the
        # attribution section is computed: stats(), /attribution, /memory)
        self._m_hbm_owner = {
            owner: reporter.gauge(
                f"hbm_bytes_{owner.replace('-', '_')}",
                f"resident HBM bytes attributed to {owner} "
                f"(serving/attribution.py memory ledger; slack = detected "
                f"limit minus every accounted owner)",
            )
            for owner in (
                "weights", "kv-pool", "sampler-state", "device-lru",
                "in-transit", "slack",
            )
        }
        # tiered prefix store (serving/prefixstore.py, docs/PREFIX.md):
        # T1 host-RAM spill + T2 object storage under the T0 prefix
        # cache. Constructed only for a paged engine with the cache on
        # (validated above); requests stalled on a T2 hydration are
        # stashed OFF the scheduler so they never head-block admission.
        self.prefix_store: PrefixStore | None = None
        self._prefix_hydrating: list = []  # (request, deadline_m, digests)
        self.prefix_t0_evictions = 0
        self._m_prefix_tier: dict[str, Any] = {}
        if (
            config.prefix_store is not None
            and config.prefix_store.enabled
            and self.block_mgr is not None
            and config.prefix_cache
        ):
            self.prefix_store = PrefixStore(
                config.prefix_store,
                fingerprint=self.kv_fingerprint(),
                block_bytes=self._kv_block_bytes,
                rows_per_block=self.paged_layout.block_size,
            )
            # pool-pressure evictions bypass demotion: record the loss
            self.block_mgr.on_prefix_evict = self._note_prefix_pool_evict
            self._m_prefix_tier = {
                "t0_bytes": reporter.gauge(
                    "prefix_tier_t0_bytes",
                    "HBM bytes held by cached prefix blocks (the paged "
                    "pool's prefix sub-owner; budget = prefix-store "
                    "t0-bytes)",
                ),
                "t1_bytes": reporter.gauge(
                    "prefix_tier_t1_bytes",
                    "host-RAM bytes held by T1 spilled prefix blocks",
                ),
                "t2_bytes": reporter.gauge(
                    "prefix_tier_t2_bytes",
                    "object-storage payload bytes indexed in T2",
                ),
                "t1_hits": reporter.counter(
                    "prefix_t1_promotions_total",
                    "prefix blocks promoted T1→T0 at admission",
                ),
                "t2_hits": reporter.counter(
                    "prefix_t2_hydrations_total",
                    "prefix blocks hydrated T2→T1 for an admission",
                ),
                "demotions": reporter.counter(
                    "prefix_demotions_total",
                    "prefix blocks demoted down-tier (T0→T1 and T1→T2)",
                ),
                "evictions": reporter.counter(
                    "prefix_evictions_total",
                    "prefix blocks evicted from any tier (bytes left the "
                    "store — counted, never silent)",
                ),
            }
        # tiered multi-LoRA adapter store (serving/adapters.py,
        # docs/ADAPTERS.md): device-resident stacked A/B rows (T0) over
        # host-RAM spill (T1) and an object-storage origin (T2). Same
        # off-scheduler hydration stash discipline as the prefix store;
        # requests stalled on a cold adapter never head-block admission.
        # Disabled (the default) the engine is byte-identical to seed:
        # no store, no gauges, no stats section, no extra jit kwargs.
        self.adapter_store: AdapterStore | None = None
        self._adapter_hydrating: list = []  # (request, deadline_m, name)
        self.adapter_refusals = 0  # cold refusals (unknown or timed out)
        self._m_adapters: dict[str, Any] = {}
        if config.adapter_store is not None and config.adapter_store.enabled:
            self.adapter_store = AdapterStore(
                config.adapter_store,
                fingerprint=self.adapter_fingerprint(),
                entry_bytes=self._adapter_entry_bytes(),
            )
            self._m_adapters = {
                "t0_bytes": reporter.gauge(
                    "adapter_tier_t0_bytes",
                    "HBM bytes held by device-resident LoRA adapter rows "
                    "(budget = adapter-store t0-entries x entry bytes)",
                ),
                "t1_bytes": reporter.gauge(
                    "adapter_tier_t1_bytes",
                    "host-RAM bytes held by T1 spilled LoRA adapters",
                ),
                "t2_bytes": reporter.gauge(
                    "adapter_tier_t2_bytes",
                    "object-storage payload bytes indexed in adapter T2",
                ),
                "loads": reporter.counter(
                    "adapter_loads_total",
                    "LoRA adapter rows loaded into the device buffers "
                    "(T1→T0 promotions)",
                ),
                "hydrations": reporter.counter(
                    "adapter_hydrations_total",
                    "LoRA adapters hydrated T2→T1 for an admission",
                ),
                "demotions": reporter.counter(
                    "adapter_demotions_total",
                    "LoRA adapters demoted T1→T2 under host-RAM pressure",
                ),
                "evictions": reporter.counter(
                    "adapter_evictions_total",
                    "LoRA adapters evicted from any tier (bytes left the "
                    "store — counted, never silent)",
                ),
            }
        # device-survival plane (docs/RESILIENCE.md): fault injection,
        # adaptive pool-shrink, crash-requeue journal. Default config
        # keeps the hot path bit-for-bit: _faults is None (every seam
        # check is one attribute test), the journal is None, and the
        # recovery probe's loop check is one None test per pass.
        if not 0.0 < config.shrink_fraction <= 1.0:
            raise ValueError("shrink_fraction must be in (0, 1]")
        if config.shrink_recovery_s <= 0:
            raise ValueError("shrink_recovery_s must be > 0")
        plans = tuple(config.faults) or plans_from_env()
        self._faults = FaultInjector(plans) if plans else None
        if self.prefix_store is not None and self._faults is not None:
            # the t2-get network seam (serving/faults.py): the hydrator
            # consults the SAME injector the device seams use, so one
            # chaos plan scripts both failure domains
            self.prefix_store._fault_injector = self._faults
        if self.adapter_store is not None and self._faults is not None:
            # the adapter hydrator shares the t2-get seam too — one plan
            # scripts prefix AND adapter origin fetches
            self.adapter_store._fault_injector = self._faults
        # fired faults hand off loop-ward through a deque: the seams
        # span both thread roles, the flight ring's emission is loop-side
        self._fault_fired: deque = deque()
        self.pool_shrinks = 0
        self.pool_restores = 0
        self.shrink_preempted = 0
        self._shrink_recover_at: float | None = None
        # preempts/sheds performed INLINE at a catch site (the chunked-
        # prefill grow handler) before the loop-level shrink pass runs:
        # the pass folds them into its evidence and its did-we-adapt
        # verdict — a tiny pool whose budget is already at its floor
        # must still count an inline requeue as adaptation, not fall
        # through to failing every in-flight request
        self._shrink_inline_preempted = 0
        self._shrink_inline_shed = 0
        self._m_shrinks = None
        self._m_restores = None
        self._m_budget = None
        if self.block_mgr is not None:
            self._m_shrinks = reporter.counter(
                "pool_shrinks_total",
                "adaptive KV-budget shrinks after a device allocator "
                "failure (degrade-don't-die: evidence rides the "
                "pool-shrink flight events)",
            )
            self._m_restores = reporter.counter(
                "pool_restores_total",
                "shrink quanta restored by the recovery probe after a "
                "quiet window",
            )
            self._m_budget = reporter.gauge(
                "kv_budget_blocks",
                "live paged-KV admission budget in blocks (configured "
                "pool minus blocks withheld by adaptive shrink)",
            )
            self._m_budget(self.block_mgr.usable_blocks)
        self.journal: RequestJournal | None = None
        self._m_journal_depth = None
        if config.journal_dir:
            self.journal = RequestJournal(
                config.journal_dir,
                on_evict=lambda rid: self.flight.event(
                    "journal-evict", request=rid
                ),
                # identity stamp: entries journaled under a different
                # model/tokenizer are refused at replay — their token
                # ids mean nothing to this engine (the dir itself is
                # engine-private by contract)
                fingerprint={
                    "model": config.model,
                    "tokenizer": config.tokenizer or "byte",
                },
            )
            self._m_journal_depth = reporter.gauge(
                "journal_depth",
                "admitted-but-unfinished requests in the crash-requeue "
                "journal",
            )
            self._journal_replay_pending = self.journal.pending()
        else:
            self._journal_replay_pending = []
        # incident capture plane (serving/incident.py): breach-triggered
        # evidence bundles. None (the default) keeps every observe path
        # one attribute test against None — byte-identical to pre-plane.
        self.incidents: IncidentRecorder | None = None
        if config.incident_dir:
            self.incidents = IncidentRecorder(
                config.incident_dir,
                on_evict=lambda bid: self.flight.event(
                    "incident-evict", bundle=bid
                ),
            )

    # ------------------------------------------------------------------
    # model + jit setup
    # ------------------------------------------------------------------

    def _init_model(self) -> None:
        mc = self.model_config
        self._ffn = None  # default dense SwiGLU inside the llama layer math
        # random-init + int8 postures generate the quantized tree DIRECTLY
        # (init_llama_params_q8): the init→quantize sequence peaks at the
        # full-precision tree PLUS the int8 copy (>= 24 GB at the 8B shape
        # — certain OOM on a 16 GB chip, round-4 bench root cause)
        quantized_at_init = False
        if self.is_moe:
            from langstream_tpu.models.moe import init_moe_params, moe_serving_ffn

            ep_constrain = None
            if self.mesh is not None and "ep" in self.mesh.axis_names:
                # pin expert-major (E, C, H) intermediates to the ep axis so
                # GSPMD resolves the flanking einsums as token all-to-alls
                # over ICI instead of all-gathering the expert weights
                # (mirrors moe_forward_sharded's training-side constraints)
                from jax.sharding import NamedSharding, PartitionSpec as P

                e_spec = NamedSharding(self.mesh, P("ep", None, None))
                ep_constrain = lambda t: jax.lax.with_sharding_constraint(  # noqa: E731
                    t, e_spec
                )
            self._ffn = moe_serving_ffn(mc, ep_constrain=ep_constrain)
            if self.config.checkpoint:
                from langstream_tpu.models.checkpoints import load_moe_checkpoint

                self.params = load_moe_checkpoint(self.config.checkpoint, mc)
            else:
                log.warning(
                    "model %r: using random-init weights (offline/dev mode)",
                    self.config.model,
                )
                if self.config.quantize == "int8":
                    from langstream_tpu.models.quant import init_moe_params_q8

                    self.params = init_moe_params_q8(mc)
                    quantized_at_init = True
                else:
                    self.params = init_moe_params(mc)
        elif self.config.checkpoint:
            from langstream_tpu.models.checkpoints import load_llama_checkpoint

            self.params = load_llama_checkpoint(self.config.checkpoint, mc)
        else:
            log.warning(
                "no checkpoint configured for model %r: using random-init "
                "weights (offline/dev mode)", self.config.model,
            )
            if self.config.quantize == "int8":
                from langstream_tpu.models.quant import init_llama_params_q8

                self.params = init_llama_params_q8(mc)
                quantized_at_init = True
            else:
                self.params = init_llama_params(mc)
        if self.config.quantize == "int8":
            if not quantized_at_init:  # checkpoint / bf16-random-init trees
                from langstream_tpu.models.quant import (
                    quantize_llama_params,
                    quantize_moe_params,
                )

                quantize = (
                    quantize_moe_params if self.is_moe else quantize_llama_params
                )
                self.params = quantize(self.params)
        elif self.config.quantize not in (None, "none"):
            raise ValueError(f"unknown quantize mode {self.config.quantize!r}")

        if self.config.kv_quantize not in (None, "none", "int8"):
            raise ValueError(
                f"unknown kv_quantize mode {self.config.kv_quantize!r}"
            )
        if self.config.pool_role not in ("combined", "prefill", "decode"):
            raise ValueError(
                f"unknown pool_role {self.config.pool_role!r}; known: "
                f"combined, prefill, decode"
            )
        if (
            self.config.pool_role != "combined"
            and self.config.kv_layout != "paged"
        ):
            raise ValueError(
                "pool-role prefill/decode requires kv-layout=paged (the "
                "KV handoff plane serializes paged blocks; a dense cache "
                "has no block tables to hand off)"
            )
        if self.config.prefix_store is not None and self.config.prefix_store.enabled:
            if self.config.kv_layout != "paged":
                raise ValueError(
                    "prefix-store requires kv-layout=paged (the tiers "
                    "demote/promote content-addressed pool blocks; a dense "
                    "cache has none)"
                )
            if not self.config.prefix_cache:
                raise ValueError(
                    "prefix-store requires prefix-cache=true (T0 IS the "
                    "automatic prefix cache; without it there is nothing "
                    "to demote or promote)"
                )
        if (
            self.config.adapter_store is not None
            and self.config.adapter_store.enabled
        ):
            if self.config.kv_layout != "paged":
                raise ValueError(
                    "adapter-store requires kv-layout=paged (batched "
                    "ragged adapter application rides the paged "
                    "decode/prefill programs)"
                )
            if jax.process_count() > 1:
                raise ValueError(
                    "adapter-store is incompatible with multi-host "
                    "lockstep (followers replay positional dispatch "
                    "descriptors that carry no adapter rows)"
                )
        if self.config.prefill_chunk > 0 and self.config.kv_layout != "paged":
            raise ValueError(
                "prefill-chunk requires kv-layout=paged (chunked prefill "
                "commits through the paged continuation path)"
            )
        if (
            self.config.speculative_drafts > 0
            and self.config.kv_layout != "paged"
        ):
            raise ValueError(
                "speculative-drafts requires kv-layout=paged (the verify "
                "step commits through the paged continuation path)"
            )
        if (
            self.config.speculative_drafts > 0
            and self.config.kv_quantize == "int8"
        ):
            # speculation's "never changes content" guarantee is weaker
            # here: verify quantizes KV at different commit boundaries than
            # the non-speculative path, so greedy streams may diverge
            # bit-for-bit from speculation-off runs (documented at the
            # model level, llama_paged.py) — surface it where the config is
            # chosen, once per engine
            log.info(
                "speculative-drafts with kv-quantize=int8: greedy streams "
                "may diverge from non-speculative runs (int8 KV commit-"
                "boundary quantization differs under the verify path)"
            )
        self.block_mgr = None
        if self.config.kv_layout == "paged":
            from langstream_tpu.models.paged import (
                BlockManager,
                PagedLayout,
                init_paged_kv_cache,
            )

            self.paged_layout = PagedLayout.for_model(
                mc.max_seq_len,
                self.config.slots,
                block_size=self.config.kv_block_size,
                hbm_fraction_of_dense=self.config.kv_pool_fraction,
                num_blocks=self.config.kv_pool_blocks,
            )
            self.block_mgr = BlockManager(self.paged_layout, self.config.slots)
            if self.config.kv_quantize == "int8":
                from langstream_tpu.models.paged import init_paged_kv_cache_int8

                cache_k, cache_v = init_paged_kv_cache_int8(
                    mc, self.paged_layout
                )
            else:
                cache_k, cache_v = init_paged_kv_cache(mc, self.paged_layout)
            kernel = self.config.paged_kernel
            if kernel == "auto":
                # the Pallas kernel is the TPU fast path for bf16 pools;
                # under a mesh it runs per-shard via shard_map (slots on
                # dp, heads on tp). int8 pools DEFAULT to the fused XLA
                # gather: the in-kernel dequant twin exists
                # (ops/paged_attention._paged_kernel_q8, equivalence-
                # tested) but chip-measured SLOWER than the gather at the
                # headline shape (62 vs 42 ms/step — Mosaic needs batch-
                # leading dot layouts, and the per-block k/v transposes
                # cost more than the densify they avoid); opt in with
                # paged_kernel=pallas.
                kernel = (
                    "pallas"
                    if jax.default_backend() == "tpu"
                    and self.config.kv_quantize != "int8"
                    else "xla"
                )
            self.paged_read_kernel = kernel
        elif self.config.kv_layout != "dense":
            raise ValueError(f"unknown kv_layout {self.config.kv_layout!r}")
        else:
            if self.config.kv_quantize == "int8":
                from langstream_tpu.models.kvquant import init_kv_cache_int8

                cache_k, cache_v = init_kv_cache_int8(mc, self.config.slots)
            else:
                cache_k, cache_v = init_kv_cache(mc, self.config.slots)
            kernel = self.config.dense_kernel
            if kernel == "auto":
                # the paged Pallas read kernel doubles as the dense fast
                # path (identity block tables); meshes keep the XLA einsum,
                # and so does the int8 cache (the scale-folded einsum read
                # IS the fused fast path — the Pallas kernel is bf16-only)
                kernel = (
                    "pallas"
                    if self.mesh is None
                    and jax.default_backend() == "tpu"
                    and mc.max_seq_len % 128 == 0
                    and self.config.kv_quantize != "int8"
                    else "xla"
                )
            elif kernel != "xla":
                # forced kernels fail fast at construction, not inside a
                # jitted trace at first decode
                if self.config.kv_quantize == "int8":
                    raise ValueError(
                        "dense_kernel=pallas reads a bf16 cache; with "
                        "kv-quantize=int8 keep dense_kernel=xla"
                    )
                if self.mesh is not None:
                    raise ValueError(
                        "dense_kernel=pallas runs per-device; under a mesh "
                        "keep dense_kernel=xla (the paged layout has the "
                        "shard_map'd kernel)"
                    )
                if mc.max_seq_len % 128 != 0:
                    raise ValueError(
                        f"dense_kernel=pallas needs max_seq_len divisible by "
                        f"128, got {mc.max_seq_len}"
                    )
            self.dense_read_kernel = kernel

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from langstream_tpu.models.quant import quantize_specs
            from langstream_tpu.parallel.mesh import put_global

            if self.is_moe:
                from langstream_tpu.models.moe import moe_param_specs

                base_specs = moe_param_specs(mc)
            else:
                base_specs = llama_param_specs(mc)
            # ONLY the optional "ep" axis is forgiven when absent (an MoE
            # engine on a pure-tp mesh keeps experts replicated — a
            # legitimate, if memory-hungry, layout). Any other missing spec
            # axis is a misconfigured mesh and must fail loudly, not
            # silently replicate the weights.
            axes = set(self.mesh.axis_names)

            def _present(entry):
                if entry is None:
                    return None
                names = entry if isinstance(entry, tuple) else (entry,)
                missing = [a for a in names if a not in axes]
                for a in missing:
                    if a != "ep":
                        raise ValueError(
                            f"model {self.config.model!r} shards over mesh "
                            f"axis {a!r} but the configured mesh has axes "
                            f"{sorted(axes)}; add {a!r} to the mesh"
                        )
                    log.warning(
                        "mesh has no 'ep' axis: expert weights will be "
                        "replicated on every device"
                    )
                kept = tuple(a for a in names if a in axes)
                if isinstance(entry, tuple):
                    return kept or None
                return kept[0] if kept else None

            base_specs = jax.tree.map(
                lambda p: P(*(_present(e) for e in p)) if isinstance(p, P) else p,
                base_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            specs = quantize_specs(base_specs, self.params)
            self.params = jax.tree.map(
                lambda p, s: put_global(p, NamedSharding(self.mesh, s)),
                self.params,
                specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            if self.block_mgr is not None:
                from langstream_tpu.models.paged import paged_cache_spec

                cspec = NamedSharding(
                    self.mesh, paged_cache_spec(self.mesh.axis_names)
                )
                if isinstance(cache_k, dict):
                    # the same (..., tp) spec fits both leaves: data ends in
                    # the fused Kh*D axis, scales in Kh — both shard on tp
                    place = lambda cache: jax.tree.map(
                        lambda a: put_global(a, cspec), cache
                    )
                    cache_k, cache_v = place(cache_k), place(cache_v)
                else:
                    cache_k = put_global(cache_k, cspec)
                    cache_v = put_global(cache_v, cspec)
            else:
                spec = kv_cache_spec(self.mesh.axis_names)
                if isinstance(cache_k, dict):
                    # int8 cache pytree: data (L,B,S,K,D) takes the full
                    # spec, scales (L,B,S,K) the same minus the head_dim axis
                    sharding = {
                        "q": NamedSharding(self.mesh, spec),
                        "s": NamedSharding(self.mesh, P(*spec[:4])),
                    }
                    cache_k = jax.tree.map(put_global, cache_k, sharding)
                    cache_v = jax.tree.map(put_global, cache_v, sharding)
                else:
                    cspec = NamedSharding(self.mesh, spec)
                    cache_k = put_global(cache_k, cspec)
                    cache_v = put_global(cache_v, cspec)
        self.cache_k, self.cache_v = cache_k, cache_v

        # stacked device LoRA buffers (docs/ADAPTERS.md): row 0 is the
        # permanent zero adapter (adapter-less slots gather zeros, so one
        # jitted program serves heterogeneous-adapter batches), rows
        # 1..t0_entries back the AdapterStore's T0 tier. The buffers are
        # NOT donated — loads rebuild one row functionally (`.at[:, row]
        # .set`) on the dispatch thread, so an in-flight dispatch keeps
        # its snapshot. `_ad_rows` is the loop-side slot→row mirror.
        self._ad_layers: dict[str, Any] | None = None
        self._ad_rows: np.ndarray | None = None
        spec_ad = self.config.adapter_store
        if spec_ad is not None and spec_ad.enabled:
            n_rows = spec_ad.t0_entries + 1
            r = spec_ad.rank
            q_dim = mc.heads * mc.head_dim
            kv_dim = mc.kv_heads * mc.head_dim
            shapes = {
                "wq_a": (mc.layers, n_rows, mc.hidden, r),
                "wq_b": (mc.layers, n_rows, r, q_dim),
                "wk_a": (mc.layers, n_rows, mc.hidden, r),
                "wk_b": (mc.layers, n_rows, r, kv_dim),
                "wv_a": (mc.layers, n_rows, mc.hidden, r),
                "wv_b": (mc.layers, n_rows, r, kv_dim),
                "wo_a": (mc.layers, n_rows, q_dim, r),
                "wo_b": (mc.layers, n_rows, r, mc.hidden),
            }
            self._ad_layers = {
                k: jnp.zeros(s, dtype=mc.dtype) for k, s in shapes.items()
            }
            self._ad_rows = np.zeros(self.config.slots, dtype=np.int32)

        mc_static = mc
        ffn_static = self._ffn  # None = dense SwiGLU; MoE routes experts

        # sampled tokens/logprobs come back to the leader host every chunk;
        # under a (possibly multi-host) mesh they inherit the dp sharding of
        # the logits, which a multi-controller leader cannot fetch — pin them
        # replicated (XLA: one tiny all-gather on ICI per chunk)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            _rep = NamedSharding(self.mesh, P())

            def _fetchable(*arrays):
                return tuple(
                    jax.lax.with_sharding_constraint(a, _rep) for a in arrays
                )
        else:
            def _fetchable(*arrays):
                return arrays

        paged = self.block_mgr is not None
        # None = auto (LS_TPU_FLASH env); under a mesh the kernel runs
        # per-shard through shard_map (heads on tp), so TP serving keeps it
        prefill_flash = None
        mesh_static = self.mesh

        def _make_decode(sampler_mode: tuple, window: int | None,
                         k_steps: int = 0, use_pen: bool = False):
            """``window``: dense → cache-row bucket (None = full cache);
            paged → number of block-table columns to sweep. ``k_steps``:
            fused steps per dispatch (0 → config.decode_chunk); light-load
            bursts compile a short variant. ``use_pen``: the variant takes
            (presences, frequencies, counts) after topps and samples with
            presence/frequency penalties."""
            use_top_p, use_top_k, all_greedy = sampler_mode
            K = k_steps or self.config.decode_chunk

            def _sample_fn_for(temps, topks, topps, pres=None, freq=None):
                # ONE definition for all three decode variants (paged,
                # dense-pallas, dense-xla) — they must sample identically
                if use_pen:
                    def sample_fn(logits, sub, counts):
                        return sample_tokens(
                            logits, sub, temps, topks,
                            use_top_p=use_top_p, top_ps=topps,
                            use_top_k=use_top_k, all_greedy=all_greedy,
                            use_penalties=True, presences=pres,
                            frequencies=freq, counts=counts,
                        )
                else:
                    def sample_fn(logits, sub):
                        return sample_tokens(
                            logits, sub, temps, topks,
                            use_top_p=use_top_p, top_ps=topps,
                            use_top_k=use_top_k, all_greedy=all_greedy,
                        )

                return sample_fn

            def _extras(pres, freq, counts):
                return (pres, freq, counts) if use_pen else None

            if paged:
                @partial(jax.jit, donate_argnums=(1, 2))
                def _decode_chunk(params, cache_k, cache_v, tokens, lengths,
                                  active, tables, key, temps, topks, topps,
                                  pres=None, freq=None, counts=None,
                                  ad_layers=None, ad_ids=None):
                    from langstream_tpu.models.llama_paged import (
                        llama_decode_chunk_paged,
                    )

                    # kwargs default to None so the adapter-less engine traces
                    # the exact seed jaxpr — adapters ride in only when the
                    # store is enabled and the dispatch passes them explicitly
                    adapters = (
                        None if ad_ids is None
                        else {"ids": ad_ids, "layers": ad_layers}
                    )
                    sample_fn = _sample_fn_for(temps, topks, topps, pres, freq)
                    # return_packed folds the tokens+bitcast-logprobs pack
                    # into the decode program itself: the chunk's whole
                    # host traffic is out[0]'s D2H copy, with no post-hoc
                    # pack dispatch (pre-fusion _pack_chunk) behind it
                    out = llama_decode_chunk_paged(
                        mc_static, params, tokens, lengths, active,
                        cache_k, cache_v, tables, sample_fn, key, K,
                        num_read_blocks=window,
                        kernel=self.paged_read_kernel,
                        mesh=mesh_static, ffn=ffn_static,
                        sample_extras=_extras(pres, freq, counts),
                        adapters=adapters,
                        return_packed=True,
                    )
                    return _fetchable(out[0]) + out[1:]

                return _decode_chunk

            @partial(jax.jit, donate_argnums=(1, 2))
            def _decode_chunk(params, cache_k, cache_v, tokens, lengths, active,
                              key, temps, topks, topps,
                              pres=None, freq=None, counts=None):
                """K fused decode steps; one host round-trip per chunk. The
                big cache is read-only inside the chunk (llama_decode_chunk)
                — per-step HBM traffic is params+cache *read* only, and the
                static ``window`` caps the cache read to the smallest bucket
                covering the longest active sequence."""
                from langstream_tpu.models.llama import llama_decode_chunk
                from langstream_tpu.models.llama_paged import (
                    pack_tokens_logprobs,
                )

                sample_fn = _sample_fn_for(temps, topks, topps, pres, freq)
                if self.dense_read_kernel != "xla":
                    from langstream_tpu.models.llama_paged import (
                        llama_decode_chunk_dense_pallas,
                    )

                    out = llama_decode_chunk_dense_pallas(
                        mc_static, params, tokens, lengths, active,
                        cache_k, cache_v, sample_fn,
                        key, K,
                        window=window, kernel=self.dense_read_kernel,
                        ffn=ffn_static,
                        sample_extras=_extras(pres, freq, counts),
                    )
                    # dense twins pack inside THIS jit: same one-fetch
                    # tail, same single compiled program per chunk
                    return _fetchable(
                        pack_tokens_logprobs(out[0], out[1])
                    ) + out[2:]

                out = llama_decode_chunk(
                    mc_static, params, tokens, lengths, active,
                    cache_k, cache_v, sample_fn,
                    key, K, window=window, ffn=ffn_static,
                    sample_extras=_extras(pres, freq, counts),
                )
                return _fetchable(
                    pack_tokens_logprobs(out[0], out[1])
                ) + out[2:]

            return _decode_chunk

        self._make_decode = _make_decode

        def _make_prefill(sampler_mode: tuple):
            use_top_p, use_top_k, all_greedy = sampler_mode
            if paged:
                @partial(jax.jit, donate_argnums=(1, 2))
                def _prefill(params, cache_k, cache_v, tokens, lengths, tables,
                             key, temps, topks, topps,
                             ad_layers=None, ad_ids=None):
                    from langstream_tpu.models.llama_paged import (
                        llama_prefill_paged,
                    )

                    adapters = (
                        None if ad_ids is None
                        else {"ids": ad_ids, "layers": ad_layers}
                    )
                    logits, ck, cv = llama_prefill_paged(
                        mc_static, params, tokens, lengths, cache_k, cache_v,
                        tables, use_flash=prefill_flash, mesh=mesh_static,
                        ffn=ffn_static, adapters=adapters,
                    )
                    next_tokens, logprobs = _fetchable(
                        *sample_tokens(
                            logits, key, temps, topks,
                            use_top_p=use_top_p, top_ps=topps,
                            use_top_k=use_top_k, all_greedy=all_greedy,
                        )
                    )
                    return next_tokens, logprobs, ck, cv

                return _prefill

            @partial(jax.jit, donate_argnums=(1, 2))
            def _prefill(params, cache_k, cache_v, tokens, lengths, slot_ids,
                         key, temps, topks, topps):
                logits, ck, cv = llama_prefill(
                    mc_static, params, tokens, lengths, cache_k, cache_v, slot_ids,
                    use_flash=prefill_flash, mesh=mesh_static, ffn=ffn_static,
                )
                next_tokens, logprobs = _fetchable(
                    *sample_tokens(
                        logits, key, temps, topks,
                        use_top_p=use_top_p, top_ps=topps,
                        use_top_k=use_top_k, all_greedy=all_greedy,
                    )
                )
                return next_tokens, logprobs, ck, cv

            return _prefill

        self._make_prefill = _make_prefill

        def _make_prefill_continue(sampler_mode: tuple, nrb: int):
            """Suffix prefill against cached prefix blocks (paged only):
            the automatic-prefix-caching fast path. ``nrb`` is the static
            block-window bucket covering the longest reused prefix."""
            use_top_p, use_top_k, all_greedy = sampler_mode

            @partial(jax.jit, donate_argnums=(1, 2))
            def _prefill_cont(params, cache_k, cache_v, tokens, starts,
                              suffix_lengths, tables, key, temps, topks, topps,
                              ad_layers=None, ad_ids=None):
                from langstream_tpu.models.llama_paged import (
                    llama_prefill_continue_paged,
                )

                adapters = (
                    None if ad_ids is None
                    else {"ids": ad_ids, "layers": ad_layers}
                )
                logits, ck, cv = llama_prefill_continue_paged(
                    mc_static, params, tokens, starts, suffix_lengths,
                    cache_k, cache_v, tables, num_read_blocks=nrb,
                    ffn=ffn_static, kernel=self._continuation_kernel(),
                    mesh=mesh_static, adapters=adapters,
                )
                next_tokens, logprobs = _fetchable(
                    *sample_tokens(
                        logits, key, temps, topks,
                        use_top_p=use_top_p, top_ps=topps,
                        use_top_k=use_top_k, all_greedy=all_greedy,
                    )
                )
                return next_tokens, logprobs, ck, cv

            return _prefill_cont

        self._make_prefill_continue = _make_prefill_continue

        def _make_spec_step(nrb: int, sampler_mode: tuple):
            """Fused device-resident speculative step (prompt-lookup
            decoding): draft over the resident context rows + verify +
            in-program context update, ONE dispatch per step. The draft
            count is static (config), the acceptance rule (greedy vs
            rejection-sampled) specializes via ``sampler_mode``. The
            host reads exactly one packed array back per step."""
            D = self.config.speculative_drafts

            @partial(jax.jit, donate_argnums=(1, 2, 3))
            def _spec_step(params, cache_k, cache_v, ctx, current, lengths,
                           active, tables, key, temps, topks, topps,
                           ad_layers=None, ad_ids=None):
                from langstream_tpu.models.llama_paged import (
                    llama_spec_step_paged,
                )

                adapters = (
                    None if ad_ids is None
                    else {"ids": ad_ids, "layers": ad_layers}
                )
                out = llama_spec_step_paged(
                    mc_static, params, ctx, current, lengths, active,
                    cache_k, cache_v, tables, num_drafts=D,
                    num_read_blocks=nrb, ffn=ffn_static,
                    kernel=self._continuation_kernel(), mesh=mesh_static,
                    key=key, temps=temps, topks=topks, topps=topps,
                    sampler_mode=sampler_mode, adapters=adapters,
                )
                # the leader host reads ONLY the packed array each step
                return _fetchable(out[0]) + out[1:]

            return _spec_step

        self._make_spec_step = _make_spec_step
        # the sampler's expensive passes (top-p vocab sort, top-k selection
        # sweep, any sampling at all for greedy-only batches) are compiled
        # in only when an active request needs them; decode additionally
        # specialises per attention window bucket. All variants compile
        # lazily on first use.
        self._decode_chunk_fns: dict[tuple[tuple, int | None, int], Any] = {}
        self._prefill_fns: dict[tuple, Any] = {}
        self._prefill_continue_fns: dict[tuple[tuple, int], Any] = {}
        self._spec_step_fns: dict[tuple[int, tuple], Any] = {}

    def _decode_fn(self, sampler_mode: tuple, window: int | None,
                   k_steps: int = 0, use_pen: bool = False):
        k_steps = k_steps or self.config.decode_chunk
        key = (sampler_mode, window, k_steps, use_pen)
        if key not in self._decode_chunk_fns:
            self._note_compile("decode", key)
            self._decode_chunk_fns[key] = self._make_decode(
                sampler_mode, window, k_steps, use_pen
            )
        return self._decode_chunk_fns[key]

    def _light_threshold(self) -> int:
        """Active-slot count at or below which bursts run short sequential
        chunks (the TTFT regime); 0 when adaptive chunking is disabled or
        the light chunk wouldn't actually be shorter."""
        cfg = self.config
        if cfg.decode_chunk_light <= 0 or cfg.decode_chunk_light >= cfg.decode_chunk:
            return 0
        if cfg.light_load_slots is not None:
            return cfg.light_load_slots
        return max(1, cfg.slots // 8)

    def _prefill_fn(self, sampler_mode: tuple):
        if sampler_mode not in self._prefill_fns:
            self._prefill_fns[sampler_mode] = self._make_prefill(sampler_mode)
        return self._prefill_fns[sampler_mode]

    def _prefill_continue_fn(self, sampler_mode: tuple, nrb: int):
        key = (sampler_mode, nrb)
        if key not in self._prefill_continue_fns:
            self._prefill_continue_fns[key] = self._make_prefill_continue(
                sampler_mode, nrb
            )
        return self._prefill_continue_fns[key]

    def _continuation_kernel(self) -> str:
        """History-read kernel for continuation/verify: the multi-query
        Pallas kernel on TPU (per-shard via shard_map under a mesh — slots
        on dp, heads on tp), XLA gather elsewhere."""
        if self.block_mgr is None:
            return "xla"
        # paged_read_kernel is resolved away from "auto" at init
        return self.paged_read_kernel

    def _spec_step_fn(self, nrb: int, sampler_mode: tuple):
        key = (nrb, sampler_mode)
        if key not in self._spec_step_fns:
            self._note_compile("spec_step", key)
            self._spec_step_fns[key] = self._make_spec_step(nrb, sampler_mode)
        return self._spec_step_fns[key]

    # ------------------------------------------------------------------
    # flight recorder plumbing
    # ------------------------------------------------------------------

    def _note_compile(self, kind: str, key) -> None:
        """Record a recompile event the first time a (kind, shape) pair is
        dispatched: jit-variant cache misses AND new prefill bucket/row
        shapes (the same Python variant re-traces per padded shape). Runs
        on the engine loop or the dispatch thread; append-only."""
        shape_key = (kind, repr(key))
        if shape_key in self._compiled_shapes:
            return
        self._compiled_shapes.add(shape_key)
        self.flight.event("recompile", what=kind, variant=repr(key))
        self._m_recompiles(1)

    # ------------------------------------------------------------------
    # attribution-ledger plumbing (serving/attribution.py)
    # ------------------------------------------------------------------

    @staticmethod
    def _sampler_code(sampler_mode: tuple) -> str:
        """Compact sampler-variant tag for program ids."""
        use_top_p, use_top_k, all_greedy = sampler_mode
        if all_greedy:
            return "greedy"
        tag = "sample"
        if use_top_k:
            tag += "-tk"
        if use_top_p:
            tag += "-tp"
        return tag

    def _window_rows(self, window: int | None) -> int:
        """Cache rows a decode/verify variant actually sweeps per slot:
        paged variants specialize on block-table columns, dense on row
        windows (None = the full cache)."""
        if self.block_mgr is not None:
            blocks = window or self.paged_layout.max_blocks_per_slot
            return blocks * self.paged_layout.block_size
        return window or self.model_config.max_seq_len

    def _program_decode(
        self, window: int | None, k_steps: int, sampler_mode: tuple,
        pen: bool,
    ) -> str:
        """Program id for a decode-chunk variant; registers its cost
        model on first sight (arithmetic only — loop-thread safe)."""
        rows = self._window_rows(window)
        program = (
            f"decode:w{rows}:k{k_steps}:{self._sampler_code(sampler_mode)}"
            + (":pen" if pen else "")
        )
        if not self.attribution.known(program):
            self.attribution.register(
                program,
                decode_cost(
                    self._prog_shape,
                    slots=self.config.slots,
                    window_rows=rows,
                    k_steps=k_steps,
                    hbm_gbps=self._hbm_gbps,
                ),
            )
        return program

    def _program_prefill(
        self, bucket: int, rows: int, sampler_mode: tuple,
    ) -> str:
        program = (
            f"prefill:p{bucket}:b{rows}:{self._sampler_code(sampler_mode)}"
        )
        if not self.attribution.known(program):
            self.attribution.register(
                program,
                prefill_cost(
                    self._prog_shape,
                    rows=rows,
                    tokens_per_row=bucket,
                    prefix_rows=0,
                    hbm_gbps=self._hbm_gbps,
                ),
            )
        return program

    def _program_prefill_continue(
        self, nrb: int, rows: int, chunk: int, sampler_mode: tuple,
    ) -> str:
        program = (
            f"prefill-continue:nrb{nrb}:b{rows}:c{chunk}:"
            f"{self._sampler_code(sampler_mode)}"
        )
        if not self.attribution.known(program):
            self.attribution.register(
                program,
                prefill_cost(
                    self._prog_shape,
                    rows=rows,
                    tokens_per_row=chunk,
                    prefix_rows=nrb * self.paged_layout.block_size,
                    hbm_gbps=self._hbm_gbps,
                ),
            )
        return program

    def _program_spec_step(self, nrb: int, sampler_mode: tuple) -> str:
        """Program id for the fused draft+verify step. A NEW census family
        (``specstep:``, replacing the pre-fusion ``verify:`` ids): the
        program now contains the prompt-lookup draft and the context
        update, so schema-2 records must not conflate its measured cost
        with the old verify-only program's. The cost model stays the
        verify forward — the draft scan and ctx scatter are noise next to
        the D+1-position forward."""
        drafts = self.config.speculative_drafts
        program = (
            f"specstep:nrb{nrb}:d{drafts}:{self._sampler_code(sampler_mode)}"
        )
        if not self.attribution.known(program):
            self.attribution.register(
                program,
                verify_cost(
                    self._prog_shape,
                    slots=self.config.slots,
                    window_rows=nrb * self.paged_layout.block_size,
                    drafts=drafts,
                    hbm_gbps=self._hbm_gbps,
                ),
            )
        return program

    def _admission_stall(self) -> str | None:
        """Why queued work is not being admitted right now (None when the
        queue is empty or admission would succeed on the next pass)."""
        if self.scheduler.empty():
            return None
        if not any(s.free for s in self.slots):
            return "no-free-slot"
        if self.block_mgr is not None:
            head = self.scheduler.peek()  # engine-loop only
            if head is None:
                return None
            if not self.block_mgr.can_admit(
                len(head.prompt_tokens) + head.max_tokens + 1
            ):
                return "no-kv-blocks"
        if self._has_prefilling():
            return "prefill-in-flight"
        return None

    def _flight_record(
        self,
        phase: str,
        device_s: float,
        tokens: int = 0,
        overlapped_s: float = 0.0,
        spec_accepted: int = 0,
        spec_rejected: int = 0,
        program: str | None = None,
    ) -> None:
        """One flight sample per dispatched burst, plus its Prometheus
        mirrors. ``overlapped_s`` is host work the pipelined loop ran
        under an in-flight dispatch's device shadow (see flight.py).
        ``program`` keys the sample by the compiled variant that ran and
        feeds the attribution ledger's measured side (achieved-vs-
        expected per program, serving/attribution.py) — credited with
        the blocked wait PLUS the overlapped host share: under the
        pipelined loop the device keeps executing while the host works
        in its shadow, so the wait alone would systematically understate
        device time and flatter the per-program ratio exactly when
        pipelining is on. Hot-path discipline (graftcheck OBS503): deque
        appends and counter bumps only — no I/O, no locks."""
        if program is not None:
            self.attribution.observe(program, device_s + overlapped_s)
        stall = self._admission_stall()
        kv_used = (
            self.block_mgr.used_ratio() if self.block_mgr is not None else None
        )
        depths = self.scheduler.depths()
        sample = self.flight.sample(
            phase,
            device_s=device_s,
            overlapped_s=overlapped_s,
            tokens=tokens,
            occupancy=sum(1 for s in self.slots if not s.free),
            queue_depth=self.scheduler.qsize(),
            stall=stall,
            kv_used=kv_used,
            prefix_hits=self.prefix_hits,
            spec_accepted=spec_accepted,
            spec_rejected=spec_rejected,
            queue_by_class=depths,
            program=program,
        )
        # watchdog heartbeat: a recorded dispatch IS step progress
        self.watchdog.beat(sample["queue_depth"])
        if (
            phase == "decode"
            and self._spec_auto_disabled
            and self.config.speculative_drafts > 0
        ):
            # measured-uplift backoff: after enough plain chunks, give
            # speculation another audition (the workload's copy-from-
            # context affinity can change mid-stream — RAG turns end,
            # code-edit turns begin)
            self._spec_plain_since_disable += 1
            if self._spec_plain_since_disable >= self._spec_retry_plain:
                self._spec_auto_disabled = False
                self._spec_plain_since_disable = 0
                self._spec_steps_since_cal = self._spec_cal_every
                self._spec_window.clear()
                self._plain_window.clear()
                self._spec_flips.append((time.monotonic(), "enable"))
                self.flight.event(
                    "spec-auto-enable",
                    plain_chunks=self._spec_retry_plain,
                )
        if depths:
            for cls, gauge in self._m_class_depth.items():
                gauge(depths.get(cls, 0))
        hist = self._m_step_hist.get(phase)
        if hist is not None:
            hist(sample["wall_ms"] / 1000.0)
        self._m_host_overhead(sample["host_ms"] / 1000.0)
        if kv_used is not None:
            self._m_kv_used(kv_used)
        if stall is not None:
            self._m_stall[stall](sample["wall_ms"] / 1000.0)

    def _flight_stall(self, reason: str) -> None:
        """Record an idle/blocked engine-loop gap as stall time."""
        kv_used = (
            self.block_mgr.used_ratio() if self.block_mgr is not None else None
        )
        sample = self.flight.stall(
            reason,
            occupancy=sum(1 for s in self.slots if not s.free),
            queue_depth=self.scheduler.qsize(),
            kv_used=kv_used,
            queue_by_class=self.scheduler.depths(),
        )
        # heartbeat on idle gaps too: an idle engine beats ~once a second,
        # so queue-empty idleness can never read as a wedge
        self.watchdog.beat(sample["queue_depth"])
        self._m_stall[reason](sample["wall_ms"] / 1000.0)

    def _slo_record(self, objective: str, good: bool) -> None:
        """Record one event against an SLO objective (engine loop only;
        no-op without a declared spec or for undeclared objectives)."""
        if self.slo is not None:
            self._slo_emit(objective, self.slo.record(objective, good))

    def _slo_record_latency(self, objective: str, seconds: float) -> None:
        """Record a measured latency; the tracker judges it against the
        objective's declared threshold (no-op when undeclared)."""
        if self.slo is not None:
            self._slo_emit(
                objective, self.slo.record_latency(objective, seconds * 1000.0)
            )

    def _slo_emit(self, objective: str, verdict: dict | None) -> None:
        """Mirror one SLO evaluation onto the burn/budget gauges and
        emit an ``alert`` flight event when the multi-window fast-burn
        condition transitions — alerts fire at record time, not scrape
        time, so an unwatched engine still leaves the evidence in its
        event ring."""
        if verdict is None:
            return
        gauge = self._m_slo_burn.get(objective)
        if gauge is not None:
            gauge(verdict["burn_rate_fast"] or 0.0)
        gauge = self._m_slo_budget.get(objective)
        if gauge is not None:
            gauge(verdict["budget_remaining"])
        if verdict["transition"]:
            self.flight.event(
                "alert",
                objective=objective,
                state="firing" if verdict["alerting"] else "resolved",
                burn_rate_fast=verdict["burn_rate_fast"],
                burn_rate_slow=verdict["burn_rate_slow"],
                budget_remaining=verdict["budget_remaining"],
                target=verdict["target"],
            )
            if verdict["alerting"] and self.incidents is not None:
                # page-threshold crossing: snapshot the evidence at the
                # breach instant (per-objective cooldown in the recorder)
                self._incident_capture(
                    "slo-fast-burn",
                    {
                        "source": "slo",
                        "objective": objective,
                        "burn_rate_fast": verdict["burn_rate_fast"],
                        "burn_rate_slow": verdict["burn_rate_slow"],
                        "budget_remaining": verdict["budget_remaining"],
                        "target": verdict["target"],
                    },
                    dedup_key=objective,
                )

    def health(self) -> dict[str, Any]:
        """Wait-free health snapshot (OBS504: callable from probe
        handlers while the engine is wedged — snapshot reads and
        arithmetic only, no device work, no locks). Judges the watchdog
        heartbeat against the live queue/occupancy and runs the
        degradation predicates over the flight window; a state
        transition is recorded as a ``health`` flight event with the
        stall evidence."""
        queued = self.scheduler.qsize()
        occupancy = sum(1 for s in self.slots if not s.free)
        # streaming TBT burn predicate (wait-free: committed-alert dict
        # reads): classes whose tbt-p99-s error budget is fast-burning
        # degrade the engine exactly like the watchdog's own predicates
        tbt_burn = [
            name
            for name, tracker in self._stream_slo.items()
            if tracker.alerting.get("tbt")
        ]
        verdict = self.watchdog.evaluate(
            queued=queued,
            occupancy=occupancy,
            extra_reasons=tuple(
                f"tbt burn-rate alert: class {name!r} is burning its "
                f"tbt-p99-s error budget at page rate"
                for name in sorted(tbt_burn)
            ),
            samples=self.flight.recent(240),
            # 256, not the display tail's 64: the shrink-pressure
            # predicate compares pool-shrink events across a whole
            # recovery window, and a busy engine emits >64 events
            # (pool-grows, the shrink's own preempt/resume pairs)
            # between two shrinks — a short tail would age the first
            # one out exactly under the sustained pressure the
            # escalation exists to flag (the ring holds 512)
            events=self.flight.recent_events(256),
            # a lockstep-broken engine stays registered but refuses all
            # requests: only a pod restart recovers the slice, so it
            # reports wedged and the liveness probe does the recycling
            stopped=self._stop,
        )
        if verdict.pop("transition"):
            self.flight.event(
                "health",
                state=verdict["state"],
                previous=verdict["previous"],
                reasons=list(verdict["reasons"]),
                last_step_age_s=verdict["last_step_age_s"],
                queued=queued,
                occupancy=occupancy,
            )
            if self.incidents is not None and verdict["state"] in (
                "degraded",
                "wedged",
            ):
                # a worsening transition is a page: classify the trigger
                # by the dominant reason so the bundle's worst-K journeys
                # rank by the segment that reason indicts
                reasons = list(verdict["reasons"])
                if verdict["state"] == "wedged":
                    kind = "health-wedged"
                elif any("memory pressure" in r for r in reasons):
                    kind = "shrink-pressure"
                elif any("tbt burn" in r for r in reasons):
                    kind = "tbt-burn"
                else:
                    kind = "health-degraded"
                self._incident_capture(
                    kind,
                    {
                        "source": "health",
                        "state": verdict["state"],
                        "previous": verdict["previous"],
                        "reasons": reasons,
                        "queued": queued,
                        "occupancy": occupancy,
                    },
                )
        if self.incidents is not None:
            # breaker-storm predicate over the already-snapshotted event
            # tail (router breaker events mirror into this ring): fires
            # independently of watchdog transitions — a replica fanout
            # melting down is an incident even while this engine's own
            # loop is healthy
            storm = breaker_storm(
                self.flight.recent_events(256), time.monotonic()
            )
            if storm is not None:
                self._incident_capture(
                    "breaker-storm", {"source": "health", **storm}
                )
            if self.adapter_store is not None:
                # adapter eviction-storm predicate (docs/ADAPTERS.md):
                # one adapter bouncing out of the tiers repeatedly
                # inside a single hydrate window — thrash the next
                # request re-pays — over the same snapshotted tail
                thrash = adapter_eviction_storm(
                    self.flight.recent_events(256),
                    time.monotonic(),
                    window_s=self.adapter_store.spec.hydrate_timeout_s,
                )
                if thrash is not None:
                    self._incident_capture(
                        "adapter-storm",
                        {"source": "health", **thrash},
                        dedup_key=thrash["adapter"],
                    )
        warmup = self._warmup_state()
        # a draining engine is alive but must take no new traffic: ready
        # drops (the router and the readiness probe both key off it)
        ready = (
            warmup not in ("pending", "running")
            and verdict["state"] != "wedged"
            and not self._draining
        )
        out = {
            "model": self.config.model,
            "slots": self.config.slots,
            **verdict,
            "warmup": warmup,
            "draining": self._draining,
            "ready": ready,
            # adaptive pool-shrink posture (docs/RESILIENCE.md): blocks
            # currently withheld from the KV admission budget — the pod
            # probes surface it so an operator reading /healthz sees a
            # degraded-capacity replica without another round trip
            "budget_withheld": (
                self.block_mgr.budget_reduction
                if self.block_mgr is not None
                else 0
            ),
        }
        if self.config.streaming:
            # which classes are currently fast-burning their tbt-p99-s
            # budget (empty list when healthy) — keyed off the same
            # committed-alert reads that fed extra_reasons above, so the
            # list and the DEGRADED verdict can never disagree
            out["tbt_burn"] = sorted(tbt_burn)
        return out

    def _incident_capture(
        self,
        kind: str,
        evidence: dict[str, Any],
        dedup_key: str | None = None,
    ) -> None:
        """Assemble one incident bundle at the breach site and hand it to
        the recorder's writer thread. Wait-free end to end (graftcheck
        INC1601): the cooldown gate is GIL-atomic dict ops, every section
        is wait-free by its own contract (flight summary, journey-ledger
        snapshots, attribution/survival/kvtransfer, SLO status), and the
        handoff is a deque append — this runs inside ``health()`` (probe
        handlers, OBS504's domain) and the finish path."""
        rec = self.incidents
        if rec is None or not rec.should_capture(kind, dedup_key):
            return
        # event-tail slice: only events past the recorder's seq
        # high-water mark, so overlapping captures dedup exactly
        events = self.flight.recent_events(256)
        watermark = rec.last_event_seq
        fresh = [e for e in events if e.get("seq", 0) > watermark]
        if events:
            rec.last_event_seq = max(watermark, events[-1].get("seq", 0))
        bundle: dict[str, Any] = {
            # wall anchor for cross-pod timeline alignment only
            # graftcheck: disable=OBS501 display anchor, never subtracted
            "captured_at_ms": round(time.time() * 1000.0, 3),
            "model": self.config.model,
            "trigger": {"kind": kind, **evidence},
            "flight": self.flight.summary(),
            "events": fresh,
            "worst_journeys": worst_journeys(kind),
            "attribution": self.attribution_section(),
            "survival": self.survival_section(),
            "kvtransfer": self.kv_transfer_section(),
            "breakers": {
                "open": self.flight.events_by_type.get("breaker-open", 0),
                "close": self.flight.events_by_type.get("breaker-close", 0),
            },
            "slo": self.slo_status(),
            "streaming": (
                self.streaming_section() if self.config.streaming else None
            ),
            "config": self.config.to_dict(),
        }
        if self.adapter_store is not None:
            # tier residency + ledger at the breach instant (key absent
            # on adapter-less engines: their bundles stay byte-identical
            # to a pre-adapter build)
            bundle["adapters"] = self.adapter_store_section()
        bundle_id = rec.submit(bundle)
        self.flight.event("incident", bundle=bundle_id, trigger=kind)

    def _warmup_state(self) -> str:
        """``not-required`` (no warmup_on_start), ``pending`` (gate armed
        but nothing triggered it yet), ``running``, ``done``, or
        ``failed`` (done with an exception — serving continues on lazy
        compiles, so failed still counts as warmed for readiness)."""
        if not self.config.warmup_on_start:
            return "not-required"
        task = self._warmup_task
        if task is None:
            return "pending"
        if not task.done():
            return "running"
        if task.cancelled() or task.exception() is not None:
            return "failed"
        return "done"

    def slo_status(self) -> dict[str, Any] | None:
        """The SLO section for ``stats()`` / ``/flight/summary`` (None
        without a declared spec). Wait-free like :meth:`health`."""
        if self.slo is None:
            return None
        return self.slo.status()

    def streaming_section(self) -> dict[str, Any]:
        """The streaming-delivery payload for ``stats()["streaming"]``
        (streaming-configured engines only — the default stats surface
        stays pinned without the flag). Wait-free by the same contract
        as :meth:`attribution_section`: counter snapshots and digest
        walks only, no locks, no awaits — a stats poll must answer while
        a stream is mid-emit."""
        return {
            # streams currently holding a decode slot (the cancellation
            # leak detector in tools/engine_top.py compares this against
            # cancelled-vs-reclaimed below)
            "active": sum(
                1
                for s in self.slots
                if not s.free
                and s.request is not None
                and s.request.on_chunk is not None
            ),
            "emits": self.stream_emits_total,
            "stalls": self.stream_stalls_total,
            "cancelled": self.stream_cancels_total,
            "reclaimed": self.stream_reclaims_total,
            # per-class inter-token-interval digests — bounded summaries
            # (count/p50/p99/max/mean), never raw interval lists
            "tbt": {
                name: digest.summary()
                for name, digest in sorted(self._stream_tbt_by_class.items())
            },
            "tbt_burn": sorted(
                name
                for name, tracker in self._stream_slo.items()
                if tracker.alerting.get("tbt")
            ),
        }

    def speculative_section(self) -> dict[str, Any]:
        """The speculation payload for ``stats()["speculative"]`` and the
        ``/flight/summary`` entry (speculative-configured engines only —
        the default surfaces stay pinned without the flag). Wait-free:
        counter snapshots only. Carries the fused-tail plumbing counters
        (dispatches/fetches must track 1:1 — one packed fetch per fused
        draft+verify step) and the measured-uplift plane that drives
        auto-disable, so engine_top's speculation panel and ``--analyze``
        need no extra engine surface."""
        return {
            "steps": self.spec_steps,
            "drafts_accepted": self.spec_accepted,
            # rejected drafts make a spec slowdown decomposable from a
            # live engine: high reject ratio = wasted verify FLOPs, not
            # host overhead
            "rejected": self.spec_rejected,
            "dispatches": self._spec_dispatches,
            "fetches": self._spec_fetches,
            "uplift": self._spec_last_uplift,
            "auto_disabled": self._spec_auto_disabled,
            "flips": len(self._spec_flips),
            "window_steps": len(self._spec_window),
            "window_plain": len(self._plain_window),
        }

    def attribution_section(self) -> dict[str, Any]:
        """The device-attribution payload: per-program achieved-vs-
        expected ledger plus the HBM memory ledger — what
        ``stats()["attribution"]``, the pod ``/attribution``/``/memory``
        endpoints, and the control-plane fan-in serve. Wait-free by
        contract (graftcheck OBS505, the attribution twin of OBS504):
        snapshot reads and arithmetic only — an attribution poll must
        answer even while the engine is wedged mid-dispatch. The
        ``hbm_bytes_by_owner`` Prometheus gauges refresh here, so any
        reader keeps the scrape surface current."""
        memory = self._memory_ledger()
        owners = memory["hbm_bytes_by_owner"]
        for owner, gauge in self._m_hbm_owner.items():
            gauge(owners.get(owner) or 0)
        return {
            "model": self.config.model,
            "slots": self.config.slots,
            "generation": self._hbm_generation,
            "hbm_gbps_assumed": self._hbm_gbps,
            "programs": self.attribution.report(),
            "memory": memory,
        }

    def _memory_ledger(self) -> dict[str, Any]:
        """Live ``hbm_bytes_by_owner`` breakdown (serving/attribution.py
        :func:`memory_ledger`). Weight/pool totals were computed once at
        init (the shapes are fixed; the live handles are donated and
        rebound on the dispatch thread, so readers never touch them);
        the LRU and prefix-cache terms are snapshot reads."""
        prefix_blocks = (
            self.block_mgr.prefix_block_count()
            if self.block_mgr is not None
            else 0
        )
        return memory_ledger(
            weights_bytes=self._weights_bytes,
            kv_pool_bytes=self._kv_cache_bytes,
            prefix_blocks=prefix_blocks,
            bytes_per_block=self._kv_block_bytes,
            sampler_bytes=self._sampler_dev_cache.device_bytes(),
            tables_bytes=self._tables_dev_cache.device_bytes(),
            # serialized handoff payloads awaiting pickup (host bytes,
            # attributed so a stalled handoff pipeline is visible in the
            # same ledger operators already watch)
            in_transit_bytes=self._kv_in_transit_bytes,
            limit_bytes=self._hbm_limit,
            limit_source=self._hbm_limit_source,
            # adaptive pool-shrink: budget blocks withheld after a device
            # allocator failure — a sub-owner of the (unchanged) pool
            # bytes, so the owner sum is identical across shrink/restore
            kv_withheld_bytes=(
                self.block_mgr.budget_reduction * self._kv_block_bytes
                if self.block_mgr is not None
                else 0
            ),
        )

    @staticmethod
    def _sampler_mode(temps, topks, topps) -> tuple:
        """(use_top_p, use_top_k, all_greedy) for the given active rows —
        the static specialization key for compiled sampler variants."""
        use_top_p = bool((topps < 1.0).any())
        use_top_k = bool((topks > 0).any())
        all_greedy = bool((temps <= 0).all()) and not use_top_p and not use_top_k
        return (use_top_p, use_top_k, all_greedy)

    def _window_for(self, max_len: int) -> int | None:
        """Smallest 128-multiple cache window covering ``max_len`` rows (the
        chunk's new tokens live in the chunk buffer, not the window).

        Decode is cache-read bound, so window granularity is read traffic:
        power-of-two buckets read up to 2× the needed rows near bucket
        edges. Hybrid granularity bounds BOTH costs: 128-multiples up to
        1024 rows (excess <128 rows/slot where most serving lengths live),
        powers of two beyond (a long-context engine would otherwise compile
        a fresh ~30s decode variant every 128 generated tokens)."""
        S = self.model_config.max_seq_len
        if max_len <= 1024:
            w = max(128, -(-max_len // 128) * 128)
        else:
            w = 2048
            while w < max_len:
                w *= 2
        return None if w >= S else w

    def _read_blocks_for(self, max_len: int) -> int:
        """Paged analogue of :meth:`_window_for`: block-table columns to
        sweep, bucketed so few decode variants compile."""
        bs = self.paged_layout.block_size
        window = self._window_for(max_len) or self.model_config.max_seq_len
        return max(1, min(-(-window // bs), self.paged_layout.max_blocks_per_slot))

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    async def generate(
        self,
        prompt: str | list[int],
        options: dict[str, Any] | None = None,
        on_token: Callable[[int, float, bool], Any] | None = None,
        on_chunk: Callable[[list, str, bool], Any] | None = None,
        _warmup_probe: bool = False,
    ) -> dict[str, Any]:
        """Generate a completion. ``on_token(token_id, logprob, last)`` fires
        per token (sync or async). ``on_chunk(new_token_ids, new_text,
        is_final)`` fires once per committed decode chunk at the burst-flush
        safe point — ``new_text`` deltas concatenate byte-identically to the
        non-streaming ``text`` (UTF-8 partials and possible stop-sequence
        prefixes are held back until they resolve). Returns
        ``{"tokens", "text", "logprobs", "num_prompt_tokens", "ttft"}``.

        ``options["stream-key"]`` (the gateway's ``langstream-stream-id``)
        registers the request with the process-wide stream-cancel registry
        so a client disconnect observed at the gateway cancels this future;
        the decode loop frees the slot at the next chunk boundary.

        ``_warmup_probe`` is internal: warmup()'s own generate calls skip
        the warmup gate below (they ARE the warmup)."""
        if self._stop:
            # closed, or stopped after a broken lockstep group: enqueueing
            # would hang forever (the restarted loop exits immediately and
            # never resolves the future) — fail loudly instead so the pod
            # restarts the slice
            raise RuntimeError(
                "serving engine is stopped (closed or lockstep group broken)"
            )
        options = options or {}
        if self.config.warmup_on_start and not _warmup_probe:
            # one shared task (also credited to explicit warmup() calls):
            # every early arrival awaits it, so the probe/wave shapes
            # aren't perturbed by real traffic and real requests only
            # start once the variants exist. A warmup failure is logged,
            # never surfaced as a request failure.
            task = self._warmup_begun()
            if not task.done():
                try:
                    await asyncio.shield(task)
                # graftcheck: disable=EXC402 warmup failure is logged by the task done-callback
                except Exception:
                    pass  # lazy compiles take over
        tokens = (
            self.tokenizer.encode(prompt) if isinstance(prompt, str) else list(prompt)
        )
        max_prompt = self.model_config.max_seq_len - 2
        if len(tokens) > max_prompt:
            tokens = tokens[-max_prompt:]
        top_k = int(options.get("top-k", 0))
        if top_k > 64:
            log.warning("top-k %d exceeds the compiled window of 64; clamping", top_k)
            top_k = 64
        max_tokens = min(
            int(options.get("max-tokens", self.config.default_max_tokens)),
            self.model_config.max_seq_len - len(tokens) - 1,
        )
        if self.block_mgr is not None and not self.block_mgr.fits_ever(
            len(tokens) + max_tokens + 1
        ):
            raise ValueError(
                f"request needs {len(tokens) + max_tokens + 1} tokens of KV, "
                f"more than the paged pool can ever hold "
                f"({self.block_mgr.stats()['num_blocks']} blocks of "
                f"{self.paged_layout.block_size}); lower max-tokens or grow "
                f"kv-pool-blocks/kv-pool-fraction"
            )
        stop = _normalize_stop(options.get("stop"))
        adapter = str(options.get("adapter", "") or "")
        if adapter and self.adapter_store is None:
            # refused loudly at submit: a silently-ignored adapter would
            # serve base-model output under the tenant's fine-tune name
            raise ValueError(
                f"request names adapter {adapter!r} but this engine has "
                "no adapter store configured (serving adapter-store)"
            )
        request = _Request(
            prompt_tokens=tokens,
            max_tokens=max_tokens,
            temperature=float(options.get("temperature", 0.0)),
            top_k=top_k,
            top_p=float(options.get("top-p", 1.0)),
            on_token=on_token,
            future=asyncio.get_running_loop().create_future(),
            loop=asyncio.get_running_loop(),
            enqueue_time=time.monotonic(),
            # warmup probes must not attach synthetic phase spans to
            # whichever record's task happened to trigger the warmup gate
            trace=None if _warmup_probe else current_context(),
            warmup=_warmup_probe,
            stop=stop,
            presence_penalty=float(options.get("presence-penalty", 0.0)),
            frequency_penalty=float(options.get("frequency-penalty", 0.0)),
            tenant=str(options.get("qos-tenant", "") or ""),
            priority=normalize_priority(options.get("priority")),
            # end-to-end deadline (docs/RESILIENCE.md): "deadline" is
            # the absolute epoch stamp the gateway/agent forwarded from
            # the langstream-deadline header; "deadline-s" a caller-
            # relative budget. Malformed values degrade to None.
            deadline=_deadline_from_options(options),
            on_chunk=on_chunk,
            stream_key=(
                str(options["stream-key"])
                if options.get("stream-key")
                else None
            ),
            adapter=adapter,
        )
        if on_chunk is not None and self.config.streaming:
            # bounded per-request TBT digest (never the raw interval
            # list); only streaming-configured engines pay for the plane
            request.stream_tbt = TbtDigest()
        if request.stream_key is not None and not _warmup_probe:
            # disconnect-as-cancellation bridge: the gateway cancels by
            # this key from its socket teardown; the entry self-cleans
            # when the future resolves either way
            STREAMS.register(
                request.stream_key, request.future, request.loop
            )
        if not _warmup_probe:
            # journey ledger key: the trace id when traced (the one id
            # that already spans gateway → broker → engine and now rides
            # the kvtransfer header), a fresh same-shaped id otherwise
            request.journey_id = (
                request.trace.trace_id
                if request.trace is not None
                else fresh_trace_id()
            )
            self._journey(
                request, "submit",
                model=self.config.model, role=self._pool_role,
                prompt_tokens=len(tokens), max_tokens=max_tokens,
            )
        if request.deadline is not None and not _warmup_probe:
            left = remaining_s(request.deadline)
            if left <= 0.0:
                # the deadline acceptance contract: an unmeetable budget
                # is refused with an explicit event BEFORE the request
                # ever queues — never a silent late completion
                raise self._note_deadline_shed(request, "submit", left)
        try:
            if self._draining and not _warmup_probe:
                # drain-before-terminate: admission is closed. The shed
                # is EXPLICIT (Retry-After) so the gateway/router resends
                # to a live replica instead of losing the request into a
                # dying pod's queue.
                raise RateLimited(
                    "draining", 1.0,
                    "engine is draining (scale-down or pod termination in "
                    "progress); retry against another replica",
                )
            self.scheduler.submit(request)
        except RateLimited as e:
            # load shed / tenant throttle: refused before any slot or
            # block was touched — callers (gateway, agents) map this to
            # 429 + Retry-After
            self.flight.event(
                "shed", reason=e.reason, tenant=request.tenant,
                priority=request.priority,
                retry_after_s=e.retry_after,
            )
            self._journey(
                request, "shed", reason=e.reason,
                retry_after_s=e.retry_after,
            )
            if e.reason == "draining":
                self._drain_shed += 1
            if self._m_shed is not None:
                self._m_shed(1)
            if not _warmup_probe:
                self._slo_record("shed-rate", False)
            raise
        if not _warmup_probe:
            # the shed-rate objective counts every submission: admitted =
            # good, refused = bad (recorded in the except arm above)
            self._slo_record("shed-rate", True)
            if self.journal is not None:
                # crash-requeue journal (docs/RESILIENCE.md): the work is
                # accepted NOW — journaled before the caller ever sees a
                # future, retired when finish/shed/fail answers it
                self.journal.admit(request_entry(request))
                if self._m_journal_depth is not None:
                    self._m_journal_depth(self.journal.depth())
        self._ensure_loop()
        self._wake.set()
        return await request.future

    def _warmup_begun(self) -> "asyncio.Task":
        """The one shared warmup task: created on first need (explicit
        warmup() call or the warmup_on_start gate), credited to both — an
        explicit pre-warm means the gate has nothing left to do."""
        if self._warmup_task is None:
            self._warmup_task = asyncio.ensure_future(self._do_warmup())

            def _log_done(task: asyncio.Task) -> None:
                if task.cancelled():
                    return
                if task.exception() is not None:
                    log.error(
                        "engine warmup failed; serving continues with "
                        "lazy compiles",
                        exc_info=task.exception(),
                    )
                else:
                    log.info("engine warmup complete: %s", task.result())

            self._warmup_task.add_done_callback(_log_done)
        return self._warmup_task

    async def warmup(self) -> dict[str, int]:
        """Compile the serving-path jit variants before real traffic (see
        :meth:`_do_warmup`). Idempotent: shares one task with the
        warmup_on_start gate, so pre-warming explicitly never repeats the
        probe/wave."""
        return await asyncio.shield(self._warmup_begun())

    async def _do_warmup(self) -> dict[str, int]:
        """A lone greedy request (light-regime burst, single-row prefill),
        then a concurrent wave one past the light-load threshold
        (heavy-regime burst, power-of-two padded prefill rows,
        prefix-cache continuation when enabled). Greedy only — non-greedy
        sampler variants compile on first use; greedy is what the
        latency-sensitive paths serve. Prompts in other prefill-length
        buckets still pay one compile on first sight. Warmup tokens count
        toward engine metrics (they ran on the chips)."""
        text = "engine warmup probe text. " * 4
        k = max(self.config.decode_chunk, self.config.decode_chunk_light) + 1
        opts = {"max-tokens": k, "temperature": 0}
        self.flight.event("warmup", stage="begin")
        await self.generate(text, dict(opts), _warmup_probe=True)
        wave = min(
            self.config.slots,
            max(2, self._light_threshold() + 1, self.config.prefill_batch),
        )
        await asyncio.gather(
            *(
                self.generate(text, dict(opts), _warmup_probe=True)
                for _ in range(wave)
            )
        )
        result = {
            "decode_variants": len(self._decode_chunk_fns),
            "prefill_variants": len(self._prefill_fns),
        }
        self.flight.event("warmup", stage="end", **result)
        return result

    def stats(self) -> dict[str, Any]:
        out = {
            "model": self.config.model,
            "slots": self.config.slots,
            "active": sum(1 for s in self.slots if not s.free),
            "queued": self.scheduler.qsize(),
            "total-generated": self.total_generated,
            # admission-policy counters (per-class queued/admitted/shed/
            # preempted under QoS; plain FIFO totals otherwise) — the
            # control-plane /qos route reads these off /flight/summary
            "scheduler": self.scheduler.stats(),
            "decode-chunks": {
                "light": self._light_chunks,
                "heavy": self._heavy_chunks,
                # the one-fetch invariant, observable live: a ratio above
                # 1.0 means the decode tail is re-crossing the host
                # boundary (regression canary for the fused sampler)
                "dispatched": self._decode_dispatches,
                "fetched": self._decode_fetches,
                "host_fetches_per_chunk": (
                    round(self._decode_fetches / self._decode_dispatches, 4)
                    if self._decode_dispatches else 0.0
                ),
            },
            # pipelined loop posture + the bounded device-upload caches
            # (size/hits/misses/evictions — the eviction counter is the
            # long-lived-engine leak canary the LRU bound exists for)
            "pipeline": self._pipeline_on,
            "device-cache": {
                "tables": self._tables_dev_cache.stats(),
                "sampler": self._sampler_dev_cache.stats(),
            },
            # per-phase dispatched-step counts (flight recorder): lets a
            # running engine decompose where its dispatches go without a
            # bench run
            "steps": dict(self.flight.steps_by_phase),
            # watchdog verdict + warmup/readiness posture (serving/health.py)
            "health": self.health(),
            # drain-before-terminate posture + last drain's counts
            # (docs/FLEET.md): the autoscaler's evidence trail
            "drain": self._drain_section(),
            # disaggregated-pool posture + handoff counters
            # (docs/DISAGG.md): combined engines report role=combined
            # with zeroed counters
            "kvtransfer": self.kv_transfer_section(),
            # device attribution plane: per-program achieved-vs-expected
            # ledger + hbm_bytes_by_owner (serving/attribution.py)
            "attribution": self.attribution_section(),
            # device-survival plane (docs/RESILIENCE.md): live KV budget
            # vs configured, shrink/restore counters, fault-injection
            # state, crash-requeue journal depth
            "survival": self.survival_section(),
        }
        slo = self.slo_status()
        if slo is not None:
            out["slo"] = slo
        if self.config.streaming:
            # streaming delivery plane: active streams, emit/stall/cancel
            # counters, per-class TBT digests (docs/OBSERVABILITY.md)
            out["streaming"] = self.streaming_section()
        if self.prefix_store is not None:
            # tiered prefix store: per-tier bytes/budgets, hit and
            # demotion/eviction counters, exact byte ledger
            # (docs/PREFIX.md)
            out["prefixstore"] = self.prefix_store_section()
        if self.adapter_store is not None:
            # tiered multi-LoRA adapter store: per-tier bytes/budgets,
            # hit/load/eviction counters, resident rows, exact byte
            # ledger (docs/ADAPTERS.md)
            out["adapters"] = self.adapter_store_section()
        if self.block_mgr is not None:
            out["kv"] = {"layout": "paged", **self.block_mgr.stats()}
        if self.config.speculative_drafts > 0:
            out["speculative"] = self.speculative_section()
        if self.incidents is not None:
            # incident capture plane: captured/suppressed/evicted counts
            # plus the bounded bundle index (docs/OBSERVABILITY.md)
            out["incidents"] = self.incidents.stats()
        return out

    async def close(self) -> None:
        self._stop = True
        self._wake.set()
        if self._loop_task is not None:
            await self._loop_task
        if self._lockstep is not None:
            self._lockstep.close()
        if self.prefix_store is not None:
            self.prefix_store.close()
        if self.adapter_store is not None:
            self.adapter_store.close()
        if self.journal is not None:
            # flush the retire tail: a clean shutdown leaves a journal
            # that replays exactly the work this process never answered
            self.journal.close()
        if self.incidents is not None:
            # flush any in-flight bundle: evidence captured moments
            # before a shutdown is exactly the evidence worth keeping
            self.incidents.close()
        # wait=True: the loop task above is done, so the executor queue is
        # empty or finishing its last closure — joining it here is what
        # makes the reference drops below race-free (the dispatch thread
        # no longer exists when they run)
        self._executor.shutdown(wait=True)
        # evict from the singleton cache: a closed engine must not be handed
        # out again (its loop would exit immediately, stranding requests)
        with self._instances_lock:
            for key, inst in list(self._instances.items()):
                if inst is self:
                    del self._instances[key]
        # drop the HBM-heavy references NOW: a closed engine object can
        # outlive close() (caller locals, task frames) and at the 8B shape
        # its weights+KV are ~12GB — a second engine in the same process
        # (speculation on/off comparison, model reload) must not OOM
        # against a ghost (r5: the speculative bench child died exactly
        # this way)
        # graftcheck: disable=RACE801 loop task awaited + executor joined (wait=True): no dispatch closure can still run
        self.params = None
        # graftcheck: disable=RACE801 loop task awaited + executor joined (wait=True): no dispatch closure can still run
        self.cache_k = self.cache_v = None
        # graftcheck: disable=RACE801 loop task awaited + executor joined (wait=True): no dispatch closure can still run
        self._ad_layers = None
        self._decode_chunk_fns.clear()
        self._pending_chunk = None
        # graftcheck: disable=RACE801 loop task awaited + executor joined (wait=True): no dispatch closure can still run
        self._tables_dev_cache.clear()
        self._sampler_dev_cache.clear()

    # ------------------------------------------------------------------
    # drain-before-terminate (docs/FLEET.md)
    # ------------------------------------------------------------------

    async def drain(self, grace_s: float = 30.0) -> dict[str, Any]:
        """Drain this engine for termination: stop admitting new work
        (submissions shed with ``Retry-After``), preempt-and-requeue
        every running generation at the loop's safe point (the PR 4 QoS
        machinery: generated tokens + sampling params ARE the snapshot,
        resume is byte-identical), then serve the backlog — queued plus
        requeued — to completion. When the grace budget expires with
        work still in flight, the leftovers are failed *explicitly* with
        :class:`RateLimited` (never silently dropped): the caller knows
        to retry elsewhere.

        Returns ``{"requeued", "completed", "shed", "duration_s"}`` —
        also emitted as a ``drain`` flight event and surfaced in
        ``stats()["drain"]``. Idempotent: a second call joins the wait
        with its own grace budget. Draining is terminal for admission
        (the pod is going away); the engine still answers stats/health.
        """
        if self._stop:
            return {
                "requeued": 0, "completed": 0, "shed": 0,
                "duration_s": 0.0, "stopped": True,
            }
        start = time.monotonic()
        if not self._draining:
            self._draining = True
            self._drain_pass_done = False
            self._drain_requeued = 0
            self._drain_shed = 0
            self._drain_base_completed = self.completed_requests
            self._drain_report = None
            self.flight.event(
                "drain", stage="begin",
                queued=self.scheduler.qsize(),
                inflight=sum(1 for s in self.slots if not s.free),
            )
        self._ensure_loop()
        self._wake.set()
        deadline = start + grace_s
        while time.monotonic() < deadline:
            if (
                self.scheduler.empty()
                and all(s.free for s in self.slots)
                and self._pending_chunk is None
                and not self._prefix_hydrating
            ):
                break
            await asyncio.sleep(0.02)
        leftovers = (
            self.scheduler.qsize()
            + len(self._prefix_hydrating)
            + sum(
                1
                for s in self.slots
                if s.request is not None and not s.request.future.done()
            )
        )
        if leftovers:
            # grace exhausted: shed the remainder loudly. _fail_inflight
            # releases every slot/block and fails queued + running
            # futures, so nothing is ever silently lost — the error
            # carries retry_after for the 429 mapping.
            self._fail_inflight(
                RateLimited(
                    "draining", 1.0,
                    f"engine drained with {leftovers} requests unfinished "
                    f"after {grace_s:.1f}s grace; retry another replica",
                )
            )
            self._drain_shed += leftovers
        report = {
            "requeued": self._drain_requeued,
            "completed": self.completed_requests - self._drain_base_completed,
            "shed": self._drain_shed,
            "duration_s": round(time.monotonic() - start, 3),
        }
        self._drain_report = report
        self.flight.event("drain", stage="end", **report)
        return report

    def _drain_preempt_pass(self) -> int:
        """One-shot preempt-and-requeue of every occupied slot, run by
        the loop at its safe point (no dispatch in flight, pending chunk
        drained — the same invariant :meth:`_maybe_preempt` relies on).
        Requeued work resumes front-of-class and completes during the
        drain wait; the preempt/resume round-trip is what makes a
        drained generation byte-identical to an undisturbed one."""
        requeued = 0
        # requests stashed awaiting a T2 prefix hydration rejoin the
        # queue NOW (cold compute if their blobs never landed): a drain
        # must serve or shed every accepted request, and a stash that
        # outlives the loop would strand its future. Reversed: each
        # requeues at the FRONT, so newest-first keeps arrival order.
        for request, _deadline, _digests in reversed(self._prefix_hydrating):
            if request.future.done():
                continue
            self._journey(request, "hydrate-done", timeout=True, drain=True)
            self.scheduler.requeue_front(request)
            requeued += 1
        self._prefix_hydrating = []
        for slot_id, slot in enumerate(self.slots):
            request = slot.request
            if request is None or request.future.done():
                continue
            self._preempt_slot(slot_id, reason="drain")
            requeued += 1
        return requeued

    def _drain_section(self) -> dict[str, Any]:
        """The ``stats()["drain"]`` section: final report once the drain
        finished, live counters while it runs."""
        out: dict[str, Any] = {"draining": self._draining}
        if self._drain_report is not None:
            out.update(self._drain_report)
        elif self._draining:
            out.update(
                {
                    "requeued": self._drain_requeued,
                    "completed": (
                        self.completed_requests - self._drain_base_completed
                    ),
                    "shed": self._drain_shed,
                }
            )
        return out

    # ------------------------------------------------------------------
    # KV handoff plane: disaggregated prefill/decode pools (docs/DISAGG.md)
    # ------------------------------------------------------------------

    def kv_fingerprint(self) -> dict[str, Any]:
        """The layout facts a KV handoff must agree on end to end —
        serialized into every export header and checked on import
        (mismatch → :class:`~langstream_tpu.serving.kvtransfer.
        LayoutMismatch` → HTTP 409). Pure attribute reads (POOL701)."""
        mc = self.model_config
        return {
            "model": self.config.model,
            "dtype": str(np.dtype(mc.dtype).name),
            "kv-quantize": self.config.kv_quantize or None,
            "kv-block-size": self.config.kv_block_size,
            "layers": mc.layers,
            "kv-heads": mc.kv_heads,
            "head-dim": mc.head_dim,
            "max-seq-len": mc.max_seq_len,
        }

    def adapter_fingerprint(self) -> dict[str, Any]:
        """The facts a LoRA adapter blob must agree on before its
        factors may touch the device A/B buffers — serialized into
        every T2 wire header and checked on fetch (mismatch → the blob
        is refused AND deleted, never installed). Pure attribute reads
        (POOL701)."""
        mc = self.model_config
        spec = self.config.adapter_store
        return {
            "model": self.config.model,
            "dtype": str(np.dtype(mc.dtype).name),
            "rank": spec.rank if spec is not None else 0,
            "layers": mc.layers,
            "hidden": mc.hidden,
            "heads": mc.heads,
            "kv-heads": mc.kv_heads,
            "head-dim": mc.head_dim,
        }

    def _adapter_entry_bytes(self) -> int:
        """Device bytes one resident adapter row occupies across the
        eight stacked factor buffers (all layers, model dtype)."""
        mc = self.model_config
        r = self.config.adapter_store.rank
        q_dim = mc.heads * mc.head_dim
        kv_dim = mc.kv_heads * mc.head_dim
        per_layer = (
            (mc.hidden * r + r * q_dim)        # wq_a / wq_b
            + (mc.hidden * r + r * kv_dim)     # wk_a / wk_b
            + (mc.hidden * r + r * kv_dim)     # wv_a / wv_b
            + (q_dim * r + r * mc.hidden)      # wo_a / wo_b
        )
        return mc.layers * per_layer * np.dtype(mc.dtype).itemsize

    def kv_transfer_section(self) -> dict[str, Any]:
        """The ``stats()["kvtransfer"]`` / flight-summary section:
        transfer counters + in-transit posture. Wait-free (POOL701):
        attribute reads and ``len`` only."""
        return {
            "role": self._pool_role,
            "exports": self.kv_exports_total,
            "exports_evicted": self.kv_exports_evicted,
            "imports": self.kv_imports_total,
            "import_sheds": self.kv_import_sheds,
            "export_bytes": self.kv_export_bytes,
            "import_bytes": self.kv_import_bytes,
            "pending_exports": len(self._exports),
            "pending_imports": len(self._pending_imports),
            "in_transit_bytes": self._kv_in_transit_bytes,
            # cross-replica failure domain (serving/handoff.py): chainer
            # re-offers/fallbacks and handoffs awaiting the decode
            # side's answer (their journal entries stay live)
            "retries": self.handoff_retries,
            "fallbacks": self.handoff_fallbacks,
            "unsettled_handoffs": len(self._handoff_journal),
        }

    def handoff_settled(self, request_id: str) -> None:
        """The decode side ANSWERED this handoff — a completed result or
        a terminal refusal (409/504, which the decode side recorded) —
        so the prefill-side journal entry retires. Until this call the
        entry stays live: a decode pod dying mid-handoff leaves it to
        replay as a fresh request on restart (docs/RESILIENCE.md).
        Wait-free: a dict pop + the journal's deque handoff."""
        journal_id = self._handoff_journal.pop(request_id, None)
        if journal_id is not None and self.journal is not None:
            self.journal.retire(journal_id)
            if self._m_journal_depth is not None:
                self._m_journal_depth(self.journal.depth())

    def note_handoff_retry(
        self, request_id: str, replica: str | None = None,
        attempt: int = 0, reason: str = "",
    ) -> None:
        """One chainer re-offer (serving/handoff.py): counter + flight
        event, so a retry storm is visible in the ring and engine_top's
        ``--analyze`` can flag it. Wait-free."""
        self.handoff_retries += 1
        if self._m_handoff_retries is not None:
            self._m_handoff_retries(1)
        self.flight.event(
            "handoff-retry", request=request_id, replica=replica,
            attempt=attempt, reason=str(reason)[:160],
        )

    def note_handoff_fallback(self, request_id: str, attempts: int = 0) -> None:
        """The chainer gave up on the decode pool and is importing the
        payload locally: counter + flight event (never invisible — a
        fallback means this prefill replica now pays a decode)."""
        self.handoff_fallbacks += 1
        if self._m_handoff_fallbacks is not None:
            self._m_handoff_fallbacks(1)
        self.flight.event(
            "handoff-fallback", request=request_id, attempts=attempts,
        )

    def note_breaker_open(self, open_replicas: int = 0) -> None:
        """Mirror of the router's breaker pressure: a lazily-registered
        gauge (first breaker event only — a fleet that never trips one
        keeps the pre-existing scrape surface)."""
        if self._m_breaker_open is None:
            self._m_breaker_open = self._reporter.gauge(
                "breaker_open_replicas",
                "replicas currently excluded from routing by an OPEN "
                "circuit breaker (gateway/router.py; docs/RESILIENCE.md)",
            )
        self._m_breaker_open(open_replicas)

    def note_fault_fired(self, **detail: Any) -> None:
        """Loop-side spelling of the ``fault-injected`` evidence event
        for the NETWORK seams (the chainer runs on the event loop, so
        no deque handoff is needed — cause still lands in the ring
        before the retry/fallback it triggers)."""
        self.flight.event("fault-injected", **detail)

    def take_export_entry(
        self, request_id: str, settle: bool = True
    ) -> dict[str, Any] | None:
        """Pop one export entry (payload + the stashed trace/journey
        coordinates — what the pod ``GET /kv/export/{request}`` handler
        needs to echo the trace header). Wait-free (POOL701): dict pops
        and journey-ledger appends only; the payload leaves the
        in-transit ledger here and the pickup lands as an
        ``export-taken`` journey edge (the handoff-wait/transfer split).

        ``settle`` (default True — the PULL model): the pickup is the
        last event this engine will ever see for the handoff, so the
        journal entry retires here, exactly as it did pre-chainer. The
        chainer passes ``settle=False``: it stays in the loop and
        settles on the decode side's actual answer, so a decode pod
        dying after pickup still replays from this journal."""
        if self._faults is not None:
            # http-export network fault seam (serving/faults.py): the
            # pickup "never arrives" — drop answers None (the pod maps
            # it to 404) WITHOUT popping, so a retried pickup can still
            # succeed once the fault disarms; the journal keeps the
            # entry live either way (chaos drills only)
            action = self._faults.fire("http-export")
            if action is not None:
                self._fault_fired.append(
                    {"site": "http-export", "shape": action.shape,
                     "fire": action.seq, "hang_ms": None}
                )
                if action.shape == "delay-ms":
                    # injected pickup stall (tests/chaos only; unarmed
                    # engines never reach this branch)
                    time.sleep(action.hang_ms / 1000.0)
                elif action.shape == "error":
                    raise RuntimeError(action.message)
                else:
                    return None
        entry = self._exports.pop(request_id, None)
        if entry is None:
            return None
        self._kv_in_transit_bytes -= entry["bytes"]
        if settle:
            self.handoff_settled(request_id)
        JOURNEYS.record(
            entry.get("journey"), "export-taken",
            handoff=request_id, bytes=entry["bytes"],
        )
        return entry

    def take_export(self, request_id: str) -> bytes | None:
        """Pop one serialized handoff payload (bytes-only spelling of
        :meth:`take_export_entry` — the tests' and chainers' surface)."""
        entry = self.take_export_entry(request_id)
        return None if entry is None else entry["payload"]

    async def _export_ready_slots(self, loop) -> None:
        """Prefill-pool half of the handoff: every slot whose prefill
        completed (it would join decode on a combined engine) exports
        its KV blocks + request snapshot and releases, so the slot and
        its reservation immediately serve the next prompt. Runs at the
        loop's safe point — no dispatch in flight."""
        for slot_id, slot in enumerate(self.slots):
            request = slot.request
            if request is None or slot.prefilling:
                continue
            if request.imported:
                # a local-fallback import (serving/handoff.py): this
                # request already WENT through the handoff plane and
                # every decode replica refused it — it decodes here,
                # on the combined path, and must never re-export
                continue
            if request.future.cancelled():
                # caller gave up between prefill and export: nothing to
                # hand off — free the slot + reservation. The tenant
                # post-debit still happens (same rule as _flush_emits:
                # cancelled requests' tokens burned engine capacity)
                slot.request = None
                slot.prefill_done = 0
                self._lengths[slot_id] = 0
                self._adapter_release(request)
                if self._ad_rows is not None:
                    self._ad_rows[slot_id] = 0
                if self.block_mgr is not None:
                    self.block_mgr.release(slot_id)
                self.scheduler.on_finished(request)
                self._journey(request, "cancelled")
                continue
            if request.future.done():
                continue
            await self._export_slot(loop, slot_id, request)

    async def _export_slot(self, loop, slot_id: int, request) -> None:
        """Export one finished-prefill slot: gather its pool rows (the
        one device sync lives in kvtransfer's sanctioned ``_fetch_rows``
        stage, on the dispatch thread, timed), serialize, stash the
        payload for pickup, release the slot, and resolve the caller's
        future with the handoff ticket."""
        from langstream_tpu.serving import kvtransfer

        t_start = time.monotonic()
        rows = int(self._lengths[slot_id])
        nrb = self._read_blocks_for(max(rows, 1))
        blocks_live = self.block_mgr.blocks_needed(max(rows, 1))
        table_row = self.block_mgr.tables[slot_id].copy()

        def _run():
            gathered_k, gathered_v = kvtransfer.gather_slot(
                self.cache_k, self.cache_v, table_row, nrb
            )
            return kvtransfer._fetch_rows(gathered_k, gathered_v, rows)

        arrays, device_s = await loop.run_in_executor(self._executor, _run)
        self._export_seq += 1
        rid = f"{self.config.model}-{self._export_seq:08d}"
        now = time.monotonic()
        first = request.first_token_time or now
        admit = request.admit_time or first
        timings = {
            "queue_wait": admit - request.enqueue_time,
            "prefill": first - admit,
            "ttft": first - request.enqueue_time,
        }
        header = {
            "fingerprint": self.kv_fingerprint(),
            "request": rid,
            # trace continuity (docs/OBSERVABILITY.md "Request journey
            # plane"): the decode pool parents its kv-import/decode spans
            # under the prefill-side trace, and its journey edges land in
            # the SAME per-request ledger — one trace_id end to end
            "trace": (
                request.trace.to_header()
                if request.trace is not None
                else None
            ),
            "journey": request.journey_id,
            "prompt-digest": kvtransfer.prompt_digest(request.prompt_tokens),
            "prompt-tokens": list(request.prompt_tokens),
            "generated": list(request.generated),
            "logprobs": list(request.logprobs),
            "current-token": int(self._current[slot_id]),
            "kv-rows": rows,
            "max-tokens": request.max_tokens,
            "temperature": request.temperature,
            "top-k": request.top_k,
            "top-p": request.top_p,
            "presence-penalty": request.presence_penalty,
            "frequency-penalty": request.frequency_penalty,
            "stop": list(request.stop),
            "tenant": request.tenant,
            "priority": request.priority,
            # the end-to-end deadline rides the wire beside the trace:
            # the decode pool enforces the SAME budget the gateway
            # stamped (docs/RESILIENCE.md)
            "deadline": request.deadline,
            "timings": {k: round(v, 6) for k, v in timings.items()},
        }
        payload = kvtransfer.serialize_handoff(header, arrays)
        # release BEFORE stashing: the slot serves the next prompt now;
        # published prefix blocks stay cached (the cache holds its refs)
        slot = self.slots[slot_id]
        slot.request = None
        slot.prefilling = False
        slot.prefill_done = 0
        self._lengths[slot_id] = 0
        self._adapter_release(request)
        if self._ad_rows is not None:
            self._ad_rows[slot_id] = 0
        self.block_mgr.release(slot_id)
        if not request.warmup:
            self._exports[rid] = {
                "payload": payload,
                "bytes": len(payload),
                "blocks": blocks_live,
                "m_s": now,
                # stashed so the pod's /kv/export pickup can echo the
                # trace header and close the journey's handoff-wait edge
                # without re-parsing the payload header
                "trace": header["trace"],
                "journey": request.journey_id,
                # the chainer derives every offer's socket timeout from
                # this (serving/handoff.py socket_timeout_s)
                "deadline": request.deadline,
            }
            self._kv_in_transit_bytes += len(payload)
            while len(self._exports) > self._export_cap:
                evicted_rid, evicted = self._exports.popitem(last=False)
                self._kv_in_transit_bytes -= evicted["bytes"]
                # an evicted export is a LOST handoff (its blocks were
                # released at export time): the decode pool's pickup
                # will 404 and the caller must re-prefill — loud by
                # contract, the handoff cost is never invisible
                self.kv_exports_evicted += 1
                self.flight.event(
                    "kv-export-dropped",
                    request=evicted_rid,
                    bytes=evicted["bytes"],
                    age_s=round(now - evicted["m_s"], 3),
                    cap=self._export_cap,
                )
            self.kv_exports_total += 1
            self.kv_export_bytes += len(payload)
            self.request_timings.append(
                {**{k: round(v, 6) for k, v in timings.items()},
                 "decode": 0.0,
                 "tokens": float(len(request.generated)),
                 "handoff": 1.0}
            )
            # exemplar: traced requests stamp their journey id on the
            # TTFT bucket (None for untraced — the scrape stays pinned)
            self._m_ttft_hist(
                timings["ttft"],
                request.journey_id if request.trace is not None else None,
            )
            self._m_queue_wait_hist(timings["queue_wait"])
            self._slo_record("availability", True)
            self._slo_record_latency("ttft", timings["ttft"])
            self._slo_record_latency("queue-wait", timings["queue_wait"])
        if self._m_kv_export_hist is not None:
            self._m_kv_export_hist(
                time.monotonic() - t_start,
                request.journey_id if request.trace is not None else None,
            )
        if self._m_kv_export_bytes is not None and not request.warmup:
            self._m_kv_export_bytes(len(payload))
        self.flight.event(
            "kv-export",
            request=rid,
            bytes=len(payload),
            blocks=blocks_live,
            rows=rows,
            ms=round((time.monotonic() - t_start) * 1000.0, 3),
            device_ms=round(device_s * 1000.0, 3),
            warmup=request.warmup,
        )
        self._journey(
            request, "export", handoff=rid, bytes=len(payload), rows=rows,
            ms=round((time.monotonic() - t_start) * 1000.0, 3),
            device_ms=round(device_s * 1000.0, 3),
            model=self.config.model, role=self._pool_role,
        )
        if request.trace is not None and not request.warmup:
            # a handoff request never reaches _flush_emits' finish path,
            # so its prefill-side phase spans materialize HERE — the
            # trace the decode pool's kv-import/decode spans join
            svc = f"engine:{self.config.model}"
            record_span("engine.queue", svc, request.trace,
                        request.enqueue_time, admit)
            record_span("engine.prefill", svc, request.trace, admit, first,
                        attributes={
                            "prompt-tokens": len(request.prompt_tokens)
                        })
            record_span("engine.kv-export", svc, request.trace, t_start,
                        time.monotonic(),
                        attributes={"bytes": len(payload), "rows": rows})
        self.scheduler.on_finished(request)
        self.completed_requests += 1
        # the handoff is NOT this request's end of life for the journal:
        # the decode side can still die before completion, and retiring
        # here made that loss invisible (the PR 15 satellite fix). The
        # entry stays live, keyed under the handoff id, until the
        # chainer confirms the decode side ANSWERED (handoff_settled) —
        # a crash anywhere in between replays the request as fresh work
        # from the prefill-side journal. Bounded: overflow drops the
        # MAPPING loudly (replay-over-loss — the entry stays live and
        # the journal's own bound is the final backstop).
        if self.journal is not None and not request.warmup:
            self._handoff_journal[rid] = request.journey_id
            while len(self._handoff_journal) > 4 * self._export_cap:
                old_rid, _old_jid = self._handoff_journal.popitem(last=False)
                self.flight.event("handoff-settle-evict", request=old_rid)
        if not request.future.done():
            request.future.set_result(
                {
                    "handoff": rid,
                    "tokens": list(request.generated),
                    "text": self.tokenizer.decode(request.generated),
                    "logprobs": list(request.logprobs),
                    "num_prompt_tokens": len(request.prompt_tokens),
                    "num_completion_tokens": len(request.generated),
                    "ttft": timings["ttft"],
                    "queue_wait": timings["queue_wait"],
                    "prefill": timings["prefill"],
                    "finish_reason": "handoff",
                }
            )

    async def import_handoff(
        self,
        payload: bytes,
        header: dict[str, Any] | None = None,
        trace_header: str | None = None,
        deadline: float | None = None,
        local_fallback: bool = False,
    ) -> dict[str, Any]:
        """Decode-pool half of the handoff: admit a request whose KV
        state arrived over the wire — blocks allocate through the
        BlockManager, rows scatter back via ``write_rows``, and the
        request joins the decode batch directly (prefill skipped; the
        ``request_timings`` entry carries ``imported`` so the skip is
        assertable). The wire header's ``trace``/``journey`` (falling
        back to ``trace_header``, the pod's ``langstream-trace`` request
        header) join this engine's spans and journey edges to the
        prefill-side trace — one trace_id end to end. Raises
        :class:`~langstream_tpu.serving.kvtransfer.LayoutMismatch` on a
        wire/fingerprint mismatch (pod → 409) and :class:`RateLimited`
        when the pool cannot take it right now (pod → 503 +
        Retry-After; the router retries the next decode replica)."""
        from langstream_tpu.serving import kvtransfer

        if self._stop:
            raise RuntimeError(
                "serving engine is stopped (closed or lockstep group broken)"
            )
        if self._pool_role == "prefill" and not local_fallback:
            # local_fallback is the chainer's escape hatch (serving/
            # handoff.py): when every decode replica is dead/held/
            # refusing, the prefill engine imports its OWN payload and
            # the request rejoins the combined decode path — the
            # serialized snapshot is the complete state, so the result
            # is byte-identical to the disaggregated path
            raise kvtransfer.LayoutMismatch(
                "prefill-role engine does not accept KV imports"
            )
        if self.block_mgr is None:
            raise kvtransfer.LayoutMismatch(
                "kv-layout=dense engine cannot accept a paged KV handoff"
            )
        header, arrays = kvtransfer.deserialize_handoff(payload, header)
        kvtransfer.check_fingerprint(
            self.kv_fingerprint(), header.get("fingerprint") or {}
        )
        if self._draining:
            raise RateLimited(
                "draining", 1.0,
                "engine is draining; retry another decode replica",
            )
        prompt = [int(t) for t in header.get("prompt-tokens") or []]
        generated = [int(t) for t in header.get("generated") or []]
        rows = int(header.get("kv-rows") or 0)
        max_tokens = int(header.get("max-tokens") or 0)
        if rows < 1 or rows >= self.model_config.max_seq_len:
            raise kvtransfer.LayoutMismatch(
                f"handoff kv-rows {rows} outside (0, "
                f"{self.model_config.max_seq_len})"
            )
        for name, arr in arrays.items():
            if arr.shape[0] != self.model_config.layers or arr.shape[1] < rows:
                raise kvtransfer.LayoutMismatch(
                    f"handoff array {name!r} shape {arr.shape} does not "
                    f"cover {self.model_config.layers} layers x {rows} rows"
                )
        if not self.block_mgr.fits_ever(len(prompt) + max_tokens + 1):
            raise ValueError(
                f"imported request needs {len(prompt) + max_tokens + 1} "
                f"tokens of KV, more than this pool can ever hold"
            )
        # trace continuity: the wire header's context first (the prefill
        # engine stamped it), then the pod HTTP header (a chainer that
        # forwarded langstream-trace without a trace-aware payload)
        trace = kvtransfer.trace_context(header)
        if trace is None:
            trace = TraceContext.parse(trace_header)
        request = _Request(
            prompt_tokens=prompt,
            max_tokens=max_tokens,
            temperature=float(header.get("temperature") or 0.0),
            top_k=int(header.get("top-k") or 0),
            top_p=float(header.get("top-p") or 1.0),
            on_token=None,
            future=asyncio.get_running_loop().create_future(),
            loop=asyncio.get_running_loop(),
            enqueue_time=time.monotonic(),
            presence_penalty=float(header.get("presence-penalty") or 0.0),
            frequency_penalty=float(header.get("frequency-penalty") or 0.0),
            generated=generated,
            logprobs=[float(x) for x in header.get("logprobs") or []],
            stop=_normalize_stop(header.get("stop")),
            tenant=str(header.get("tenant") or ""),
            priority=normalize_priority(header.get("priority")),
            imported=True,
            trace=trace,
            # deadline continuity: the wire header's stamp (the prefill
            # side carried the ORIGINAL budget) wins over the pod HTTP
            # header's copy — both are the same epoch clock, and
            # parse_deadline only ever returns None or a positive stamp
            deadline=(
                parse_deadline(header.get("deadline"))
                or parse_deadline(deadline)
            ),
        )
        request.import_base_tokens = len(generated)
        request.journey_id = kvtransfer.journey_id(header) or (
            trace.trace_id if trace is not None else fresh_trace_id()
        )
        self._journey(
            request, "import-received", bytes=len(payload),
            handoff=header.get("request"),
            model=self.config.model, role=self._pool_role,
        )
        if (
            request.deadline is not None
            and remaining_s(request.deadline) <= 0.0
        ):
            # expired in transit: refuse 504-shaped BEFORE queueing the
            # scatter (the pod maps this to HTTP 504; an overrun this
            # early must never burn blocks/device work). After the
            # journey id is bound, so the refusal lands as a terminal
            # edge in the request's ledger instead of vanishing.
            raise self._note_deadline_shed(request, "kv-import", 0.0)
        self._pending_imports.append(
            (header, arrays, request, len(payload))
        )
        self._ensure_loop()
        self._wake.set()
        return await request.future

    @staticmethod
    def _resource_exhausted(error: BaseException) -> bool:
        """True for a device allocator failure or the BlockManager's
        pool-exhaustion RuntimeError — the refusals ROADMAP item 5 wants
        adapted to, not died from. Covers every jaxlib allocator
        spelling observed across backends/versions (the canonical
        ``RESOURCE_EXHAUSTED:`` status prefix, the BFC allocator's
        ``Out of memory while trying to allocate``, the PJRT client's
        ``Failed to allocate request``, and TFRT's ``Allocation ...
        exceeds`` phrasing) — a spelling this misses dies instead of
        adapting, so each one is pinned by a unit test."""
        text = f"{type(error).__name__}: {error}"
        return bool(_RESOURCE_EXHAUSTED_RE.search(text))

    def _fault(self, site: str) -> None:
        """Fault-injection seam check (serving/faults.py — tests/chaos
        drills only). Production engines carry ``_faults = None``, so
        this is ONE attribute test on the hot path. A fired fault is
        stashed on the ``_fault_fired`` handoff deque (the seams span
        the loop AND the dispatch thread; the flight ring's counters are
        loop-side state, so emission happens at the loop's safe point —
        chaos assertions read the emitted ``fault-injected`` events,
        never guess), then the action runs: a synthetic
        RESOURCE_EXHAUSTED raise, or a stall of whichever thread hit
        the seam."""
        faults = self._faults
        if faults is None:
            return
        action = faults.fire(site)
        if action is None:
            return
        self._fault_fired.append(
            {
                "site": site,
                "shape": action.shape,
                "fire": action.seq,
                "hang_ms": (
                    action.hang_ms if action.shape == "hang" else None
                ),
            }
        )
        if action.shape == "hang":
            # the r03 shape: the dispatch goes quiet. The watchdog
            # heartbeat stops while work stays pending, so /healthz
            # must flip WEDGED until the stall resolves.
            time.sleep(action.hang_ms / 1000.0)
            return
        raise InjectedFault(site, action.message)

    def _drain_fault_events(self) -> None:
        """Emit stashed ``fault-injected`` events at the loop's safe
        point (and before any ``pool-shrink`` evidence, so the ring
        reads cause-then-effect)."""
        while self._fault_fired:
            self.flight.event("fault-injected", **self._fault_fired.popleft())

    def _note_deadline_shed(
        self, request, where: str, left: float, estimate: float = 0.0
    ) -> DeadlineExceeded:
        """Record one deadline refusal (counter + lazy metric + a
        ``deadline-exceeded`` flight event with the budget evidence) and
        build the 504-shaped error the caller raises/sets. The metric
        registers on FIRST use so a deadline-less engine's scrape
        surface stays byte-identical (the default-config pin)."""
        self.deadline_sheds += 1
        if self._m_deadline_shed is None:
            self._m_deadline_shed = self._reporter.counter(
                "deadline_shed_total",
                "requests refused because the remaining langstream-"
                "deadline budget could not cover the admission estimate "
                "(504-shaped; docs/RESILIENCE.md)",
            )
        self._m_deadline_shed(1)
        self.flight.event(
            "deadline-exceeded",
            where=where,
            remaining_s=round(left, 6),
            estimate_s=round(estimate, 6),
            tenant=request.tenant,
            priority=request.priority,
        )
        self._journey(
            request, "deadline-exceeded", where=where,
            remaining_s=round(left, 6),
        )
        if not request.warmup:
            self._slo_record("shed-rate", False)
        return DeadlineExceeded(
            f"deadline exceeded at {where}: {left:.3f}s of budget left, "
            f"admission estimate {estimate:.3f}s",
            overrun_s=max(0.0, estimate - left),
        )

    def _admit_estimate_s(self) -> float:
        """The admission-time cost estimate a deadline must still cover:
        the median recent prefill time (enqueue-side work the engine is
        ABOUT to spend on the device). No history → 0.0, so a fresh
        engine only sheds already-expired budgets — the estimate
        tightens as evidence accumulates, never guesses ahead of it."""
        vals = sorted(
            t.get("prefill", 0.0)
            for t in list(self.request_timings)[-32:]
            if not t.get("imported")
        )
        return vals[len(vals) // 2] if vals else 0.0

    def _shed_import(self, request, reason: str, detail: str) -> None:
        """Refuse one pending import explicitly: RateLimited with a retry
        hint, so the pod handler answers 503 + Retry-After and the router
        retries the next decode replica (never a silent loss)."""
        self.kv_import_sheds += 1
        self.flight.event(
            "shed", reason=reason, tenant=request.tenant,
            priority=request.priority, retry_after_s=1.0, imported=True,
        )
        self._journey(request, "shed", reason=reason, imported=True)
        if not request.future.done():
            request.future.set_exception(RateLimited(reason, 1.0, detail))

    async def _apply_imports(self, loop) -> None:
        """Admit every queued KV import at the loop's safe point. Each
        import needs a free slot and a worst-case block reservation —
        exactly admission's contract; refusals are explicit 503-shaped
        sheds (the decode pool is saturated and the router should spread
        the handoff), and a RESOURCE_EXHAUSTED during block allocation
        sheds instead of failing the request."""
        from langstream_tpu.serving import kvtransfer

        while self._pending_imports:
            header, arrays, request, nbytes = self._pending_imports.popleft()
            if request.future.done():
                continue  # caller gave up while queued
            if request.deadline is not None:
                # the deadline rode the wire header: an import whose
                # budget died in transit is refused 504-shaped before
                # any block allocation or scatter (the pod handler maps
                # DeadlineExceeded to HTTP 504; the chainer treats it
                # as terminal — no sibling replica has more budget)
                left = remaining_s(request.deadline)
                if left <= 0.0:
                    err = self._note_deadline_shed(
                        request, "kv-import", left
                    )
                    if not request.future.done():
                        request.future.set_exception(err)
                    continue
            if self._draining:
                self._shed_import(
                    request, "draining",
                    "engine is draining; retry another decode replica",
                )
                continue
            free = next(
                (i for i, s in enumerate(self.slots) if s.free), None
            )
            total = len(request.prompt_tokens) + request.max_tokens + 1
            if free is None:
                self._shed_import(
                    request, "no-free-slot",
                    "decode pool has no free slot; retry another replica",
                )
                continue
            if not self.block_mgr.can_admit(total):
                self._shed_import(
                    request, "kv-import-capacity",
                    "decode pool cannot reserve the request's worst-case "
                    "KV blocks; retry another replica",
                )
                continue
            rows = int(header["kv-rows"])
            t_start = time.monotonic()
            try:
                self.block_mgr.admit(free, total)
                self.block_mgr.ensure_capacity(free, rows)
            except RuntimeError as e:
                # the first slice of the RESOURCE_EXHAUSTED adaptation
                # story (ROADMAP item 5): allocator refusal is a shed,
                # never a request failure
                self.block_mgr.release(free)
                if self._resource_exhausted(e):
                    self._shed_import(
                        request, "kv-import-capacity",
                        f"block allocation failed ({e}); retry another "
                        f"replica",
                    )
                    continue
                raise
            table_row = self.block_mgr.tables[free].copy()
            padded = _bucket(rows, hi=self.model_config.max_seq_len)

            def _run(arrays=arrays, table_row=table_row, rows=rows,
                     padded=padded):
                self._fault("scatter")
                out_k, out_v = kvtransfer.scatter_slot(
                    self.cache_k, self.cache_v, arrays, table_row, rows,
                    padded,
                )
                # donated pools re-bound on the dispatch thread — the
                # same side every dispatch closure reads them (RACE801)
                self.cache_k, self.cache_v = out_k, out_v
                t_dev = time.monotonic()
                # graftcheck: disable=JAX104 the one per-import sync, off-loop and timed
                jax.block_until_ready((out_k, out_v))
                return time.monotonic() - t_dev

            try:
                device_s = await loop.run_in_executor(self._executor, _run)
            except Exception as e:
                self.block_mgr.release(free)
                if self._resource_exhausted(e):
                    self._shed_import(
                        request, "kv-import-oom",
                        f"device allocation failed mid-scatter ({e}); "
                        f"retry another replica",
                    )
                    continue
                raise
            slot = self.slots[free]
            slot.request = request
            slot.prefilling = False
            slot.prefill_done = 0
            self._lengths[free] = rows
            self._current[free] = int(header["current-token"])
            self._temps[free] = request.temperature
            self._topks[free] = request.top_k
            self._topps[free] = request.top_p
            self._pres[free] = request.presence_penalty
            self._freq[free] = request.frequency_penalty
            now = time.monotonic()
            # prefill is SKIPPED: admit == first-token boundary (the
            # handoff's first token was produced on the prefill pool)
            request.admit_time = now
            request.first_token_time = now
            self.kv_imports_total += 1
            self.kv_import_bytes += nbytes
            if self._m_kv_import_hist is not None:
                self._m_kv_import_hist(
                    time.monotonic() - t_start,
                    request.journey_id
                    if request.trace is not None
                    else None,
                )
            if self._m_kv_import_bytes is not None:
                self._m_kv_import_bytes(nbytes)
            self.flight.event(
                "kv-import",
                request=header.get("request"),
                digest=header.get("prompt-digest"),
                bytes=nbytes,
                blocks=self.block_mgr.blocks_needed(max(rows, 1)),
                rows=rows,
                ms=round((time.monotonic() - t_start) * 1000.0, 3),
                device_ms=round(device_s * 1000.0, 3),
            )
            self._journey(
                request, "import", bytes=nbytes, rows=rows,
                ms=round((time.monotonic() - t_start) * 1000.0, 3),
                device_ms=round(device_s * 1000.0, 3),
                model=self.config.model, role=self._pool_role,
            )
            if request.trace is not None:
                # the decode-pool spans join the prefill-side trace: the
                # import (block admit + scatter) as its own child, the
                # decode phase via the usual completion-time spans
                record_span(
                    "engine.kv-import", f"engine:{self.config.model}",
                    request.trace, t_start, now,
                    attributes={"bytes": nbytes, "rows": rows},
                )

    # ------------------------------------------------------------------
    # engine loop
    # ------------------------------------------------------------------

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.ensure_future(self._run_loop())

    def _split_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def _has_prefilling(self) -> bool:
        return any(s.prefilling for s in self.slots)

    async def _run_loop(self) -> None:
        loop = asyncio.get_running_loop()
        # reset the flight timeline: the loop starts lazily on the first
        # generate(), and the construction→first-request gap (an hour for
        # an idle deploy) must not be billed to the first sample as host
        # time — from here on the loop itself records every gap
        self.flight.mark()
        # fresh heartbeat at loop start: the wedge window measures from
        # here, not from engine construction
        self.watchdog.beat(self.scheduler.qsize())
        if self._journal_replay_pending:
            # crash-requeue (docs/RESILIENCE.md): the previous process
            # died with accepted work unfinished — replay it through the
            # QoS front-of-class resume path before any new admission
            self._replay_journal(loop)
        while not self._stop:
            try:
                if self._fault_fired:
                    # chaos-drill evidence first: injected faults land in
                    # the ring before whatever they caused this pass
                    self._drain_fault_events()
                if self._shrink_recover_at is not None:
                    # pool-shrink recovery probe: one quiet window with
                    # no further allocator failures restores one shrink
                    # quantum (wait-free check; docs/RESILIENCE.md)
                    self._shrink_step()
                if self.prefix_store is not None:
                    # tier bookkeeping first: hydrations that landed
                    # requeue at class front, so the admission passes
                    # below see them immediately (docs/PREFIX.md)
                    self._prefix_tier_step()
                if self.adapter_store is not None:
                    # adapter hydrations settle at the same safe point
                    # (requeue at class front or cold-refuse loudly —
                    # docs/ADAPTERS.md)
                    self._adapter_tier_step()
                if self._pending_imports:
                    # KV handoff imports land at the loop's safe point,
                    # exactly like admission: a free slot + a worst-case
                    # block reservation, then the wire rows scatter in
                    # and the request joins decode with NO prefill
                    # (docs/DISAGG.md)
                    await self._apply_imports(loop)
                if not self.scheduler.empty():
                    await self._admit(loop)
                # a pipelined burst may have left a decode chunk in
                # flight: drained only AFTER admission so the prefill
                # above dispatched under its device shadow, and BEFORE
                # preemption so a victim's slot state is settled when the
                # snapshot is taken
                await self._drain_pending(loop)
                if self._draining and not self._drain_pass_done:
                    # drain-before-terminate: one preempt-and-requeue
                    # sweep at the safe point (pending chunk settled);
                    # the requeued work re-admits below and finishes
                    # under drain()'s grace budget
                    self._drain_pass_done = True
                    self._drain_requeued += self._drain_preempt_pass()
                if not self.scheduler.empty():
                    # slots the drained chunk just freed are admission
                    # opportunities NOW, not one burst later
                    await self._admit(loop)
                    # QoS preemption: admission stalled on KV pressure
                    # with a higher-priority request waiting → preempt
                    # the policy-chosen victim (its blocks free NOW) and
                    # re-run admission so the waiter lands this pass
                    if self._maybe_preempt():
                        await self._admit(loop)
                if self.prefix_store is not None:
                    # T0 byte-budget demotions ride the same safe point
                    # (the pending chunk above is settled, so the gather
                    # reads stable pool contents)
                    await self._demote_prefix_blocks(loop)
                if self._has_prefilling():
                    # one bounded chunk per loop pass: long prefills make
                    # progress without stalling the decode bursts below
                    await self._advance_prefills(loop)
                if self._pool_role == "prefill":
                    # disaggregated prefill pool: every slot whose
                    # prefill just finished exports its KV blocks and
                    # releases instead of decoding — the decode pool
                    # picks the payload up over the pod HTTP plane
                    await self._export_ready_slots(loop)
                active = [
                    i
                    for i, s in enumerate(self.slots)
                    if not s.free and not s.prefilling
                ]
                self._m_active(len(active))
                self._m_queued(self.scheduler.qsize())
                if not active:
                    if self.scheduler.empty() and not self._has_prefilling():
                        self._wake.clear()
                        # a stashed hydration resolves on the hydrator
                        # thread, and a T0 cache over its byte budget
                        # has demotions to drain: poll tightly while
                        # either is pending so TTFT pays milliseconds
                        # (hydration) and spilled blocks reach the
                        # durable tier promptly instead of one bounded
                        # batch per idle second
                        idle_s = (
                            0.02
                            if self._prefix_hydrating
                            or self._adapter_hydrating
                            or self._prefix_demote_pending()
                            else 1.0
                        )
                        try:
                            await asyncio.wait_for(
                                self._wake.wait(), timeout=idle_s
                            )
                        except asyncio.TimeoutError:
                            pass
                        # the whole gap was engine idle time: record it so
                        # the flight timeline stays contiguous and the
                        # rollup's stall component is exact
                        self._flight_stall("queue-empty")
                    continue
                if (
                    self.config.speculative_drafts > 0
                    and self.block_mgr is not None
                    # measured-uplift auto-disable parks the engine on the
                    # plain pipelined loop until the retry window elapses
                    and not self._spec_auto_disabled
                    # greedy bursts use argmax acceptance; sampled bursts
                    # use rejection sampling against the filtered target
                    # distribution (distribution-exact). Penalties alone
                    # stay on plain decode: they change the distribution
                    # per EMITTED token and the verify step has no counts.
                    and not (
                        (self._pres[active] != 0).any()
                        or (self._freq[active] != 0).any()
                    )
                ):
                    await self._speculative_burst(loop, active)
                else:
                    await self._decode_burst(loop, active)
            except Exception as e:  # device/runtime error: fail in-flight work,
                # free the slots, keep serving (callers see the exception)
                if (
                    self._lockstep is None
                    and self._resource_exhausted(e)
                    and self._maybe_pool_shrink(e)
                ):
                    # degrade-don't-die (docs/RESILIENCE.md): device
                    # memory pressure is a load signal. The budget
                    # shrank, the victims requeued front-of-class, and
                    # the loop keeps serving — nothing was failed.
                    continue
                log.exception("serving engine step failed")
                from langstream_tpu.serving.lockstep import LockstepBroken

                if self._lockstep is not None and not isinstance(e, LockstepBroken):
                    # leading a multi-host group: ANY step failure is
                    # group-fatal — followers may have replayed collectives
                    # this process aborted mid-step (e.g. the coordination
                    # service poisoned a pending collective after a member
                    # died), so surviving state is unknowable. Wrap so
                    # callers see one loud type either way.
                    e = LockstepBroken(
                        f"multi-host step failed: {type(e).__name__}: {e}"
                    )
                self._fail_inflight(e)
                if isinstance(e, LockstepBroken):
                    # a lost follower is unrecoverable for this process
                    # group — stop serving so the slice restarts as a unit
                    log.error("lockstep group broken; engine stops serving")
                    self.flight.event(
                        "lockstep-divergence", error=str(e)[:200]
                    )
                    self._stop = True
        if self._pending_chunk is not None:
            # a stop that lands between a pipelined burst and the next
            # loop pass leaves one dispatched chunk in flight: drain it so
            # the dispatch/fetch ledger closes 1:1 (the one-fetch-per-
            # chunk canary) and the flight timeline stays contiguous —
            # finished slots' tokens are identity-filtered as always
            await self._drain_pending(loop)

    def _fail_inflight(self, error: Exception) -> None:
        self.flight.event(
            "preempt",
            error=f"{type(error).__name__}: {error}"[:200],
            inflight=sum(1 for s in self.slots if not s.free),
        )
        # a pending pipelined chunk belongs to the failed dispatch stream:
        # drop it (every slot below is failed + released uniformly anyway)
        self._pending_chunk = None
        self._defer_release = False
        self._deferred_releases.clear()
        # stale inline-adaptation counters must not leak into a later,
        # unrelated shrink pass's evidence
        self._shrink_inline_preempted = 0
        self._shrink_inline_shed = 0
        error_text = f"{type(error).__name__}: {error}"[:160]
        for slot_id, slot in enumerate(self.slots):
            request = slot.request
            if request is not None:
                if not request.future.done():
                    request.future.set_exception(error)
                    self._journey(request, "fail", error=error_text)
                    if not request.warmup:
                        self._slo_record("availability", False)
                # an explicitly failed request was ANSWERED — retire its
                # journal entry so a restart never replays served errors
                self._journal_retire(request)
                self._adapter_release(request)
            slot.request = None
            slot.prefilling = False
            slot.prefill_done = 0
            if self.block_mgr is not None:
                self.block_mgr.release(slot_id)
        self._lengths[:] = 0
        if self._ad_rows is not None:
            self._ad_rows[:] = 0
        for request in self.scheduler.drain():
            if not request.future.done():
                request.future.set_exception(error)
                self._journey(request, "fail", error=error_text)
                if not request.warmup:
                    self._slo_record("availability", False)
            self._journal_retire(request)
        for pending in list(self._pending_imports):
            request = pending[2]
            if not request.future.done():
                request.future.set_exception(error)
                self._journey(request, "fail", error=error_text)
        self._pending_imports.clear()
        for stashed in self._prefix_hydrating:
            request = stashed[0]
            if not request.future.done():
                request.future.set_exception(error)
                self._journey(request, "fail", error=error_text)
                if not request.warmup:
                    self._slo_record("availability", False)
            self._journal_retire(request)
        self._prefix_hydrating.clear()
        for stashed in self._adapter_hydrating:
            request = stashed[0]
            if not request.future.done():
                request.future.set_exception(error)
                self._journey(request, "fail", error=error_text)
                if not request.warmup:
                    self._slo_record("availability", False)
            self._journal_retire(request)
        self._adapter_hydrating.clear()
        self._pending_emits.clear()
        self._finished_requests.clear()

    def _journey(self, request: "_Request", kind: str, **detail: Any) -> None:
        """Append one lifecycle edge to the request's journey ledger
        (serving/journey.py). Wait-free appends on the dispatch path by
        OBS506's contract; warmup probes carry no journey id and record
        nothing."""
        if request.journey_id is not None:
            JOURNEYS.record(request.journey_id, kind, **detail)

    def _maybe_preempt(self) -> bool:
        """Preemptive load shedding under KV pressure: when admission is
        stalled on ``no-kv-blocks`` and the scheduler's cost model names
        a running victim (strictly lower class than the stalled head,
        preemptions left, more deadline slack than the waiter), preempt
        it so the waiter's blocks free immediately. Returns True when a
        slot was preempted (the caller re-runs admission). Runs at the
        loop's safe point — no dispatch is in flight."""
        if not self._qos_enabled or self.block_mgr is None:
            return False
        if self._admission_stall() != "no-kv-blocks":
            return False
        head = self.scheduler.peek()
        if head is None:
            return False
        running = [
            (i, s.request)
            for i, s in enumerate(self.slots)
            if s.request is not None and not s.prefilling
        ]
        victim = self.scheduler.preempt_candidate(head, running)
        if victim is None:
            return False
        self._preempt_slot(victim)
        return True

    def _preempt_slot(self, slot_id: int, reason: str = "no-kv-blocks") -> None:
        """Preempt one running request: its generated tokens + sampling
        params ARE the snapshot (greedy resume re-prefills
        ``context_tokens`` and continues bit-identically — see
        ``_Request.context_tokens``). Free the slot and its worst-case
        block reservation, then requeue at the front of its class so
        resume latency is bounded by the pressure, not the backlog.
        ``reason`` labels the flight event: ``no-kv-blocks`` (the QoS
        pressure path) or ``drain`` (drain-before-terminate)."""
        slot = self.slots[slot_id]
        request = slot.request
        now = time.monotonic()
        slot.request = None
        slot.prefilling = False
        slot.prefill_done = 0
        self._lengths[slot_id] = 0
        # drop the adapter pin across the preemption (the slot frees and
        # its row may evict); re-admission re-resolves — and may re-
        # hydrate, so the one-shot attempt flag resets too
        self._adapter_release(request)
        request.adapter_hydrate_attempted = False
        if self._ad_rows is not None:
            self._ad_rows[slot_id] = 0
        if self.block_mgr is not None:
            self.block_mgr.release(slot_id)
        request.preemptions += 1
        request.preempt_time = now
        self.scheduler.note_preempted(request)
        self.scheduler.requeue_front(request)
        if self._m_preempted is not None:
            self._m_preempted(1)
        if self._m_preempt_hist is not None and request.admit_time is not None:
            self._m_preempt_hist(now - request.admit_time)
        self.flight.event(
            "preempt",
            reason=reason,
            priority=request.priority,
            tenant=request.tenant,
            generated=len(request.generated),
        )
        self._journey(
            request, "preempt", reason=reason,
            generated=len(request.generated),
        )
        if request.trace is not None:
            record_span(
                "engine.preempt", f"engine:{self.config.model}",
                request.trace, now, now,
                attributes={"generated": len(request.generated)},
            )

    def _note_resume(self, request: "_Request") -> None:
        """A preempted request was just re-admitted: close the resume
        accounting (histogram + flight/trace events)."""
        if request.preempt_time is None:
            return
        now = time.monotonic()
        waited = now - request.preempt_time
        if self._m_resume_hist is not None:
            self._m_resume_hist(waited)
        self.flight.event(
            "resume",
            priority=request.priority,
            tenant=request.tenant,
            generated=len(request.generated),
            waited_ms=round(waited * 1000.0, 3),
        )
        self._journey(
            request, "resume", waited_ms=round(waited * 1000.0, 3),
            generated=len(request.generated),
        )
        if request.trace is not None:
            record_span(
                "engine.resume", f"engine:{self.config.model}",
                request.trace, request.preempt_time, now,
                attributes={"generated": len(request.generated)},
            )
        request.preempt_time = None

    # ------------------------------------------------------------------
    # device-survival plane: adaptive pool-shrink + crash-requeue
    # (docs/RESILIENCE.md)
    # ------------------------------------------------------------------

    def _shed_stranded(self, slot_id: int, error: Exception) -> None:
        """Shed one stranded (never-prefilled) request whose dispatch
        keeps failing past the shrink retry cap: the device demonstrably
        cannot serve it right now, so the answer is an explicit
        ``RateLimited`` + Retry-After — the gateway/router resends to a
        replica with memory — never an unbounded admit→OOM→requeue
        livelock and never a silent drop."""
        slot = self.slots[slot_id]
        request = slot.request
        slot.request = None
        slot.prefilling = False
        slot.prefill_done = 0
        self._lengths[slot_id] = 0
        self._adapter_release(request)
        if self._ad_rows is not None:
            self._ad_rows[slot_id] = 0
        if self.block_mgr is not None:
            self.block_mgr.release(slot_id)
        self.flight.event(
            "shed", reason="device-oom", tenant=request.tenant,
            priority=request.priority, retry_after_s=2.0,
            retries=request.preemptions,
        )
        self._journey(
            request, "shed", reason="device-oom",
            retries=request.preemptions,
        )
        if self._m_shed is not None:
            self._m_shed(1)
        if not request.warmup:
            self._slo_record("availability", False)
        self._journal_retire(request)
        if not request.future.done():
            request.future.set_exception(
                RateLimited(
                    "device-oom", 2.0,
                    f"device memory pressure persisted across "
                    f"{request.preemptions} adaptation retries "
                    f"({type(error).__name__}: {error}); retry another "
                    f"replica",
                )
            )

    def _shrink_victim(self) -> int | None:
        """The next preemption victim under device memory pressure: the
        occupied slot in the LOWEST priority class, breaking ties on
        least generated progress (cheapest byte-identical resume).
        Prefilling slots are eligible — their worst-case reservations
        are exactly the bytes the shrink needs back."""
        best = None
        best_key = None
        for slot_id, slot in enumerate(self.slots):
            request = slot.request
            if request is None:
                continue
            key = (
                -priority_rank(request.priority),  # lowest class first
                len(request.generated),            # cheapest redo
            )
            if best_key is None or key < best_key:
                best, best_key = slot_id, key
        return best

    def _maybe_pool_shrink(self, error: Exception) -> bool:
        """Adapt to a device allocator failure instead of dying: withhold
        one shrink quantum from the KV admission budget, preempt the
        lowest-class victims until the surviving reservations fit it
        (requeued FRONT-of-class — resume is the PR 4 byte-identical
        path), and arm the recovery probe. Runs on the loop thread from
        the loop's exception edge — no dispatch is in flight (the failed
        one already raised; an abandoned pipelined chunk re-derives on
        the next dispatch from unchanged host state, greedy-identically).
        Returns False when nothing could be adapted (budget at its floor
        AND nothing to preempt) — the caller falls through to the loud
        ``_fail_inflight`` path, never a silent retry loop."""
        bm = self.block_mgr
        if bm is None:
            return False
        # cause before effect in the event ring: a fault injected on the
        # dispatch thread emits here, ahead of its pool-shrink evidence
        self._drain_fault_events()
        quantum = max(
            1, int(bm.configured_blocks * self.config.shrink_fraction)
        )
        reduced = bm.reduce_budget(quantum)
        reserved_before = bm.reserved_blocks
        # adaptation a catch site already performed inline this pass
        preempted = self._shrink_inline_preempted
        shed = self._shrink_inline_shed
        self._shrink_inline_preempted = 0
        self._shrink_inline_shed = 0
        # FIRST: sweep slots whose monolithic prefill never completed —
        # the failed dispatch may have been their prefill, so no KV was
        # ever written (_lengths still 0, prefilling False). Left in
        # place they would join the next decode burst and emit garbage
        # from unwritten cache rows; requeued they re-prefill correctly.
        # (Chunked prefills are excluded by prefilling=True and resume
        # from their committed prefill_done either way.) Retries are
        # BOUNDED: a request whose dispatch keeps failing even as the
        # budget hits its floor would otherwise livelock the loop in an
        # admit→OOM→requeue cycle forever — past the cap it is shed
        # LOUDLY (RateLimited + Retry-After: another replica may have
        # the memory this one demonstrably does not).
        for slot_id, slot in enumerate(self.slots):
            if (
                slot.request is not None
                and not slot.prefilling
                and int(self._lengths[slot_id]) == 0
            ):
                if slot.request.preemptions >= _SHRINK_RETRY_CAP:
                    self._shed_stranded(slot_id, error)
                    shed += 1
                else:
                    self._preempt_slot(slot_id, reason="pool-shrink")
                    preempted += 1
        while bm.reserved_blocks > bm.usable_blocks:
            victim = self._shrink_victim()
            if victim is None:
                break
            self._preempt_slot(victim, reason="pool-shrink")
            preempted += 1
        if reduced == 0 and preempted == 0 and shed == 0:
            return False
        now = time.monotonic()
        self.pool_shrinks += 1
        self.shrink_preempted += preempted
        self._shrink_recover_at = now + self.config.shrink_recovery_s
        if self._m_shrinks is not None:
            self._m_shrinks(1)
        if self._m_budget is not None:
            self._m_budget(bm.usable_blocks)
        # the evidence event PRECEDES any admission against the reduced
        # budget (same loop pass): site + error text, what was withheld,
        # what preemption freed, and the budget admissions now face
        self.flight.event(
            "pool-shrink",
            site=getattr(error, "fault_site", None) or "device",
            error=f"{type(error).__name__}: {error}"[:160],
            withheld_blocks=reduced,
            withheld_bytes=reduced * self._kv_block_bytes,
            freed_blocks=reserved_before - bm.reserved_blocks,
            freed_bytes=(
                (reserved_before - bm.reserved_blocks)
                * self._kv_block_bytes
            ),
            preempted=preempted,
            shed=shed,
            budget_blocks=bm.usable_blocks,
            configured_blocks=bm.configured_blocks,
            recovery_s=self.config.shrink_recovery_s,
        )
        log.warning(
            "device memory pressure (%s): KV budget shrunk to %d/%d "
            "blocks, %d victims requeued front-of-class",
            type(error).__name__, bm.usable_blocks, bm.configured_blocks,
            preempted,
        )
        return True

    def _shrink_step(self) -> None:
        """Recovery probe (loop safe point, wait-free): after one quiet
        ``shrink_recovery_s`` window — no further allocator failures,
        which would have pushed ``_shrink_recover_at`` out — restore one
        shrink quantum. Staged, not all-at-once: if the pressure is
        still there, the next failure re-shrinks immediately and the
        thrash is visible in the event ring (engine_top --analyze flags
        it) instead of oscillating the whole budget."""
        at = self._shrink_recover_at
        bm = self.block_mgr
        if at is None or bm is None or time.monotonic() < at:
            return
        quantum = max(
            1, int(bm.configured_blocks * self.config.shrink_fraction)
        )
        restored = bm.restore_budget(quantum)
        if restored:
            self.pool_restores += 1
            if self._m_restores is not None:
                self._m_restores(1)
            if self._m_budget is not None:
                self._m_budget(bm.usable_blocks)
            self.flight.event(
                "pool-restore",
                restored_blocks=restored,
                restored_bytes=restored * self._kv_block_bytes,
                budget_blocks=bm.usable_blocks,
                configured_blocks=bm.configured_blocks,
            )
        if bm.budget_reduction == 0:
            self._shrink_recover_at = None
            self._wake.set()  # restored headroom is an admission signal
        else:
            self._shrink_recover_at = (
                time.monotonic() + self.config.shrink_recovery_s
            )

    def _replay_journal(self, loop) -> None:
        """Requeue the previous process's admitted-but-unfinished
        journal entries FRONT-of-class (the drain/preemption resume
        path), ahead of anything this process accepted since. The
        original callers' futures died with their process — each replay
        gets a fresh future whose completion (or explicit failure)
        retires the entry, so the journal converges to empty exactly
        once per entry."""
        entries, self._journal_replay_pending = (
            self._journal_replay_pending, []
        )
        replayed = 0
        # reversed: each requeues at the FRONT of its class, so
        # newest-first preserves the original admit order
        for entry in reversed(entries):
            try:
                tokens = [int(t) for t in entry["prompt"]]
                # the same clamps generate() applies at accept time: the
                # restarted engine may run a smaller max-seq-len/pool
                # than the one that journaled the entry
                max_prompt = self.model_config.max_seq_len - 2
                if len(tokens) > max_prompt:
                    tokens = tokens[-max_prompt:]
                max_tokens = min(
                    int(entry["max-tokens"]),
                    self.model_config.max_seq_len - len(tokens) - 1,
                )
                if max_tokens < 1 or (
                    self.block_mgr is not None
                    and not self.block_mgr.fits_ever(
                        len(tokens) + max_tokens + 1
                    )
                ):
                    # generate() refuses never-fitting requests up front
                    # and admission relies on that invariant — a replayed
                    # entry that can no longer fit would head-block
                    # admission FOREVER (and re-wedge every restart, as
                    # it is never answered and so never retired). Refuse
                    # it loudly instead.
                    raise ValueError(
                        "request no longer fits the restarted engine's "
                        "KV pool"
                    )
                request = _Request(
                    prompt_tokens=tokens,
                    max_tokens=max_tokens,
                    temperature=float(entry.get("temperature", 0.0)),
                    top_k=int(entry.get("top-k", 0)),
                    top_p=float(entry.get("top-p", 1.0)),
                    on_token=None,
                    future=loop.create_future(),
                    loop=loop,
                    enqueue_time=time.monotonic(),
                    stop=_normalize_stop(entry.get("stop")),
                    presence_penalty=float(
                        entry.get("presence-penalty", 0.0)
                    ),
                    frequency_penalty=float(
                        entry.get("frequency-penalty", 0.0)
                    ),
                    tenant=str(entry.get("tenant", "") or ""),
                    priority=normalize_priority(entry.get("priority")),
                    # the original end-to-end budget replays with the
                    # entry: the admission deadline gate sheds it loudly
                    # if the crash already spent it
                    deadline=parse_deadline(entry.get("deadline")),
                )
            except (KeyError, TypeError, ValueError) as e:
                # a corrupt entry is retired loudly, never replayed as
                # garbage and never left to wedge every future restart
                log.error("journal entry unreplayable (%s): %r", e, entry)
                self.journal.retire(entry.get("id"))
                continue
            request.journey_id = entry.get("id")
            # nobody awaits a replayed future: swallow its outcome so a
            # shed replay can't die as "exception never retrieved"
            request.future.add_done_callback(
                lambda f: f.exception() if not f.cancelled() else None
            )
            self._journey(request, "journal-replay")
            self.scheduler.requeue_front(request)
            replayed += 1
        if replayed:
            self.journal.note_replayed(replayed)
            self.flight.event("journal-replay", requests=replayed)
            log.info(
                "journal replay: %d admitted-but-unfinished requests "
                "requeued front-of-class", replayed,
            )

    def _journal_retire(self, request: "_Request") -> None:
        """Retire one request's journal entry (finish/shed/fail — every
        path that ANSWERS the caller). Wait-free: a deque append."""
        if self.journal is not None and not request.warmup:
            self.journal.retire(request.journey_id)
            if self._m_journal_depth is not None:
                self._m_journal_depth(self.journal.depth())

    def survival_section(self) -> dict[str, Any]:
        """The ``stats()["survival"]`` / flight-summary section: live
        budget posture, shrink/restore counters, fault-injection state,
        journal depth. Wait-free (attribute reads + small copies) — the
        autoscaler's fan-in and ``engine_top`` read it from
        ``/flight/summary``."""
        bm = self.block_mgr
        out: dict[str, Any] = {
            "shrinks": self.pool_shrinks,
            "restores": self.pool_restores,
            "shrink_preempted": self.shrink_preempted,
            "recovery_s": self.config.shrink_recovery_s,
            "recovering": self._shrink_recover_at is not None,
            # cross-replica failure domain (docs/RESILIENCE.md
            # "Distributed failure domain"): 504-shaped deadline
            # refusals and post-hoc overruns, chainer re-offers and
            # local-decode fallbacks — engine_top's panel reads these
            "deadline_sheds": self.deadline_sheds,
            "deadline_overruns": self.deadline_overruns,
            "handoff_retries": self.handoff_retries,
            "handoff_fallbacks": self.handoff_fallbacks,
        }
        if bm is not None:
            out["budget_blocks"] = bm.usable_blocks
            out["configured_blocks"] = bm.configured_blocks
            out["withheld_blocks"] = bm.budget_reduction
            out["withheld_bytes"] = (
                bm.budget_reduction * self._kv_block_bytes
            )
        if self._faults is not None:
            out["faults"] = self._faults.stats()
        if self.journal is not None:
            out["journal"] = self.journal.stats()
        return out

    # ------------------------------------------------------------------
    # tiered prefix store (serving/prefixstore.py, docs/PREFIX.md)
    # ------------------------------------------------------------------

    def _note_prefix_pool_evict(self, digest_hex: str, block: int) -> None:
        """Pool pressure organically evicted a cached prefix block with
        no demotion (BlockManager._evict_one): record the T0 loss so the
        tier ledgers never lose bytes silently. Wait-free: a counter
        bump and a flight append."""
        self.prefix_t0_evictions += 1
        if self._m_prefix_tier:
            self._m_prefix_tier["evictions"](1)
        self.flight.event(
            "prefix-evict",
            tier="t0",
            digest=digest_hex[:16],
            bytes=self._kv_block_bytes,
            reason="pool-pressure",
        )

    def _emit_prefix_events(self) -> None:
        """Drain the prefix store's pending event feed (see
        :meth:`_emit_store_events` for the shared emission path)."""
        self._emit_store_events(self.prefix_store.drain_events())

    def _emit_store_events(self, events) -> None:
        """Drain a tiered store's pending event feed into the flight
        ring and mirror each transition onto its Prometheus counter —
        the ONE dynamic emission path in the engine (both the prefix
        and the adapter store drain through this call site; the
        event-vocabulary conformance test pins it), so the scrape
        surface can never drift from the flight events (wait-free:
        appends + counter bumps, PFX801/LORA1701)."""
        for kind, detail in events:
            self.flight.event(kind, **detail)
            if kind.startswith("adapter-"):
                if not self._m_adapters:
                    continue
                if kind == "adapter-evict":
                    self._m_adapters["evictions"](1)
                elif kind == "adapter-demote":
                    self._m_adapters["demotions"](1)
                elif kind == "adapter-load":
                    self._m_adapters["loads"](1)
                elif (
                    kind == "adapter-hydrate"
                    and detail.get("stage") == "fetched"
                ):
                    self._m_adapters["hydrations"](1)
                continue
            if not self._m_prefix_tier:
                continue
            if kind == "prefix-demote":
                self._m_prefix_tier["demotions"](1)
            elif kind == "prefix-evict":
                self._m_prefix_tier["evictions"](1)
            elif kind == "prefix-promote":
                self._m_prefix_tier["t1_hits"](detail.get("blocks") or 1)
            elif (
                kind == "prefix-hydrate"
                and detail.get("stage") == "fetched"
            ):
                self._m_prefix_tier["t2_hits"](1)

    def _prefix_tier_step(self) -> None:
        """Loop-safe-point tier bookkeeping (wait-free, PFX801): apply
        the hydrator's results, emit the store's pending flight events,
        and settle the hydration stash — a request whose T2 fetches
        landed in T1 (or timed out / failed) requeues at the FRONT of
        its class so the admission pass right after this finds it."""
        store = self.prefix_store
        if store is None:
            return
        store.apply_results()
        self._emit_prefix_events()
        if not self._prefix_hydrating:
            return
        now = time.monotonic()
        still_waiting = []
        # reversed: each settled request requeues at the FRONT, so
        # walking newest-first leaves the oldest stashed request at the
        # actual head — arrival order survives a same-pass settle burst
        for request, deadline, digests in reversed(self._prefix_hydrating):
            if request.future.cancelled():
                self._journey(request, "cancelled", stage="prefix-hydrate")
                continue
            ready = all(store.t1_has(d) for d in digests)
            pending = any(store.hydrating(d) for d in digests)
            if not ready and pending and now < deadline:
                still_waiting.append((request, deadline, digests))
                continue
            # ready, failed, or timed out: admission decides what the
            # T1 tier can actually cover — a partial hydration still
            # promotes its landed blocks and prefills the rest
            hit = sum(1 for d in digests if store.t1_has(d))
            timed_out = not ready and now >= deadline
            if timed_out:
                store.hydrate_failures += 1
            self.flight.event(
                "prefix-hydrate",
                stage="timeout" if timed_out else "done",
                blocks=hit,
                requested=len(digests),
            )
            self._journey(
                request, "hydrate-done",
                blocks=hit, requested=len(digests),
                timeout=timed_out,
            )
            self.scheduler.requeue_front(request)
        still_waiting.reverse()  # restore arrival order in the stash
        self._prefix_hydrating = still_waiting

    def _prefix_demote_pending(self) -> bool:
        """Whether the T0 prefix cache sits over its byte budget with
        demotion candidates available — the loop polls tightly while
        true so spill drains promptly. Wait-free (PFX801)."""
        store = self.prefix_store
        if store is None or store.spec.t0_bytes is None:
            return False
        if (
            self.block_mgr.prefix_block_count() * self._kv_block_bytes
            <= store.spec.t0_bytes
        ):
            return False
        return bool(self.block_mgr.evictable_prefixes(1))

    def _chain_t2_candidates(self, chain: list[bytes]) -> list[str]:
        """The prompt-chain digests an admission should WAIT for: the
        consecutive run, starting where T0+T1 coverage ends, of digests
        the T2 index knows. ``chain`` is the admission's shared
        :meth:`BlockManager.chain_digests` walk. Empty = nothing worth
        stashing for. Wait-free: dict membership only (PFX801)."""
        store = self.prefix_store
        out: list[str] = []
        for d in chain:
            if self.block_mgr.prefix_has(d):
                continue
            h = d.hex()
            if store.t1_has(h):
                continue
            if store.t2_has(h) or store.hydrating(h):
                out.append(h)
            else:
                break  # chain gap: deeper links are unreachable anyway
        return out

    async def _promote_prefix(
        self, loop, request: "_Request", chain: list[bytes]
    ) -> int:
        """Promote the T1 run extending this prompt's T0 chain back into
        freshly allocated pool blocks (T1→T0): take the entries, install
        cache-owned blocks, and scatter the host rows in on the dispatch
        thread (the kvtransfer pack path — one timed dispatch, donated
        pools rebound there like every other dispatch closure). After
        this, the ordinary ``match_prefix`` walk sees the longer chain
        and the suffix prefill shrinks accordingly. Returns the number
        of blocks promoted (0 = nothing to do or no pool space)."""
        store = self.prefix_store
        run: list[tuple[bytes, bytes]] = []  # (digest, parent)
        prev = b""
        for d in chain:
            if self.block_mgr.prefix_has(d):
                prev = d
                continue
            if run or store.t1_has(d.hex()):
                if not store.t1_has(d.hex()):
                    break
                run.append((d, prev))
                prev = d
            else:
                break
        if not run:
            return 0
        entries = []
        for d, _parent in run:
            entry = store.take_t1(d.hex())
            if entry is None:  # raced with a shrink: stop the run here
                run = run[: len(entries)]
                break
            entries.append(entry)
        if not entries:
            return 0
        blocks = self.block_mgr.install_prefix_chain(run)
        if blocks is None:
            # no pool space even after eviction: put the entries back
            # (MRU — they were just wanted) and compute cold
            for (d, parent), entry in zip(run, entries):
                store.insert_t1(
                    d.hex(), parent.hex() if parent else "",
                    entry["arrays"], source="t2",
                )
            return 0
        bs = self.paged_layout.block_size
        nbytes = sum(e["nbytes"] for e in entries)
        rows = len(blocks) * bs
        # shape-static scatter: rows pad to the same power-of-two bucket
        # and the table row to the full slot width, so promotions of any
        # run length share the import path's jit variants instead of
        # compiling one program per chain length (pad rows mask to the
        # scratch block exactly like /kv/import)
        padded = _bucket(rows, hi=self.model_config.max_seq_len)
        table_row = np.zeros(
            self.paged_layout.max_blocks_per_slot, dtype=np.int32
        )
        table_row[: len(blocks)] = blocks

        def _run():
            from langstream_tpu.serving import kvtransfer

            # one scatter covering the whole promoted run: concatenate
            # the per-block rows in chain order and write them through
            # the slot-shaped pack path with a block-table row of the
            # freshly installed blocks
            names = sorted(entries[0]["arrays"])
            arrays = {
                name: np.concatenate(
                    [e["arrays"][name] for e in entries], axis=1
                )
                for name in names
            }
            out_k, out_v = kvtransfer.scatter_slot(
                self.cache_k, self.cache_v, arrays,
                table_row, rows, padded,
            )
            # donated pools re-bound on the dispatch thread (RACE801:
            # single thread role, same contract as every dispatch)
            self.cache_k, self.cache_v = out_k, out_v
            t_dev = time.monotonic()
            # graftcheck: disable=JAX104 the one per-dispatch sync, moved off-loop and timed
            jax.block_until_ready((out_k, out_v))
            return time.monotonic() - t_dev

        device_s = await loop.run_in_executor(self._executor, _run)
        store.note_promoted(len(blocks), nbytes, device_ms=device_s * 1e3)
        self._emit_prefix_events()
        return len(blocks)

    async def _demote_prefix_blocks(self, loop) -> None:
        """T0 byte-budget enforcement at the loop's safe point: while
        the prefix cache sits over ``t0-bytes``, gather LRU cache-only
        leaf blocks to host (ONE timed dispatch-thread fetch for the
        batch) and hand their rows to the T1 tier, then free the pool
        blocks. Bounded per pass so a storm never starves admission."""
        store = self.prefix_store
        budget = store.spec.t0_bytes
        if budget is None:
            return
        t0_bytes = self.block_mgr.prefix_block_count() * self._kv_block_bytes
        over = t0_bytes - budget
        if over <= 0 or self._kv_block_bytes <= 0:
            return
        want = min(4, -(-over // self._kv_block_bytes))
        candidates = self.block_mgr.evictable_prefixes(want)
        if not candidates:
            return
        bs = self.paged_layout.block_size

        def _run():
            from langstream_tpu.serving import kvtransfer

            out = []
            for digest, block, parent in candidates:
                gathered_k, gathered_v = kvtransfer.gather_slot(
                    self.cache_k, self.cache_v,
                    np.asarray([block], dtype=np.int32), 1,
                )
                arrays, device_s = kvtransfer._fetch_rows(
                    gathered_k, gathered_v, bs
                )
                arrays = {
                    name: np.ascontiguousarray(a)
                    for name, a in arrays.items()
                }
                out.append((digest, parent, arrays, device_s))
            return out

        gathered = await loop.run_in_executor(self._executor, _run)
        for digest, parent, arrays, _device_s in gathered:
            if self.block_mgr.drop_prefix(digest) is None:
                continue  # re-referenced while gathering: keep it in T0
            store.insert_t1(
                digest.hex(), parent.hex() if parent else "", arrays
            )
        self._emit_prefix_events()

    def prefix_store_section(self) -> dict[str, Any]:
        """``stats()["prefixstore"]`` / flight-summary section: per-tier
        bytes vs budget, hit/demotion/eviction counters, and the exact
        byte ledger. Wait-free (PFX801): snapshot reads + arithmetic;
        the tier gauges refresh here so any reader keeps the scrape
        surface current."""
        store = self.prefix_store
        t0_blocks = (
            self.block_mgr.prefix_block_count()
            if self.block_mgr is not None
            else 0
        )
        t0_bytes = t0_blocks * self._kv_block_bytes
        section = {
            "t0": {
                "blocks": t0_blocks,
                "bytes": t0_bytes,
                "budget_bytes": store.spec.t0_bytes,
                "hits": self.prefix_hits,
                "tokens_reused": self.prefix_tokens,
                "pool_evictions": self.prefix_t0_evictions,
            },
            "hydrating_requests": len(self._prefix_hydrating),
            **store.stats(),
        }
        if self._m_prefix_tier:
            self._m_prefix_tier["t0_bytes"](t0_bytes)
            self._m_prefix_tier["t1_bytes"](store.t1_bytes)
            self._m_prefix_tier["t2_bytes"](store.t2_bytes)
        return section

    # ------------------------------------------------------------------
    # multi-LoRA adapter tier plumbing (serving/adapters.py,
    # docs/ADAPTERS.md)
    # ------------------------------------------------------------------

    def install_adapter(
        self, name: str, arrays: dict[str, np.ndarray]
    ) -> None:
        """Install LoRA factors into the store's T1 tier directly (the
        local load path: tests, bench seeding, a sidecar that fetched
        out-of-band). Shapes are checked against the model HERE so a
        wrong-rank adapter fails at install, not mid-decode."""
        if self.adapter_store is None:
            raise ValueError(
                "adapter store not configured (serving adapter-store)"
            )
        mc = self.model_config
        r = self.config.adapter_store.rank
        q_dim = mc.heads * mc.head_dim
        kv_dim = mc.kv_heads * mc.head_dim
        expect = {
            "wq_a": (mc.layers, mc.hidden, r),
            "wq_b": (mc.layers, r, q_dim),
            "wk_a": (mc.layers, mc.hidden, r),
            "wk_b": (mc.layers, r, kv_dim),
            "wv_a": (mc.layers, mc.hidden, r),
            "wv_b": (mc.layers, r, kv_dim),
            "wo_a": (mc.layers, q_dim, r),
            "wo_b": (mc.layers, r, mc.hidden),
        }
        for k, shape in expect.items():
            got = tuple(np.asarray(arrays[k]).shape) if k in arrays else None
            if got != shape:
                raise ValueError(
                    f"adapter {name!r} factor {k}: shape {got}, "
                    f"model expects {shape}"
                )
        self.adapter_store.install(name, arrays)

    async def _resolve_adapter(self, loop, request: "_Request") -> str:
        """Admission-side adapter resolve. Returns one of:

        - ``"ready"``    — a device row holds the adapter; the request is
          pinned against eviction and carries the row index.
        - ``"wait"``     — the adapter is hydrating T2→T1; the request was
          popped and stashed OFF the scheduler (same discipline as the
          prefix hydration stash — it never head-blocks admission).
        - ``"refused"``  — unknown adapter or a spent hydration attempt:
          the request was popped and failed loudly (AdapterUnavailable).
        - ``"backpressure"`` — every T0 row is pinned by in-flight
          requests; the caller breaks the admission pass and retries
          after decode frees pins.

        Wait-free on the loop side apart from the one awaited device
        row-copy dispatch (LORA1701: the T2 I/O lives on the hydrator)."""
        store = self.adapter_store
        name = request.adapter
        row = store.t0_row(name)
        if row is None and store.t1_has(name):
            row = store.t0_assign(name)
            if row is None:
                return "backpressure"
            await self._load_adapter_row(loop, name, row)
        if row is not None:
            request.adapter_row = row
            store.pin(name)
            request.adapter_pinned = True
            return "ready"
        if (
            not request.adapter_hydrate_attempted
            and not self._draining
            and (store.t2_has(name) or store.hydrating(name))
        ):
            request.adapter_hydrate_attempted = True
            if store.request_hydration([name]):
                self.scheduler.pop()
                deadline = (
                    time.monotonic() + store.spec.hydrate_timeout_s
                )
                self._adapter_hydrating.append((request, deadline, name))
                store.hydrations += 1
                self.flight.event(
                    "adapter-hydrate", stage="begin", adapter=name
                )
                self._journey(request, "adapter-hydrate", adapter=name)
                return "wait"
        self.scheduler.pop()
        self.adapter_refusals += 1
        self.flight.event("adapter-refused", adapter=name)
        self._journal_retire(request)
        if not request.future.done():
            request.future.set_exception(
                AdapterUnavailable(
                    f"adapter {name!r} unavailable: not resident in any "
                    "tier (install it or publish it to the T2 origin)"
                )
            )
        return "refused"

    async def _load_adapter_row(self, loop, name: str, row: int) -> None:
        """Copy a T1-resident adapter's factors into device row ``row``
        (T1→T0). Runs on the dispatch thread — the only thread that
        touches ``_ad_layers`` — as a functional per-row rebuild
        (``.at[:, row].set``): in-flight dispatches keep the buffer
        snapshot they captured, exactly like the donated caches."""
        store = self.adapter_store
        entry = store.t1_peek(name)
        arrays = entry["arrays"]
        dtype = self.model_config.dtype

        def _run():
            t0 = time.monotonic()
            new = {
                k: buf.at[:, row].set(jnp.asarray(arrays[k], dtype=dtype))
                for k, buf in self._ad_layers.items()
            }
            # graftcheck: disable=JAX104 one timed per-load sync, on the dispatch thread
            jax.block_until_ready(list(new.values()))
            self._ad_layers = new
            return (time.monotonic() - t0) * 1000.0

        device_ms = await loop.run_in_executor(self._executor, _run)
        store.note_loaded(name, row, device_ms)

    def _adapter_release(self, request: "_Request") -> None:
        """Release a finished/failed request's pin on its adapter row.
        Wait-free: dict arithmetic (LORA1701)."""
        if request.adapter_pinned:
            request.adapter_pinned = False
            if self.adapter_store is not None:
                self.adapter_store.unpin(request.adapter)

    def _adapter_tier_step(self) -> None:
        """Loop-safe-point adapter bookkeeping (wait-free, LORA1701):
        apply the hydrator's results, emit the store's pending flight
        events through the shared drain, and settle the hydration
        stash. A request whose adapter landed in T1 requeues at the
        FRONT of its class; a timed-out or failed hydration is a COLD
        REFUSAL (AdapterUnavailable) — unlike a prefix miss there is no
        cheaper fallback compute, so requeueing would just spin."""
        store = self.adapter_store
        if store is None:
            return
        store.apply_results()
        self._emit_store_events(store.drain_events())
        if not self._adapter_hydrating:
            return
        now = time.monotonic()
        still_waiting = []
        # reversed: settled requests requeue at the FRONT, so walking
        # newest-first leaves the oldest at the actual head
        for request, deadline, name in reversed(self._adapter_hydrating):
            if request.future.cancelled():
                self._journey(request, "cancelled", stage="adapter-hydrate")
                self._journal_retire(request)
                continue
            if store.t1_has(name):
                self.flight.event(
                    "adapter-hydrate", stage="done", adapter=name
                )
                self._journey(request, "adapter-hydrate-done", adapter=name)
                self.scheduler.requeue_front(request)
                continue
            if store.hydrating(name) and now < deadline:
                still_waiting.append((request, deadline, name))
                continue
            # failed or timed out: refuse cold — loudly, never silently
            store.hydrate_failures += 1
            self.adapter_refusals += 1
            self.flight.event(
                "adapter-hydrate", stage="timeout", adapter=name
            )
            self.flight.event("adapter-refused", adapter=name)
            self._journey(
                request, "adapter-hydrate-done", adapter=name, timeout=True
            )
            self._journal_retire(request)
            if not request.future.done():
                request.future.set_exception(
                    AdapterUnavailable(
                        f"adapter {name!r} hydration timed out after "
                        f"{store.spec.hydrate_timeout_s:.1f}s"
                    )
                )
        still_waiting.reverse()  # restore arrival order in the stash
        self._adapter_hydrating = still_waiting

    def adapter_store_section(self) -> dict[str, Any]:
        """``stats()["adapters"]`` / flight-summary section: per-tier
        bytes vs budget, hit/load/eviction counters, the resident row
        map, and the exact byte ledger. Wait-free (LORA1701): snapshot
        reads + arithmetic; the tier gauges refresh here so any reader
        keeps the scrape surface current."""
        store = self.adapter_store
        section = {
            "hydrating_requests": len(self._adapter_hydrating),
            "refusals": self.adapter_refusals,
            **store.stats(),
        }
        if self._m_adapters:
            self._m_adapters["t0_bytes"](section["t0"]["bytes"])
            self._m_adapters["t1_bytes"](store.t1_bytes)
            self._m_adapters["t2_bytes"](store.t2_bytes)
        return section

    def _draft_tokens(
        self, slot_id: int, num_drafts: int
    ) -> tuple[list[int], int]:
        """Prompt-lookup draft: continue the context's most recent bigram
        match. Unmatched slots get zero drafts — greedy verify accepts a
        draft only when the model would have emitted it anyway, so a bad
        draft costs nothing but the verified position. Returns the padded
        draft row and the number of REAL drafts in it (padding zeros are
        not drafts — counting them as rejected would deflate the accept
        ratio on workloads where lookup rarely matches)."""
        request = self.slots[slot_id].request
        ctx = request.prompt_tokens + request.generated
        n = len(ctx)
        # index new bigrams whose SECOND element sits at <= n-2 (the final
        # bigram is the query; it enters the index once the context grows)
        idx = request.bigram_index
        for i in range(max(request.bigram_covered, 1), n - 1):
            idx[(ctx[i - 1], ctx[i])] = i - 1
        request.bigram_covered = max(request.bigram_covered, n - 1)
        if n >= 3:
            pos = idx.get((ctx[-2], ctx[-1]))
            if pos is not None:
                cont = ctx[pos + 2 : pos + 2 + num_drafts]
                padded = list(cont) + [0] * (num_drafts - len(cont))
                return padded, len(cont)
        return [0] * num_drafts, 0

    def _sync_ctx_rows(
        self, live: list[int]
    ) -> tuple[np.ndarray, np.ndarray] | tuple[None, None]:
        """Host-side payload for re-syncing stale context rows of the
        device-resident token buffer the fused drafter reads. The ledger
        ``_ctx_synced[slot]`` holds the number of valid tokens in the
        slot's device row; a row is current when it equals ``lengths+1``
        (history plus the pending current token). The fused spec step
        extends rows in-program as drafts are accepted, so under a pure
        speculative run NOTHING re-syncs — only freshly-prefilled slots
        and slots advanced by a plain decode chunk (calibration, or an
        auto-disabled interval), each with one full-row upload. Loop-
        thread only (host truth, ledger update); the device write itself
        happens in the dispatch closure, which also broadcasts this
        payload so lockstep followers apply the identical update."""
        S = self.model_config.max_seq_len
        rows: list[int] = []
        vals: list[np.ndarray] = []
        for slot_id in live:
            request = self.slots[slot_id].request
            n = min(int(self._lengths[slot_id]) + 1, S)
            if int(self._ctx_synced[slot_id]) == n:
                continue
            ctx = request.prompt_tokens + request.generated
            row = np.zeros(S, dtype=np.int32)
            m = min(n, len(ctx))
            row[:m] = ctx[:m]
            rows.append(slot_id)
            vals.append(row)
            self._ctx_synced[slot_id] = n
        if not rows:
            return None, None
        return np.fromiter(rows, dtype=np.int32, count=len(rows)), np.stack(vals)

    def _fetch_spec(
        self, packed, d1: int
    ) -> tuple[np.ndarray, ...]:
        """Designated fetch stage for the fused speculative step: ONE
        device→host transfer per step carries emitted tokens, per-slot
        advance counts, the next-token feedback, new lengths, real-draft
        counts, and bitcast logprobs."""
        B = self.config.slots
        nE = B * d1
        self._fault("fetch")
        flat = np.asarray(packed)
        self._spec_fetches += 1
        return (
            flat[:nE].reshape(B, d1),
            flat[nE:nE + B],
            flat[nE + B:nE + 2 * B],
            flat[nE + 2 * B:nE + 3 * B],
            flat[nE + 3 * B:nE + 4 * B],
            flat[nE + 4 * B:].view(np.float32).reshape(B, d1),
        )

    def _spec_note_step(self, tokens: int, wall_s: float) -> None:
        if tokens > 0 and wall_s > 0:
            self._spec_window.append((tokens, wall_s))

    def _spec_note_plain(self, tokens: int, wall_s: float) -> None:
        if tokens > 0 and wall_s > 0:
            self._plain_window.append((tokens, wall_s))

    def _spec_uplift(self) -> float | None:
        """Rolling measured uplift: speculative tokens/s over plain
        tokens/s, None until the spec window is full AND at least one
        plain (calibration) sample exists — a half-window verdict would
        flap on warmup jitter."""
        if len(self._spec_window) < (self._spec_window.maxlen or 1):
            return None
        if not self._plain_window:
            return None
        spec_n = sum(n for n, _ in self._spec_window)
        spec_t = sum(w for _, w in self._spec_window)
        plain_n = sum(n for n, _ in self._plain_window)
        plain_t = sum(w for _, w in self._plain_window)
        if spec_t <= 0 or plain_t <= 0 or plain_n <= 0:
            return None
        return (spec_n / spec_t) / (plain_n / plain_t)

    def _spec_check_uplift(self) -> bool:
        """Flip speculation off when the measured uplift drops below 1 —
        the honest answer to BENCH_r05's 0.23x speculative slowdown: a
        high accept ratio is NOT a win if the per-step cost eats it.
        Returns True when the flip happened (the burst must return to the
        plain decode loop). Re-enable is time-served: see the
        ``spec-auto-enable`` branch in :meth:`_flight_record`."""
        uplift = self._spec_uplift()
        if uplift is None:
            return False
        self._spec_last_uplift = uplift
        self._m_spec_uplift(uplift)
        if uplift >= 1.0:
            return False
        self._spec_auto_disabled = True
        self._spec_plain_since_disable = 0
        self._spec_flips.append((time.monotonic(), "disable"))
        self.flight.event(
            "spec-auto-disable",
            uplift=round(uplift, 4),
            window_steps=len(self._spec_window),
            plain_samples=len(self._plain_window),
        )
        self._spec_window.clear()
        self._plain_window.clear()
        return True

    def _spec_cal_due(self) -> bool:
        return self._spec_steps_since_cal >= self._spec_cal_every

    async def _spec_calibration_chunk(
        self, loop, live: list[int], active_mask: np.ndarray,
        sampler_mode: tuple, tables: np.ndarray, nrb: int,
    ) -> bool:
        """One plain K=1 decode chunk, wall-timed end to end, feeding the
        plain-throughput window the uplift verdict divides by. Greedy
        streams stay byte-identical: a single plain greedy step emits
        exactly the token the spec step's first verified position would.
        Returns True when any slot finished (the burst tears down, same
        as the sequential decode loop)."""
        K = 1
        fn = self._decode_fn(sampler_mode, nrb, K, False)
        program = self._program_decode(nrb, K, sampler_mode, False)
        amask, temps, topks, topps = self._sampler_device(active_mask)
        lengths_np = self._lengths.copy()
        current_np = self._current.copy()
        temps_np = self._temps.copy()
        topks_np = self._topks.copy()
        topps_np = self._topps.copy()
        ad_np = self._ad_rows.copy() if self._ad_rows is not None else None
        key = self._split_key()

        def _run():
            if self._lockstep is not None:
                self._lockstep.broadcast(
                    {
                        "op": "decode",
                        "sampler_mode": list(sampler_mode),
                        "window": nrb,
                        "k": K,
                        "key": np.asarray(key),
                        "active": active_mask,
                        "tables": tables,
                        "tokens": current_np,
                        "lengths": lengths_np,
                        "temps": temps_np,
                        "topks": topks_np,
                        "topps": topps_np,
                    }
                )
            self.profiler.on_decode_chunk()
            tables_dev = self._tables_device(tables)
            ad_kw = (
                {}
                if ad_np is None
                else {"ad_layers": self._ad_layers,
                      "ad_ids": jnp.asarray(ad_np)}
            )
            packed, _t, _l, ck, cv = fn(
                self.params, self.cache_k, self.cache_v,
                jnp.asarray(current_np), jnp.asarray(lengths_np),
                amask, tables_dev, key, temps, topks, topps, **ad_kw,
            )
            self.cache_k, self.cache_v = ck, cv
            self._decode_dispatches += 1
            self._start_fetch(packed)
            return self._fetch_chunk(packed, K)

        t_wall = time.monotonic()
        chunk_t, chunk_lp, fetch_s = await loop.run_in_executor(
            self._executor, _run
        )
        gen_before = self.total_generated
        finished = self._process_chunk(chunk_t, chunk_lp, live)
        self._spec_note_plain(
            self.total_generated - gen_before, time.monotonic() - t_wall
        )
        self._flight_record(
            "decode", device_s=fetch_s,
            tokens=self.total_generated - gen_before, program=program,
        )
        await self._flush_emits(live)
        return finished

    async def _speculative_burst(self, loop, active: list[int]) -> None:
        """Device-resident prompt-lookup speculative decoding: per step,
        ONE fused dispatch drafts each slot's continuation from the
        device-resident context rows, verifies D+1 positions, extends the
        context rows in-program, and packs everything the host needs into
        a single array — zero host syncs inside the dispatch closure
        (graftcheck HOT1401/HOT1402), one packed fetch per step. Streams
        are identical to plain greedy decode — only the tokens-per-step
        ratio changes. A rolling measured-uplift window (calibrated by
        periodic plain K=1 chunks) flips speculation off with a
        ``spec-auto-disable`` flight event when the fused step is not
        actually paying for itself."""
        D = self.config.speculative_drafts
        D1 = D + 1
        S = self.model_config.max_seq_len
        while True:
            if self._spec_auto_disabled:
                return
            live = [
                i for i in active
                if self.slots[i].request is not None
                and not self.slots[i].prefilling
            ]
            if not live:
                return
            self._fault("pool-grow")
            grown_blocks = grown_slots = 0
            for slot_id in live:
                n = self.block_mgr.ensure_capacity(
                    slot_id, min(int(self._lengths[slot_id]) + D1, S)
                )
                grown_blocks += n
                grown_slots += bool(n)
            if grown_blocks:
                self.flight.event(
                    "pool-grow", slots=grown_slots, blocks=grown_blocks,
                    bytes=grown_blocks * self._kv_block_bytes,
                    phase="verify",
                )
            tables = self.block_mgr.tables.copy()
            active_mask = np.zeros(self.config.slots, dtype=bool)
            active_mask[live] = True
            nrb = self._read_blocks_for(
                max(int(self._lengths[live].max()) if live else 1, 1)
            )
            sampler_mode = self._sampler_mode(
                self._temps[active_mask], self._topks[active_mask],
                self._topps[active_mask],
            )
            if self._spec_cal_due():
                finished = await self._spec_calibration_chunk(
                    loop, live, active_mask, sampler_mode, tables, nrb
                )
                self._spec_steps_since_cal = 0
                if self._spec_check_uplift():
                    return
                if (
                    finished
                    or not self.scheduler.empty()
                    or self._stop
                    or self._has_prefilling()
                    or (self._draining and not self._drain_pass_done)
                ):
                    return
                continue  # re-derive live/lengths: the chunk advanced them
            ctx_rows, ctx_vals = self._sync_ctx_rows(live)
            fn = self._spec_step_fn(nrb, sampler_mode)
            program = self._program_spec_step(nrb, sampler_mode)
            # host state snapshotted on the LOOP thread: the spec step
            # yields to admission between iterations, which rewrites the
            # sampler arrays — the dispatch closure must not re-read
            # mutable engine fields mid-flight (RACE801)
            lengths_np = self._lengths.copy()
            current_np = self._current.copy()
            temps_np = self._temps.copy()
            topks_np = self._topks.copy()
            topps_np = self._topps.copy()
            ad_np = (
                self._ad_rows.copy() if self._ad_rows is not None else None
            )
            key = self._split_key()

            def _run():
                if self._lockstep is not None:
                    # drafting moved on-device: followers replay the same
                    # fused jit from control-plane state only — current
                    # tokens, lengths, and any context rows the leader
                    # re-synced this step (device rows chain otherwise)
                    desc: dict[str, Any] = {
                        "op": "spec_step",
                        "nrb": nrb,
                        "sampler_mode": list(sampler_mode),
                        "current": current_np,
                        "lengths": lengths_np,
                        "active": active_mask,
                        "tables": tables,
                        "key": np.asarray(key),
                        "temps": temps_np,
                        "topks": topks_np,
                        "topps": topps_np,
                    }
                    if ctx_rows is not None:
                        desc["ctx_rows"] = ctx_rows
                        desc["ctx_vals"] = ctx_vals
                    self._lockstep.broadcast(desc)
                ad_kw = (
                    {}
                    if ad_np is None
                    else {"ad_layers": self._ad_layers,
                          "ad_ids": jnp.asarray(ad_np)}
                )
                # the context buffer lives on the dispatch thread, like
                # the KV caches: created lazily, patched with the loop
                # thread's re-sync payload, then chained through the
                # fused program's donated output
                if self._ctx_dev is None:
                    self._ctx_dev = jnp.zeros(
                        (self.config.slots, self.model_config.max_seq_len),
                        dtype=jnp.int32,
                    )
                if ctx_rows is not None:
                    self._ctx_dev = self._ctx_dev.at[
                        jnp.asarray(ctx_rows)
                    ].set(jnp.asarray(ctx_vals))
                out = fn(
                    self.params, self.cache_k, self.cache_v, self._ctx_dev,
                    jnp.asarray(current_np), jnp.asarray(lengths_np),
                    jnp.asarray(active_mask), jnp.asarray(tables),
                    key, jnp.asarray(temps_np), jnp.asarray(topks_np),
                    jnp.asarray(topps_np), **ad_kw,
                )
                self._ctx_dev = out[1]
                self.cache_k, self.cache_v = out[2], out[3]
                self._spec_dispatches += 1
                self._start_fetch(out[0])
                # dispatch returned async; the fetch below blocks until
                # the device finishes — that wait is the step's device time
                t_dev = time.monotonic()
                fetched = self._fetch_spec(out[0], D1)
                return fetched + (time.monotonic() - t_dev,)

            t_wall = time.monotonic()
            emitted, adv, nxt, new_lengths, n_real, logprobs, device_s = (
                await loop.run_in_executor(self._executor, _run)
            )
            self._m_spec_steps(1)
            self.spec_steps += 1
            self._spec_steps_since_cal += 1
            finished = False
            emitted_before = self.total_generated  # _emit_token counts each
            accepted_before = self.spec_accepted
            rejected_step = 0
            for slot_id in live:
                a = int(adv[slot_id])
                base = int(self._lengths[slot_id])
                done = False
                acc_slot = 0
                for j in range(a):
                    # advance the length BEFORE each emit so the emit-side
                    # max_seq_len stop guard sees the true context size
                    # (plain decode increments per step; a stale base would
                    # let accepted drafts run past the cap and diverge from
                    # the bit-identical-to-greedy invariant)
                    self._lengths[slot_id] = base + j + 1
                    done = self._emit_token(
                        slot_id,
                        int(emitted[slot_id, j]),
                        float(logprobs[slot_id, j]),
                    )
                    if j > 0:
                        self._m_spec_accepted(1)
                        self.spec_accepted += 1
                        acc_slot += 1
                    if done:
                        finished = True
                        break
                if not done:
                    self._current[slot_id] = int(nxt[slot_id])
                    # the fused step appended this slot's accepted tokens
                    # to its device context row in-program
                    self._ctx_synced[slot_id] = base + a + 1
                # only REAL drafts count as rejected (padding zeros never
                # were drafts); drafts left unconsumed by a mid-burst
                # stop/EOS were still wasted verify positions
                rejected_step += max(0, int(n_real[slot_id]) - acc_slot)
            self._m_tokens(self.total_generated - emitted_before)
            accepted_step = self.spec_accepted - accepted_before
            self.spec_rejected += rejected_step
            self._m_spec_rejected(rejected_step)
            drafted = self.spec_accepted + self.spec_rejected
            if drafted:
                self._m_spec_ratio(self.spec_accepted / drafted)
            self._spec_note_step(
                self.total_generated - emitted_before,
                time.monotonic() - t_wall,
            )
            self._flight_record(
                "verify",
                device_s=device_s,
                tokens=self.total_generated - emitted_before,
                spec_accepted=accepted_step,
                spec_rejected=rejected_step,
                program=program,
            )
            if self._spec_check_uplift():
                await self._flush_emits(live)
                return
            await self._flush_emits(live)
            if (
                finished
                or not self.scheduler.empty()
                or self._stop
                or self._has_prefilling()
                # a pending drain preempts at the loop's safe point
                or (self._draining and not self._drain_pass_done)
            ):
                return

    def _burst_should_yield(self, finished: bool, pipelined: bool = False) -> bool:
        """End the decode burst only when the engine loop can actually make
        progress elsewhere: a slot just freed (admission now possible),
        queued work can land in an already-free slot, the engine is
        stopping, or a prefill is mid-flight. A non-empty queue with ZERO
        free slots must NOT end the burst — returning would tear down the
        pipelined chunk stream and re-pay the per-burst device uploads on
        every chunk (r5 chip attribution: each synchronous upload RPC costs
        ~70ms over a tunneled chip, and the saturated bench held a full
        admission queue for its whole duration — every chunk became its own
        burst, serializing ~500ms of host RPCs against 787ms of device
        compute).

        Pipelined bursts additionally survive a finish when nobody is
        queued: the finished slot is frozen in the device-side active mask
        from the next dispatch on (its over-run tokens discarded host-side,
        never billed), so mixed-length workloads don't tear the pipeline
        down — and re-pay its teardown/rebuild — once per completion. The
        sequential reference loop keeps the yield-on-finish behavior."""
        if self._stop or self._has_prefilling():
            return True
        if self._draining and not self._drain_pass_done:
            # a pending drain must reach the loop's safe point NOW: the
            # preempt-and-requeue sweep snapshots every running request
            # after the current chunk, not after the whole burst
            return True
        if finished:
            # a freed slot is an admission opportunity the moment anyone
            # is queued; otherwise the pipelined loop freezes it in place
            return not (pipelined and self.scheduler.empty())
        if self.scheduler.empty():
            return False
        if os.environ.get("LS_TPU_STICKY_BURSTS", "1") == "0":
            return True  # pre-r5 behavior (A/B knob): yield on any queue
        return any(s.free for s in self.slots)

    def _fetch_chunk(
        self, packed, k_steps: int
    ) -> tuple[np.ndarray, np.ndarray, float]:
        """The designated fetch stage (graftcheck PERF701 polices syncs
        anywhere else on the dispatch path): ONE device→host transfer per
        chunk — tokens and bitcast logprobs ride the same packed array,
        whose D2H copy the dispatch already started asynchronously. The
        third element is the seconds this call spent blocked on the
        device — the chunk's un-overlapped device wait, which the flight
        recorder subtracts from wall time to expose the host share."""
        B = self.config.slots
        n = k_steps * B
        self._fault("fetch")
        t_dev = time.monotonic()
        flat = np.asarray(packed)
        fetch_s = time.monotonic() - t_dev
        self._decode_fetches += 1
        return (
            flat[:n].reshape(k_steps, B),
            flat[n:].view(np.float32).reshape(k_steps, B),
            fetch_s,
        )

    @staticmethod
    def _chunk_ready(packed) -> bool:
        """Non-blocking completion probe for an in-flight packed chunk
        (overlap accounting only — never a sync): True once the device
        has finished producing it. Backends without the probe report
        not-ready, i.e. the pre-readiness-bounded accounting."""
        try:
            return bool(packed.is_ready())
        except AttributeError:
            return False

    @staticmethod
    def _start_fetch(packed) -> None:
        """Begin the packed chunk's device→host copy without blocking, so
        the transfer rides under the next dispatch's device shadow and the
        deferred wait in :meth:`_fetch_chunk` finds the bytes already in
        flight (or landed)."""
        try:
            packed.copy_to_host_async()
        except AttributeError:  # backends without async D2H: fetch blocks
            pass

    def _tables_device(self, tables: np.ndarray | None):
        """Device copy of the block tables, re-uploaded only on a content
        miss (most chunks allocate no new blocks; the upload RPC is the
        cost that matters, not the 4KB payload). LRU-bounded: see
        :class:`_DeviceLru`."""
        if tables is None:
            return None
        return self._tables_dev_cache.get_or_put(
            tables.tobytes(), lambda: jnp.asarray(tables)
        )

    def _sampler_device(self, active_mask: np.ndarray):
        """Device copies of (active mask, temps, topks, topps), re-uploaded
        only on a content miss (4 upload RPCs per burst otherwise) —
        LRU-bounded, so the pipelined loop's finished-slot mask refreshes
        flip between populations without re-uploading each time."""
        raw = (
            active_mask.tobytes() + self._temps.tobytes()
            + self._topks.tobytes() + self._topps.tobytes()
        )
        return self._sampler_dev_cache.get_or_put(
            raw,
            lambda: (
                jnp.asarray(active_mask),
                jnp.asarray(self._temps),
                jnp.asarray(self._topks),
                jnp.asarray(self._topps),
            ),
        )

    async def _decode_burst(self, loop, active: list[int]) -> None:
        """Depth-2 pipelined chunk decoding (docs/PIPELINE.md): chunk k+1
        is dispatched from chunk k's *device-resident* outputs before k's
        tokens reach the host (the sampler feedback never round-trips),
        the packed fetch is started asynchronously at dispatch, and the
        host's fetch/detokenize/stop-check/emit work for chunk k runs
        under chunk k+1's device shadow — recorded as the sample's
        ``host_overlapped_ms``. Slots that finish inside an in-flight
        chunk are frozen in the device-side active mask from the next
        dispatch on; their over-run tokens are discarded host-side and
        never billed. The burst ends when admission work appears, leaving
        its in-flight chunk pending so the admission prefill dispatches
        under that chunk's shadow (drained identity-filtered afterwards —
        see :meth:`_drain_pending`).

        Light-load regime (active slots <= ``_light_threshold``): the burst
        fuses only ``decode_chunk_light`` steps per dispatch and runs them
        SEQUENTIALLY — no speculative chunk in flight — so an arriving
        request reaches prefill after at most one short chunk instead of
        two long ones. The device idles for one host round-trip between
        chunks, which is free precisely when the engine is under-loaded;
        past the threshold the pipelined big-chunk path takes over. The
        same sequential loop serves penalty bursts and the
        ``pipeline=False`` / ``LS_TPU_PIPELINE=0`` escape hatch — it is
        the reference the pipelined loop's greedy byte-identity is tested
        against."""
        key1 = self._split_key()
        active_mask = np.zeros(self.config.slots, dtype=bool)
        active_mask[active] = True
        amask, temps, topks, topps = self._sampler_device(active_mask)
        sampler_mode = self._sampler_mode(
            self._temps[active_mask], self._topks[active_mask],
            self._topps[active_mask],
        )
        light = len(active) <= self._light_threshold()
        K = (
            self.config.decode_chunk_light if light
            else self.config.decode_chunk
        )
        # never fuse far past the longest remaining budget: a 96-step chunk
        # serving 48-token answers burns half its steps on finished slots
        # (and doubles head-of-line latency for queued arrivals). Halving
        # buckets keep the compile-variant count logarithmic.
        max_remaining = 1
        for slot_id in active:
            request = self.slots[slot_id].request
            if request is not None:
                max_remaining = max(
                    max_remaining,
                    request.max_tokens - len(request.generated),
                )
        while K >= 2 * max(max_remaining, self.config.decode_chunk_light, 1):
            K //= 2
        # presence/frequency penalties: the in-chunk token counts evolve in
        # the scan carry but are NOT returned (the host rebuilds them from
        # request.generated before each dispatch) — so penalty bursts run
        # the SEQUENTIAL path: a pipelined speculative chunk would need the
        # previous chunk's final counts before the host has its tokens
        pen = bool(
            (self._pres[active_mask] != 0).any()
            or (self._freq[active_mask] != 0).any()
        )
        # penalty state snapshotted on the LOOP thread: _admit/_advance_
        # prefills rewrite these arrays between bursts, and the dispatch
        # thread must never re-read engine fields mid-flight (RACE801)
        pres_np = self._pres.copy() if pen else None
        freq_np = self._freq.copy() if pen else None
        # host-tracked longest active sequence: each dispatched chunk grows
        # it by K; the attention window bucket follows
        base_max = int(self._lengths[active].max())
        paged = self.block_mgr is not None

        def _build_counts() -> np.ndarray:
            counts = np.zeros(
                (self.config.slots, self.model_config.vocab_size),
                dtype=np.int32,
            )
            for slot_id in active:
                request = self.slots[slot_id].request
                if request is not None:
                    for t in request.generated:
                        counts[slot_id, t] += 1
            return counts

        def _grow_blocks(pending_chunks: int) -> np.ndarray | None:
            """Paged: allocate blocks covering this dispatch's chunk plus
            the ``pending_chunks`` dispatched-but-unprocessed chunks whose
            tokens host ``_lengths`` doesn't reflect yet (0 in the
            sequential path — lengths are current at each re-dispatch; 1
            for a pipelined speculative dispatch). Indexing by cumulative
            chunk count instead would over-reserve by one chunk per
            processed chunk and needlessly evict shared prefix-cache
            blocks. Returns a host snapshot of the block tables (the
            dispatch converts it device-side — keeping it numpy here lets
            the lockstep broadcast ship it without a device→host
            round-trip)."""
            if not paged:
                return None
            self._fault("pool-grow")
            S = self.model_config.max_seq_len
            grown_blocks = grown_slots = 0
            for slot_id in active:
                request = self.slots[slot_id].request
                if request is not None:
                    # the reservation can never need to exceed the request's
                    # own budget: without this cap the pipelined lookahead
                    # (+2K) overshoots into pool exhaustion on the last
                    # chunks — on the r5 chip run that eviction churn cost
                    # more than the pipelining won
                    cap = len(request.prompt_tokens) + request.max_tokens + 1
                    need = min(
                        int(self._lengths[slot_id]) + (pending_chunks + 1) * K,
                        cap, S,
                    )
                    n = self.block_mgr.ensure_capacity(slot_id, need)
                    grown_blocks += n
                    grown_slots += bool(n)
            if grown_blocks:
                self.flight.event(
                    "pool-grow", slots=grown_slots, blocks=grown_blocks,
                    bytes=grown_blocks * self._kv_block_bytes,
                    phase="decode",
                )
            return self.block_mgr.tables.copy()

        def _dispatch(tokens, lengths, key, window, tables, decode_fn,
                      counts_np=None, first=False, ad_np=None):
            # async JAX dispatch: returns device arrays without blocking.
            # Everything the closure needs (the resolved jit variant, the
            # penalty snapshot, the block tables) was prepared on the loop
            # thread by _submit — the dispatch thread reads no mutable
            # engine fields outside the lockstep protocol branch (RACE801)
            if self._lockstep is not None:
                # runs on the single dispatch thread → broadcast order is
                # dispatch order. Speculative chunks ("decode_cont") carry
                # only control (plus the active mask, so a mid-burst
                # finished-slot freeze reaches followers): followers chain
                # their own device-resident tokens/lengths outputs, so
                # nothing syncs to host here.
                desc: dict[str, Any] = {
                    "op": "decode" if first else "decode_cont",
                    "sampler_mode": list(sampler_mode),
                    "window": window,
                    "k": K,
                    "key": np.asarray(key),
                    "active": active_mask,
                }
                if tables is not None:
                    desc["tables"] = tables  # host snapshot from _grow_blocks
                if pen:
                    # penalty bursts are sequential, so every chunk ships
                    # fresh host state (counts are (slots, vocab) — heavy,
                    # but penalties are a per-request opt-in)
                    desc.update(
                        pen=True,
                        pres=pres_np,
                        freq=freq_np,
                        counts=counts_np,
                    )
                if first:
                    desc.update(
                        tokens=np.asarray(self._current),
                        lengths=np.asarray(self._lengths),
                        temps=np.asarray(self._temps),
                        topks=np.asarray(self._topks),
                        topps=np.asarray(self._topps),
                    )
                self._lockstep.broadcast(desc)
            self.profiler.on_decode_chunk()
            tables_dev = self._tables_device(tables)
            args = (
                (self.params, self.cache_k, self.cache_v,
                 tokens, lengths, amask, tables_dev, key, temps, topks, topps)
                if paged
                else (self.params, self.cache_k, self.cache_v,
                      tokens, lengths, amask, key, temps, topks, topps)
            )
            if pen:
                args = args + (
                    jnp.asarray(pres_np), jnp.asarray(freq_np),
                    jnp.asarray(counts_np),
                )
            # adapter rows ride as kwargs only when the store is enabled:
            # the default engine's trace (and its jaxpr) stays the seed's.
            # _ad_layers is touched only on this (dispatch) thread, so the
            # snapshot here serializes after any in-flight row load.
            ad_kw = (
                {}
                if ad_np is None
                else {"ad_layers": self._ad_layers,
                      "ad_ids": jnp.asarray(ad_np)}
            )
            self.profiler.dump_hlo(
                f"decode_chunk_w{window}_s{sampler_mode}", decode_fn, *args
            )
            packed, t, l, ck, cv = decode_fn(*args, **ad_kw)
            self.cache_k, self.cache_v = ck, cv
            # tokens+logprobs were packed INSIDE the decode program
            # (sample-in-program): start their D2H copy now, so by the
            # time the deferred _fetch_chunk wait runs, the transfer has
            # been riding under this dispatch's own device shadow
            self._decode_dispatches += 1
            self._start_fetch(packed)
            return packed, t, l

        def _bucket_for(max_len: int):
            return (
                self._read_blocks_for(max_len) if paged
                else self._window_for(max_len)
            )

        # program ids of dispatched-but-unrecorded chunks, FIFO (≤ 2 in
        # flight under the depth-2 pipeline): each flight record pops the
        # oldest so measured device time lands on the variant that ran it
        prog_q: list[str] = []

        def _submit(tokens, lengths, key, window, tables, first=False):
            """Loop-thread half of a chunk dispatch: resolve the jit
            variant (so the ``_decode_chunk_fns``/``_compiled_shapes``
            bookkeeping never runs on the dispatch thread), rebuild the
            penalty counts from host truth, bump the regime counters,
            then hand the fully-prepared closure to the dispatch thread.
            Returns the executor future — awaited immediately by the
            sequential path, left in flight by the pipelined one."""
            decode_fn = self._decode_fn(sampler_mode, window, K, pen)
            prog_q.append(self._program_decode(window, K, sampler_mode, pen))
            counts_np = _build_counts() if pen else None
            # slot→adapter-row mirror snapshotted on the LOOP thread
            # (RACE801): admission rewrites _ad_rows between bursts
            ad_np = self._ad_rows.copy() if self._ad_rows is not None else None
            if light:
                self._light_chunks += 1
            else:
                self._heavy_chunks += 1
            return loop.run_in_executor(
                self._executor,
                partial(_dispatch, tokens, lengths, key, window, tables,
                        decode_fn, counts_np, first=first, ad_np=ad_np),
            )

        out = await _submit(
            jnp.asarray(self._current), jnp.asarray(self._lengths),
            key1, _bucket_for(base_max), _grow_blocks(0), first=True,
        )
        chunk_index = 0
        if light or pen or not self._pipeline_on:
            # the SEQUENTIAL reference loop (also the light-load / penalty
            # posture): one chunk in flight at a time, burst torn down on
            # any finish — byte-identical greedy output is defined here,
            # and the pipelined loop below is equivalence-tested against it
            while True:
                chunk_t, chunk_lp, fetch_s = await loop.run_in_executor(
                    self._executor, partial(self._fetch_chunk, out[0], K)
                )
                gen_before = self.total_generated
                finished = self._process_chunk(chunk_t, chunk_lp, active)
                self._flight_record(
                    "decode", device_s=fetch_s,
                    tokens=self.total_generated - gen_before,
                    program=prog_q.pop(0) if prog_q else None,
                )
                await self._flush_emits(active)
                if self._burst_should_yield(finished):
                    return
                base_max += K
                chunk_index += 1
                # sequential: the chunk just processed is in _lengths, so
                # blocks grow with a fixed one-chunk lookahead
                out = await _submit(
                    out[1], out[2], self._split_key(),
                    _bucket_for(base_max), _grow_blocks(0),
                )

        async def _drain(out, expected, overlapped_s: float = 0.0) -> None:
            """Fetch + apply one dispatched chunk (the burst's tail or an
            all-finished over-run): identity-filtered so tokens never land
            on a request the slot no longer runs."""
            chunk_t, chunk_lp, fetch_s = await loop.run_in_executor(
                self._executor, partial(self._fetch_chunk, out[0], K)
            )
            gen_before = self.total_generated
            self._process_chunk(chunk_t, chunk_lp, active, expected=expected)
            self._flight_record(
                "decode", device_s=fetch_s, overlapped_s=overlapped_s,
                tokens=self.total_generated - gen_before,
                program=prog_q.pop(0) if prog_q else None,
            )
            await self._flush_emits(active)

        # the PIPELINED depth-2 loop: chunk N+1 executes on device while
        # the host fetches/processes chunk N under its shadow. Finished
        # slots' block releases are deferred to burst exit — an in-flight
        # chunk commits via the tables captured at its dispatch, and no
        # mid-burst allocation may reuse those blocks under it.
        self._defer_release = self.block_mgr is not None
        finished = False
        try:
            while True:
                if finished:
                    # device-side finished-slot mask: slots that completed
                    # inside chunk N freeze in place from the next dispatch
                    # on (the decode jit holds their token/length wherever
                    # ``active`` is False); their in-flight over-run tokens
                    # are discarded host-side and never billed
                    live = [
                        i for i in active
                        if self.slots[i].request is not None
                    ]
                    if not live:
                        await _drain(out, [None] * len(active))
                        return
                    if len(live) != len(active):
                        active = live
                        active_mask = np.zeros(self.config.slots, dtype=bool)
                        active_mask[active] = True
                        amask, temps, topks, topps = self._sampler_device(
                            active_mask
                        )
                # speculate the next chunk from device state
                base_max += K
                chunk_index += 1
                key_next = self._split_key()
                # pipelined: exactly one dispatched chunk is still
                # unprocessed when the speculative chunk is dispatched
                next_out_task = _submit(
                    out[1], out[2], key_next,
                    _bucket_for(base_max), _grow_blocks(1),
                )
                chunk_t, chunk_lp, fetch_s = await loop.run_in_executor(
                    self._executor, partial(self._fetch_chunk, out[0], K)
                )
                # the dispatch ran before the fetch on the single executor
                # thread, so this await resolves instantly — we just need
                # the in-flight chunk's handle for the readiness probes
                out = await next_out_task
                gen_before = self.total_generated
                # host work from here to the sample runs under chunk N+1's
                # device shadow — but credit it as overlapped only while
                # the device was ACTUALLY still executing (the readiness
                # probes below), or host-heavy workloads would overstate
                # the device share and overlap_ratio could never collapse
                t_overlap = time.monotonic()
                in_flight = not self._chunk_ready(out[0])
                finished = self._process_chunk(chunk_t, chunk_lp, active)
                await self._flush_emits(active)
                elapsed = time.monotonic() - t_overlap
                if not in_flight:
                    overlapped_s = 0.0  # device finished before we started
                elif not self._chunk_ready(out[0]):
                    overlapped_s = elapsed  # device outlived all our work
                else:
                    overlapped_s = elapsed / 2.0  # finished mid-span
                self._flight_record(
                    "decode", device_s=fetch_s,
                    overlapped_s=overlapped_s,
                    tokens=self.total_generated - gen_before,
                    program=prog_q.pop(0) if prog_q else None,
                )
                if self._burst_should_yield(finished, pipelined=True):
                    if not self._stop:
                        # carry the in-flight chunk across the burst
                        # boundary: the loop runs admission FIRST, so
                        # prefill dispatches interleave under this chunk's
                        # device execution, and _drain_pending applies it
                        # afterwards (identity-filtered per slot)
                        self._pending_chunk = (
                            out, list(active),
                            [self.slots[i].request for i in active], K,
                            prog_q.pop(0) if prog_q else None,
                        )
                        return
                    # stopping: nothing will drain a pending chunk — do it
                    # inline so the flight timeline stays contiguous
                    await _drain(
                        out, [self.slots[i].request for i in active]
                    )
                    return
        finally:
            self._defer_release = False
            if self._deferred_releases:
                for slot_id in self._deferred_releases:
                    self.block_mgr.release(slot_id)
                self._deferred_releases.clear()

    async def _drain_pending(self, loop) -> None:
        """Apply the decode chunk the previous pipelined burst left in
        flight. Runs AFTER admission in the engine loop, so the admission
        batch's prefill was dispatched under this chunk's device shadow
        (the "prefill interleave" overlap). Identity-filtered: a slot that
        finished and was re-admitted between the chunk's dispatch and now
        must not receive the old request's tokens."""
        pending = self._pending_chunk
        if pending is None:
            return
        self._pending_chunk = None
        out, active, expected, k_steps, program = pending
        chunk_t, chunk_lp, fetch_s = await loop.run_in_executor(
            self._executor, partial(self._fetch_chunk, out[0], k_steps)
        )
        gen_before = self.total_generated
        self._process_chunk(chunk_t, chunk_lp, active, expected=expected)
        self._flight_record(
            "decode", device_s=fetch_s,
            tokens=self.total_generated - gen_before,
            program=program,
        )
        await self._flush_emits(active)

    def _release_blocks(self, slot_id: int) -> None:
        """Free a finished slot's block reservation — immediately between
        bursts, DEFERRED to burst exit inside a pipelined burst (the
        in-flight chunk still commits via tables captured at dispatch;
        reusing its blocks mid-burst would land stale K/V on a live
        slot — between bursts the adopting prefill's overwrite makes the
        immediate release safe)."""
        if self.block_mgr is None:
            return
        # the slot's device-resident context row is dead with the request:
        # the next occupant re-syncs from host truth
        self._ctx_synced[slot_id] = 0
        if self._defer_release:
            self._deferred_releases.append(slot_id)
        else:
            self.block_mgr.release(slot_id)

    async def _advance_prefills(self, loop) -> None:
        """One bounded chunk of progress for every mid-prefill slot, batched
        through the continuation path. Intermediate chunks commit K/V only;
        the FINAL chunk's sampled token (from the prompt's last position) is
        the request's first generated token — the slot then joins decode."""
        # a cancelled caller's prefill stops here: release the slot AND its
        # worst-case block reservation instead of burning the remaining
        # chunks for a dead request (under paged backpressure that
        # reservation is exactly what blocks live admissions)
        for i, s in enumerate(self.slots):
            if s.prefilling and s.request.future.cancelled():
                self._journal_retire(s.request)
                self._adapter_release(s.request)
                s.request = None
                s.prefilling = False
                s.prefill_done = 0
                if self._ad_rows is not None:
                    self._ad_rows[i] = 0
                if self.block_mgr is not None:
                    self.block_mgr.release(i)
        pre = [i for i, s in enumerate(self.slots) if s.prefilling]
        if not pre:
            return
        C = self.config.prefill_chunk
        Bp = _pow2(len(pre))
        tokens = np.zeros((Bp, C), dtype=np.int32)
        starts = np.zeros(Bp, dtype=np.int32)
        suffix_lens = np.zeros(Bp, dtype=np.int32)
        slot_ids = np.zeros(Bp, dtype=np.int32)
        temps = np.zeros(Bp, dtype=np.float32)
        topks = np.zeros(Bp, dtype=np.int32)
        topps = np.ones(Bp, dtype=np.float32)
        for i in range(Bp):
            slot_id = pre[min(i, len(pre) - 1)]
            slot = self.slots[slot_id]
            request = slot.request
            chunk = request.context_tokens[
                slot.prefill_done : slot.prefill_done + C
            ]
            tokens[i, : len(chunk)] = chunk
            starts[i] = slot.prefill_done
            suffix_lens[i] = len(chunk)
            slot_ids[i] = slot_id
            temps[i] = request.temperature
            topks[i] = request.top_k
            topps[i] = request.top_p
        mode = self._sampler_mode(temps, topks, topps)
        nrb = self._read_blocks_for(max(int(starts.max()), 1))
        fn = self._prefill_continue_fn(mode, nrb)
        # the continuation variant re-traces per (rows, chunk, window) shape
        self._note_compile("prefill-continue", (mode, nrb, Bp, C))
        program = self._program_prefill_continue(nrb, Bp, C, mode)
        sel_np = self.block_mgr.tables[slot_ids]
        key = self._split_key()
        # adapter rows for the CHUNK batch rows (loop-thread snapshot,
        # RACE801); None when the store is disabled keeps the seed trace
        ad_np = (
            self._ad_rows[slot_ids].copy()
            if self._ad_rows is not None else None
        )

        def _run():
            self._fault("prefill")
            if self._lockstep is not None:
                self._lockstep.broadcast(
                    {
                        "op": "prefill_continue",
                        "sampler_mode": list(mode),
                        "nrb": nrb,
                        "tokens": tokens,
                        "starts": starts,
                        "lengths": suffix_lens,
                        "sel": sel_np,
                        "key": np.asarray(key),
                        "temps": temps,
                        "topks": topks,
                        "topps": topps,
                    }
                )
            ad_kw = (
                {}
                if ad_np is None
                else {"ad_layers": self._ad_layers,
                      "ad_ids": jnp.asarray(ad_np)}
            )
            out = fn(
                self.params, self.cache_k, self.cache_v,
                jnp.asarray(tokens), jnp.asarray(starts),
                jnp.asarray(suffix_lens), jnp.asarray(sel_np), key,
                jnp.asarray(temps), jnp.asarray(topks), jnp.asarray(topps),
                **ad_kw,
            )
            # the donated caches are re-bound HERE, on the dispatch thread
            # — the same side that reads them in every dispatch closure, so
            # cache_k/cache_v stay single-thread-role (RACE801)
            self.cache_k, self.cache_v = out[2], out[3]
            t_dev = time.monotonic()
            # the ONE per-dispatch sync, on the dispatch thread and timed
            # (the sample's device_ms); the token/logprob fetch rides the
            # same stop so the loop thread never blocks on the device
            # graftcheck: disable=JAX104 the one per-dispatch sync, moved off-loop and timed
            jax.block_until_ready(out)
            device_s = time.monotonic() - t_dev
            return np.asarray(out[0]), np.asarray(out[1]), device_s

        next_np, logprob_np, device_s = await loop.run_in_executor(
            self._executor, _run
        )
        now = time.monotonic()
        done_slots = []
        for i, slot_id in enumerate(pre):
            slot = self.slots[slot_id]
            request = slot.request
            slot.prefill_done += int(suffix_lens[i])
            if slot.prefill_done >= len(request.context_tokens):
                self._lengths[slot_id] = len(request.context_tokens)
                self._current[slot_id] = int(next_np[i])
                self._temps[slot_id] = request.temperature
                self._topks[slot_id] = request.top_k
                self._topps[slot_id] = request.top_p
                self._pres[slot_id] = request.presence_penalty
                self._freq[slot_id] = request.frequency_penalty
                if request.first_token_time is None:
                    # a resumed request keeps its ORIGINAL first-token
                    # time: TTFT measures the client-visible first token
                    request.first_token_time = now
                    self._journey(request, "first-token")
                slot.prefilling = False
                # register BEFORE emitting: a max-tokens=1 / instant-EOS
                # request is released inside _emit_token, and registering
                # against a released slot's empty table publishes nothing.
                # Resumed contexts stay out of the prefix cache — their
                # block chains mix generated content into what looks like
                # a prompt prefix. Adapter contexts stay out too: their
                # KV is adapter-colored (docs/ADAPTERS.md).
                if (
                    self.config.prefix_cache
                    and not request.preemptions
                    and not request.adapter
                ):
                    self.block_mgr.register_prefix(
                        slot_id, request.prompt_tokens
                    )
                self._emit_token(
                    slot_id, int(next_np[i]), float(logprob_np[i])
                )
                done_slots.append(slot_id)
                self._m_tokens(1)
        self._flight_record(
            "prefill", device_s=device_s, tokens=len(done_slots),
            program=program,
        )
        if done_slots:
            await self._flush_emits(done_slots)

    async def _admit(self, loop) -> None:
        """Admit queued requests in batched prefill calls (grouped by
        prompt-length bucket, count padded to a power of two by repeating
        the last row — a duplicate write of identical K/V is a no-op).

        With the paged prefix cache on, each request first matches its
        prompt against cached block chains; matched requests adopt the
        shared blocks and prefill only the SUFFIX (grouped by suffix-length
        bucket, dispatched through the continuation path)."""
        use_prefix = (
            self.block_mgr is not None and self.config.prefix_cache
        )
        while not self.scheduler.empty():
            free = [i for i, s in enumerate(self.slots) if s.free]
            if not free:
                return
            batch: list[tuple[int, _Request, int]] = []  # (slot, req, reuse)
            bucket = None
            while (
                not self.scheduler.empty()
                and len(batch) < min(len(free), self.config.prefill_batch)
            ):
                # the scheduler names the next admission candidate (FIFO
                # head by default; the WDRR-selected class head under QoS)
                request = self.scheduler.peek()
                if request is None:
                    break
                if request.future.cancelled():
                    self.scheduler.pop()  # caller gave up while queued
                    # the caller walked away — answered by cancellation,
                    # so a restart must not replay it
                    self._journal_retire(request)
                    continue
                if request.deadline is not None:
                    # deadline gate (docs/RESILIENCE.md): shed BEFORE
                    # any device work when the remaining budget cannot
                    # cover the admission estimate — an explicit
                    # 504-shaped refusal beats a silent late completion
                    left = remaining_s(request.deadline)
                    estimate = self._admit_estimate_s()
                    if left <= estimate:
                        self.scheduler.pop()
                        err = self._note_deadline_shed(
                            request, "admission", left, estimate
                        )
                        self._journal_retire(request)
                        if not request.future.done():
                            request.future.set_exception(err)
                        continue
                if self.adapter_store is not None and request.adapter:
                    # multi-LoRA resolve (docs/ADAPTERS.md): the request
                    # admits only once its adapter holds a device row.
                    # "wait" stashed it off-scheduler (like the prefix
                    # hydration stash), "refused" failed it loudly —
                    # both popped it, so the pass moves on.
                    verdict = await self._resolve_adapter(loop, request)
                    if verdict == "backpressure":
                        # every T0 row pinned by in-flight requests;
                        # finishing slots release pins — retry next pass
                        break
                    if verdict != "ready":
                        continue
                # one chain-digest walk per admission attempt, shared by
                # the hydration check, the promotion, and match_prefix
                # below — the admission path hashes the prompt ONCE
                chain = (
                    self.block_mgr.chain_digests(request.context_tokens)
                    if self.prefix_store is not None
                    and use_prefix
                    and not request.preemptions
                    and not request.adapter
                    else None
                )
                if (
                    chain is not None
                    and not request.hydrate_attempted
                    and not self._draining
                ):
                    # tiered prefix store: when the prompt's chain
                    # extends into T2 (object storage), stash the
                    # request OFF the queue while the background
                    # hydrator pulls the blobs into T1 — it requeues at
                    # class front the moment they land (or the timeout
                    # falls it back to cold compute). Never head-blocks:
                    # the loop moves on to the next admission candidate.
                    request.hydrate_attempted = True
                    missing = self._chain_t2_candidates(chain)
                    if missing and self.prefix_store.request_hydration(
                        missing
                    ):
                        self.scheduler.pop()
                        deadline = (
                            time.monotonic()
                            + self.prefix_store.spec.hydrate_timeout_s
                        )
                        self._prefix_hydrating.append(
                            (request, deadline, missing)
                        )
                        self.flight.event(
                            "prefix-hydrate", stage="begin",
                            blocks=len(missing),
                        )
                        self._journey(
                            request, "hydrate-begin", blocks=len(missing)
                        )
                        continue
                if self.block_mgr is not None and not self.block_mgr.can_admit(
                    len(request.prompt_tokens) + request.max_tokens + 1
                ):
                    # paged backpressure: the worst case doesn't fit the
                    # pool right now; finished slots will free reservations.
                    # (Requests that could NEVER fit are rejected up front in
                    # generate(), so this always unblocks eventually. The
                    # QoS loop may also preempt a lower-class victim to
                    # unblock this head — see _maybe_preempt.)
                    break
                # a resumed request's prefill content is its full context
                # (prompt + generated so far), rebuilding the KV state the
                # preemption dropped; untouched requests see ctx == prompt
                ctx = request.context_tokens
                # adapter requests bypass the shared prefix plane both
                # ways: their KV is colored by the adapter's attention
                # projections, so reusing a base/other-adapter chain
                # would splice foreign KV under this request — and
                # registering theirs would poison adapter-less traffic
                # (docs/ADAPTERS.md)
                if use_prefix and not request.preemptions \
                        and not request.adapter:
                    if chain is not None:
                        # promote the T1 run extending this prompt's T0
                        # chain back into pool blocks, so the match
                        # below sees the longer chain (docs/PREFIX.md)
                        await self._promote_prefix(loop, request, chain)
                    blocks, reuse = self.block_mgr.match_prefix(
                        ctx, digests=chain
                    )
                    if (
                        reuse
                        and len(ctx) - reuse
                        > self.config.prefix_cache_max_suffix
                    ):
                        # long suffix, small saving: the flash/ring full
                        # prefill beats the XLA continuation path
                        blocks, reuse = [], 0
                else:
                    blocks, reuse = [], 0
                to_prefill = len(ctx) - reuse
                if (
                    self.block_mgr is not None
                    and self.config.prefill_chunk > 0
                    and to_prefill > self.config.prefill_chunk
                ):
                    # chunked prefill: claim the slot + reservation now, but
                    # feed the prompt through _advance_prefills one bounded
                    # chunk per loop pass instead of one monolithic prefill
                    slot_id = free.pop(len(batch))
                    self.scheduler.pop()
                    self.block_mgr.admit(
                        slot_id,
                        len(request.prompt_tokens) + request.max_tokens + 1,
                    )
                    if blocks:
                        self.block_mgr.adopt_prefix(slot_id, blocks)
                    slot = self.slots[slot_id]
                    # slot claimed BEFORE the physical grow: an allocator
                    # failure below is then recoverable (a popped request
                    # in no slot would be invisible to every failure
                    # path). The chunked claim must undo ITSELF on a
                    # grow failure: a prefilling slot whose table never
                    # grew would scatter its chunks into the scratch
                    # block (silent corruption), and the shrink sweep
                    # deliberately leaves prefilling slots alone —
                    # requeue (or shed past the retry cap) HERE, then
                    # re-raise so the loop's shrink pass still adapts.
                    slot.request = request
                    slot.prefilling = True
                    slot.prefill_done = reuse
                    if self._ad_rows is not None:
                        self._ad_rows[slot_id] = request.adapter_row
                    try:
                        self._fault("pool-grow")
                        self.block_mgr.ensure_capacity(slot_id, len(ctx))
                    except Exception as e:
                        # monolithic members selected earlier this pass
                        # are popped + reserved but NOT yet slotted —
                        # invisible to every failure path (the shrink
                        # sweep and _fail_inflight both walk slots):
                        # undo them first, reservations released and
                        # requeued front in order
                        for sid, req, _r in reversed(batch):
                            self.block_mgr.release(sid)
                            self.scheduler.requeue_front(req)
                        batch.clear()
                        if not self._resource_exhausted(e):
                            raise
                        if request.preemptions >= _SHRINK_RETRY_CAP:
                            self._shed_stranded(slot_id, e)
                            self._shrink_inline_shed += 1
                        else:
                            self._preempt_slot(
                                slot_id, reason="pool-shrink"
                            )
                            self._shrink_inline_preempted += 1
                        raise
                    request.admit_time = time.monotonic()
                    self._note_resume(request)
                    self._journey(request, "admit", chunked=True)
                    if reuse:
                        self.prefix_hits += 1
                        self.prefix_tokens += reuse
                        self._m_prefix_hits(1)
                        self._m_prefix_tokens(reuse)
                    continue
                b = _bucket(to_prefill, hi=self.model_config.max_seq_len)
                if bucket is None:
                    bucket = b
                elif b != bucket:
                    break
                slot_id = free[len(batch)]
                self.scheduler.pop()
                if self.block_mgr is not None:
                    # reserve at pop time so the NEXT peek's can_admit sees
                    # this batch member's reservation
                    self.block_mgr.admit(
                        slot_id, len(request.prompt_tokens) + request.max_tokens + 1
                    )
                    if blocks:
                        self.block_mgr.adopt_prefix(slot_id, blocks)
                batch.append((slot_id, request, reuse))
            if not batch:
                return
            admit_now = time.monotonic()
            for slot_id, request, _reuse in batch:
                self.slots[slot_id].request = request
                if self._ad_rows is not None:
                    self._ad_rows[slot_id] = request.adapter_row
                request.admit_time = admit_now
                self._note_resume(request)
                self._journey(request, "admit")
            # physical grows AFTER every batch member owns its slot: an
            # allocator failure here is then recoverable by the shrink
            # pass's preempt-and-requeue sweep (a popped request in no
            # slot would be invisible to every failure path)
            if self.block_mgr is not None:
                self._fault("pool-grow")
                for slot_id, request, _reuse in batch:
                    self.block_mgr.ensure_capacity(
                        slot_id, len(request.context_tokens)
                    )
            Bp = _pow2(len(batch))
            use_continue = any(r > 0 for _, _, r in batch)
            padded = np.zeros((Bp, bucket), dtype=np.int32)
            lengths = np.zeros(Bp, dtype=np.int32)
            starts = np.zeros(Bp, dtype=np.int32)
            slot_ids = np.zeros(Bp, dtype=np.int32)
            temps = np.zeros(Bp, dtype=np.float32)
            topks = np.zeros(Bp, dtype=np.int32)
            topps = np.ones(Bp, dtype=np.float32)
            for i in range(Bp):
                slot_id, request, reuse = batch[min(i, len(batch) - 1)]
                suffix = request.context_tokens[reuse:]
                padded[i, : len(suffix)] = suffix
                lengths[i] = len(suffix)
                starts[i] = reuse
                slot_ids[i] = slot_id
                temps[i] = request.temperature
                topks[i] = request.top_k
                topps[i] = request.top_p
            key = self._split_key()
            prefill_mode = self._sampler_mode(temps, topks, topps)
            # per-batch-row adapter rows (loop-thread snapshot, RACE801)
            ad_np = (
                self._ad_rows[slot_ids].copy()
                if self._ad_rows is not None else None
            )

            if self.block_mgr is not None:
                # per-batch-row block tables (duplicate padded rows write
                # identical values to identical blocks — harmless)
                sel_np = self.block_mgr.tables[slot_ids]
            else:
                sel_np = slot_ids
            sel = jnp.asarray(sel_np)
            if use_continue:
                nrb = self._read_blocks_for(int(starts.max()))
                prefill_fn = self._prefill_continue_fn(prefill_mode, nrb)
                self._note_compile(
                    "prefill-continue", (prefill_mode, nrb, Bp, bucket)
                )
                program = self._program_prefill_continue(
                    nrb, Bp, bucket, prefill_mode
                )
            else:
                prefill_fn = self._prefill_fn(prefill_mode)
                # same Python variant, fresh XLA program per (bucket, rows)
                self._note_compile("prefill", (prefill_mode, bucket, Bp))
                program = self._program_prefill(bucket, Bp, prefill_mode)

            def _run():
                self._fault("prefill")
                if self._lockstep is not None:
                    desc = {
                        "sampler_mode": list(prefill_mode),
                        "tokens": padded,
                        "lengths": lengths,
                        "sel": np.asarray(sel_np),
                        "key": np.asarray(key),
                        "temps": temps,
                        "topks": topks,
                        "topps": topps,
                    }
                    if use_continue:
                        desc.update(
                            {"op": "prefill_continue", "starts": starts,
                             "nrb": nrb}
                        )
                    else:
                        desc["op"] = "prefill"
                    self._lockstep.broadcast(desc)
                if use_continue:
                    args = (
                        self.params, self.cache_k, self.cache_v,
                        jnp.asarray(padded), jnp.asarray(starts),
                        jnp.asarray(lengths), sel, key,
                        jnp.asarray(temps), jnp.asarray(topks),
                        jnp.asarray(topps),
                    )
                else:
                    args = (
                        self.params, self.cache_k, self.cache_v,
                        jnp.asarray(padded), jnp.asarray(lengths),
                        sel, key,
                        jnp.asarray(temps), jnp.asarray(topks),
                        jnp.asarray(topps),
                    )
                ad_kw = (
                    {}
                    if ad_np is None
                    else {"ad_layers": self._ad_layers,
                          "ad_ids": jnp.asarray(ad_np)}
                )
                variant = f"_cont_nrb{nrb}" if use_continue else ""
                self.profiler.dump_hlo(
                    f"prefill_p{bucket}_b{Bp}{variant}", prefill_fn, *args
                )
                out = prefill_fn(*args, **ad_kw)
                # donated caches re-bound on the dispatch thread — see
                # _advance_prefills._run (RACE801: single thread role)
                self.cache_k, self.cache_v = out[2], out[3]
                t_dev = time.monotonic()
                # same single sync the loop-thread np.asarray used to pay,
                # moved onto the dispatch thread so it can be timed; the
                # token/logprob fetch rides the same stop
                # graftcheck: disable=JAX104 the one per-dispatch sync, moved off-loop and timed
                jax.block_until_ready(out)
                device_s = time.monotonic() - t_dev
                return np.asarray(out[0]), np.asarray(out[1]), device_s

            next_np, logprob_np, device_s = await loop.run_in_executor(
                self._executor, _run
            )
            if use_prefix:
                for slot_id, request, reuse in batch:
                    if request.preemptions or request.adapter:
                        # resumed contexts stay out of the prefix cache
                        # (generated content is not a shareable prompt);
                        # adapter contexts too — their KV is colored by
                        # the adapter's projections (docs/ADAPTERS.md)
                        continue
                    self.block_mgr.register_prefix(
                        slot_id, request.prompt_tokens
                    )
                    if reuse:
                        self.prefix_hits += 1
                        self.prefix_tokens += reuse
                        self._m_prefix_hits(1)
                        self._m_prefix_tokens(reuse)
            now = time.monotonic()
            admitted_slots = []
            for i, (slot_id, request, _reuse) in enumerate(batch):
                self._lengths[slot_id] = len(request.context_tokens)
                self._current[slot_id] = int(next_np[i])
                self._temps[slot_id] = request.temperature
                self._topks[slot_id] = request.top_k
                self._topps[slot_id] = request.top_p
                self._pres[slot_id] = request.presence_penalty
                self._freq[slot_id] = request.frequency_penalty
                if request.first_token_time is None:
                    request.first_token_time = now
                    self._journey(request, "first-token")
                self._emit_token(slot_id, int(next_np[i]), float(logprob_np[i]))
                admitted_slots.append(slot_id)
            self._m_tokens(len(batch))
            self._flight_record(
                "prefill", device_s=device_s, tokens=len(batch),
                program=program,
            )
            await self._flush_emits(admitted_slots)

    def _process_chunk(
        self,
        chunk_tokens: np.ndarray,
        chunk_lps: np.ndarray,
        active: list[int],
        expected: list | None = None,
    ) -> bool:
        """Apply a chunk's tokens to host state; queue emissions. Returns
        True if any slot finished (→ admission opportunity).

        ``expected`` (the pipelined drain path) pins each slot to the
        request it ran when the chunk was dispatched: a slot re-admitted
        in between (the prefill-interleave window) silently drops the old
        request's over-run tokens instead of corrupting the new one."""
        K = chunk_tokens.shape[0]
        finished_any = False
        emitted_before = self.total_generated
        eos = self.tokenizer.eos_id
        for pos, slot_id in enumerate(active):
            slot = self.slots[slot_id]
            request = slot.request
            if request is None:
                continue
            if expected is not None and request is not expected[pos]:
                continue
            if (
                request.stop
                or request.on_token is not None
                or request.on_chunk is not None
                or request.future.cancelled()
            ):
                # slow path: per-token semantics (stop-string windows,
                # stream emissions, cancellation checks)
                for k in range(K):
                    if slot.request is None:
                        break  # finished mid-chunk; discard the tail
                    self._lengths[slot_id] += 1
                    token = int(chunk_tokens[k, slot_id])
                    self._current[slot_id] = token
                    if self._emit_token(
                        slot_id, token, float(chunk_lps[k, slot_id])
                    ):
                        finished_any = True
                continue
            # fast path — the saturated-decode hot loop: one numpy pass per
            # slot instead of K Python iterations (at 64 slots x 96 steps
            # the per-token loop costs hundreds of ms per chunk on the
            # single-threaded engine, rivaling the device time itself).
            # Exact same semantics as _emit_token for this request shape:
            # consume until eos / max-tokens / context-window, then finish.
            toks = chunk_tokens[:, slot_id]
            lengths0 = int(self._lengths[slot_id])
            # consuming the t-th token (1-based): finishes at t == remaining
            # (budget) or t == max_seq cap (window), whichever first
            fin_at = min(
                request.max_tokens - len(request.generated),
                self.model_config.max_seq_len - 1 - lengths0,
            )
            upto = min(K, max(fin_at, 0))
            eos_hits = np.nonzero(toks[:upto] == eos)[0]
            if eos_hits.size:
                consumed = int(eos_hits[0]) + 1
                n_gen = consumed - 1  # the eos token itself is not emitted
                done = True
            else:
                consumed = upto
                n_gen = consumed
                done = consumed == fin_at
            if consumed:
                request.generated.extend(toks[:n_gen].tolist())
                request.logprobs.extend(
                    chunk_lps[:n_gen, slot_id].tolist()
                )
                self.total_generated += consumed
                self._lengths[slot_id] += consumed
                self._current[slot_id] = int(toks[consumed - 1])
            if done:
                finished_any = True
                slot.request = None
                slot.prefilling = False
                slot.prefill_done = 0
                self._lengths[slot_id] = 0
                self._adapter_release(request)
                if self._ad_rows is not None:
                    self._ad_rows[slot_id] = 0
                self._release_blocks(slot_id)
                self._finished_requests.append(
                    (request, bool(eos_hits.size))
                )
        # one prometheus update per chunk, not per token (host hot path)
        self._m_tokens(self.total_generated - emitted_before)
        return finished_any

    def _emit_token(self, slot_id: int, token: int, logprob: float) -> bool:
        """Synchronous part of emission; async callbacks are deferred to
        :meth:`_flush_emits`. Returns True when the slot finished."""
        slot = self.slots[slot_id]
        request = slot.request
        if request is None:
            return False
        is_eos = token == self.tokenizer.eos_id
        if not is_eos:
            request.generated.append(token)
            request.logprobs.append(logprob)
        stop_matched = False
        if request.stop and not is_eos:
            # decode only a tail WINDOW per token — a full re-decode would
            # be O(n^2) per request on the single-threaded emit hot path.
            # Any new match must involve the newest token; every token
            # decodes from at least one UTF-8 byte, so a window of
            # max-stop-BYTES tokens (plus margin for tokenizer boundary
            # effects) always covers it — char count would undersize the
            # window for multi-byte stop strings under the byte-level
            # tokenizer (1 token per byte) and silently miss the stop. The
            # authoritative truncation re-finds on the full final decode in
            # _flush_emits.
            window = max(len(s.encode("utf-8")) for s in request.stop) + 8
            tail = self.tokenizer.decode(request.generated[-window:])
            if any(s in tail for s in request.stop):
                request.stop_matched = True
                stop_matched = True
        self.total_generated += 1
        done = bool(
            is_eos
            or stop_matched
            or len(request.generated) >= request.max_tokens
            or self._lengths[slot_id] + 1 >= self.model_config.max_seq_len
            # caller gave up (client disconnect / task cancel): stop
            # burning the slot on tokens nobody will read
            or request.future.cancelled()
        )
        # streaming consumers always get a final last=True emission (the
        # tokenizer hides the EOS id itself), so chunk streams terminate
        if request.on_token is not None or request.on_chunk is not None:
            self._pending_emits.append((request, token, logprob, done))
        if done:
            slot.request = None
            slot.prefilling = False
            slot.prefill_done = 0
            self._lengths[slot_id] = 0
            self._adapter_release(request)
            if self._ad_rows is not None:
                self._ad_rows[slot_id] = 0
            # release is safe while a speculative chunk is in flight (it
            # writes via the tables captured at its dispatch, and those
            # writes land before any re-allocation's prefill — single
            # executor thread); INSIDE a pipelined burst the release is
            # deferred to burst exit instead (see _release_blocks)
            self._release_blocks(slot_id)
            self._finished_requests.append((request, is_eos))
        return done

    def _final_text(self, request: _Request) -> str:
        """The authoritative completion text: full decode, truncated at
        the earliest stop match (OpenAI semantics — the match itself
        excluded). One helper so the finish path and the streaming final
        chunk produce byte-identical text."""
        text = self.tokenizer.decode(request.generated)
        if request.stop_matched:
            hits = [
                i for i in (text.find(s) for s in request.stop) if i >= 0
            ]
            if hits:
                text = text[: min(hits)]
        return text

    def _stream_text(self, request: _Request, is_final: bool) -> str:
        """The stream-safe decoded prefix of the generated text. Final →
        :meth:`_final_text` (so chunk deltas concatenate byte-identically
        to the non-streaming completion). Mid-stream → the full decode
        minus a trailing UTF-8 partial (the replacement char a cut
        multi-byte sequence renders as) and minus any tail that could
        still grow into a stop match — the same holdback contract the
        agents' _StreamAdapter keeps per token, applied per chunk."""
        if is_final:
            return self._final_text(request)
        text = self.tokenizer.decode(request.generated)
        if text.endswith("�"):
            text = text[:-1]
        if request.stop:
            hits = [
                i for i in (text.find(s) for s in request.stop) if i >= 0
            ]
            if hits:
                return text[: min(hits)]
            hold = 0
            for s in request.stop:
                for k in range(min(len(s) - 1, len(text)), 0, -1):
                    if s.startswith(text[-k:]):
                        hold = max(hold, k)
                        break
            if hold:
                text = text[: len(text) - hold]
        return text

    def _stream_tbt_hist(self, cls_name: str):
        """Per-QoS-class ``tbt_seconds`` histogram closure
        (``langstream_stream_tbt_seconds{agent_id="<class>"}`` — the
        class rides the reporter's agent_id label, the gateway's
        _count_throttle pattern). Lazily created on a class's first
        measured interval; class names are clamped to the QoS vocabulary
        so the map stays bounded. Streaming-configured engines only —
        the default scrape surface never grows."""
        h = self._m_tbt_hist.get(cls_name)
        if h is None:
            h = PrometheusMetricsReporter(
                prefix="langstream_stream", agent_id=cls_name
            ).exemplar_histogram(
                "tbt_seconds",
                "streaming inter-chunk interval (time between token "
                "deliveries) by QoS class",
            )
            self._m_tbt_hist[cls_name] = h
        return h

    def _stream_stall_threshold(self, cls_name: str) -> float:
        """The stall line for one class: its declared tbt-p99-s target
        when it has one, the engine-wide stream-stall-s default
        otherwise."""
        if self.config.qos is not None:
            tbt = self.config.qos.class_policy(cls_name).tbt_p99_s
            if tbt is not None:
                return tbt
        return self.config.stream_stall_s

    async def _deliver_chunk(
        self, request: _Request, is_final: bool, now: float
    ) -> None:
        """Deliver one committed decode chunk to the request's on_chunk
        consumer and record its telemetry. Runs at the burst-flush safe
        point between device dispatches — wait-free apart from awaiting
        the consumer itself (graftcheck STRM1501 polices this body the
        way OBS503 polices the emit hot loop)."""
        if request.stream_closed:
            return
        if request.future.cancelled():
            # the client is gone — deliver nothing; the finished drain
            # records the stream-cancel evidence below
            request.stream_closed = True
            return
        safe = self._stream_text(request, is_final)
        delta = safe[request.stream_sent_chars:]
        new_ids = request.generated[request.stream_sent_tokens:]
        if not delta and not new_ids and not is_final:
            return  # the holdback ate the whole chunk; nothing surfaced
        request.stream_sent_chars = max(
            request.stream_sent_chars, len(safe)
        )
        request.stream_sent_tokens = len(request.generated)
        if request.stream_tbt is not None:
            if request.stream_first_emit is None:
                request.stream_first_emit = now
                self._journey(request, "first-emit")
            else:
                interval = now - (request.stream_last_emit or now)
                request.stream_tbt.add(interval)
                digest = self._stream_tbt_by_class.get(request.priority)
                if digest is None:
                    digest = TbtDigest()
                    self._stream_tbt_by_class[request.priority] = digest
                digest.add(interval)
                self._stream_tbt_hist(request.priority)(
                    interval,
                    request.journey_id
                    if request.trace is not None
                    else None,
                )
                threshold = self._stream_stall_threshold(request.priority)
                if interval > threshold:
                    request.stream_stalls += 1
                    self.stream_stalls_total += 1
                    self.flight.event(
                        "stream-stall",
                        request=request.journey_id,
                        interval_s=round(interval, 6),
                        threshold_s=threshold,
                        priority=request.priority,
                        tokens=len(request.generated),
                    )
            request.stream_last_emit = now
            request.stream_emits += 1
            self.stream_emits_total += 1
        if is_final:
            request.stream_closed = True
            if request.stream_tbt is not None:
                # ONE summarized event per stream, never one per chunk
                # (a 4k-token stream would otherwise flood the ring)
                summary = request.stream_tbt.summary()
                self.flight.event(
                    "stream-emit",
                    request=request.journey_id,
                    emits=request.stream_emits,
                    tokens=len(request.generated),
                    tbt_p50_s=summary["p50"],
                    tbt_p99_s=summary["p99"],
                    tbt_max_s=summary["max"],
                    stalls=request.stream_stalls,
                    priority=request.priority,
                )
                self._journey(
                    request, "last-emit", emits=request.stream_emits
                )
        result = request.on_chunk(new_ids, delta, is_final)
        if asyncio.iscoroutine(result):
            await result

    async def _flush_emits(self, active: list[int]) -> None:
        emits, self._pending_emits = self._pending_emits, []
        # per-request chunk grouping, first-appearance order: on_token
        # subscribers keep exact per-token delivery; on_chunk subscribers
        # get ONE delivery per request per flush with everything that
        # committed in this burst
        chunks: "OrderedDict[int, list]" = OrderedDict()
        for request, token, logprob, done in emits:
            if request.on_token is not None:
                result = request.on_token(token, logprob, done)
                if asyncio.iscoroutine(result):
                    await result
            if request.on_chunk is not None:
                entry = chunks.get(id(request))
                if entry is None:
                    chunks[id(request)] = [request, done]
                elif done:
                    entry[1] = True
        if chunks:
            # one clock per flush: chunk emission is the granularity the
            # client observes, so inter-EMIT gaps are what TBT digests
            now = time.monotonic()
            for request, done in chunks.values():
                await self._deliver_chunk(request, done, now)
        # decode-pool first-step edge: the first NEW token after a KV
        # import closes the decode-admission segment (the emits list
        # above only carries on_token subscribers; imported handoffs
        # stream nothing, so the finished/slot scan below is the spot
        # that sees every request). One attribute check per emit batch.
        for slot in self.slots:
            request = slot.request
            if (
                request is not None
                and request.imported
                and not request.first_step_noted
                and len(request.generated) > request.import_base_tokens
            ):
                request.first_step_noted = True
                self._journey(request, "first-step")
        finished, self._finished_requests = self._finished_requests, []
        for request, is_eos in finished:
            # tenant tokens/s accounting (QoS post-debit): cancelled
            # requests debit too — their tokens burned engine capacity
            self.scheduler.on_finished(request)
            # crash-requeue journal: the request is ANSWERED (result,
            # cancellation — either way nothing is left to replay)
            self._journal_retire(request)
            if request.imported and not request.first_step_noted:
                # finished inside its first emit batch: the slot is
                # already released, so the scan above never saw it
                request.first_step_noted = True
                self._journey(request, "first-step")
            if request.future.cancelled():
                # aborted by the caller: not a served request — keep it out
                # of the request-rate/TTFT metrics (a disconnect storm must
                # not read as healthy throughput) and skip the decode
                if request.on_chunk is not None and self.config.streaming:
                    # disconnect-as-cancellation evidence: the slot was
                    # freed in _emit_token's done branch, i.e. within one
                    # chunk boundary of the cancel landing. tokens_wasted
                    # is the decode work nobody consumed (generated but
                    # never delivered — the engine-visible waste).
                    self.stream_cancels_total += 1
                    self.stream_reclaims_total += 1
                    self.flight.event(
                        "stream-cancel",
                        request=request.journey_id,
                        tokens_generated=len(request.generated),
                        tokens_delivered=request.stream_sent_tokens,
                        tokens_wasted=(
                            len(request.generated)
                            - request.stream_sent_tokens
                        ),
                        emits=request.stream_emits,
                        priority=request.priority,
                        tenant=request.tenant,
                        slot_reclaimed=True,
                    )
                self._journey(request, "cancelled")
                continue
            self.completed_requests += 1
            self._m_requests()
            if request.first_token_time is not None:
                self._m_ttft(request.first_token_time - request.enqueue_time)
            # OpenAI semantics: the stop match itself is excluded. The
            # token list keeps every generated token (they are in the
            # KV cache and were streamed); only the text truncates. The
            # find runs on the FINAL decode (the detection window can
            # render boundary chars differently) — shared with the
            # streaming final chunk so deltas concatenate to this exact
            # string.
            text = self._final_text(request)
            done_t = time.monotonic()
            first = request.first_token_time or done_t
            admit = request.admit_time or first
            if request.deadline is not None:
                # the deadline acceptance's second half: a request that
                # completes PAST its budget still answers (the work is
                # done; discarding it helps nobody) but the overrun is
                # recorded — never a silent late completion
                overrun = time.time() - request.deadline  # graftcheck: disable=OBS501 deadline overrun compares epoch stamps, not a latency
                if overrun > 0:
                    self.deadline_overruns += 1
                    self.flight.event(
                        "deadline-overrun",
                        overrun_s=round(overrun, 6),
                        tokens=len(request.generated),
                        tenant=request.tenant,
                    )
                    self._journey(
                        request, "deadline-overrun",
                        overrun_s=round(overrun, 6),
                    )
            timing = {
                "queue_wait": admit - request.enqueue_time,
                "prefill": first - admit,
                "ttft": first - request.enqueue_time,
                # decode phase + its step count: the bench derives achieved
                # step time from these (EOS can end a request well before
                # max_tokens, so the client can't know the step count)
                "decode": done_t - first,
                "tokens": float(len(request.generated)),
            }
            if request.imported:
                # KV-import admission skipped prefill entirely: the
                # marker the disagg acceptance asserts on (queue_wait/
                # prefill here are decode-pod-local and ~0 by design —
                # the prefill pool's share rode the handoff header)
                timing["imported"] = 1.0
            if request.stream_tbt is not None and request.stream_tbt.count:
                # bounded TBT record (p50/p99/max + count, NEVER the raw
                # interval list): what the gateway bench and perf_diff
                # read off request_timings
                summary = request.stream_tbt.summary()
                timing["tbt_p50"] = summary["p50"]
                timing["tbt_p99"] = summary["p99"]
                timing["tbt_max"] = summary["max"]
                timing["tbt_count"] = float(summary["count"])
            if not request.warmup:
                # warmup probes never enter the latency record: their TTFT
                # is XLA compile time, which would poison both the
                # cumulative histograms and the bench's request_timings
                # decomposition (a warmup_on_start engine created lazily
                # inside the measured window)
                self.request_timings.append(timing)
                # exemplar: a traced request's journey id rides the TTFT
                # bucket it lands in (None for untraced traffic — the
                # default scrape stays byte-identical)
                self._m_ttft_hist(
                    timing["ttft"],
                    request.journey_id
                    if request.trace is not None
                    else None,
                )
                self._m_queue_wait_hist(timing["queue_wait"])
                # SLO evidence (no-ops without a declared objective): a
                # served request is availability-good, and the tracker
                # judges the measured latencies against the declared
                # thresholds
                self._slo_record("availability", True)
                self._slo_record_latency("ttft", timing["ttft"])
                self._slo_record_latency("queue-wait", timing["queue_wait"])
                if (
                    request.stream_tbt is not None
                    and request.stream_tbt.count
                ):
                    # one tbt event per finished stream: the request's
                    # own p99 inter-emit interval, judged against (a)
                    # the engine-wide slo.tbt objective when declared
                    # and (b) the class's tbt-p99-s burn tracker — the
                    # health() tbt_burn predicate reads the latter
                    p99 = request.stream_tbt.quantile(0.99)
                    self._slo_record_latency("tbt", p99)
                    tracker = self._stream_slo.get(request.priority)
                    if tracker is not None:
                        verdict = tracker.record_latency(
                            "tbt", p99 * 1000.0
                        )
                        if verdict is not None and verdict["transition"]:
                            self.flight.event(
                                "alert",
                                objective=f"tbt:{request.priority}",
                                state=(
                                    "firing"
                                    if verdict["alerting"]
                                    else "resolved"
                                ),
                                burn_rate_fast=verdict["burn_rate_fast"],
                                burn_rate_slow=verdict["burn_rate_slow"],
                                budget_remaining=verdict[
                                    "budget_remaining"
                                ],
                                target=verdict["target"],
                            )
                            if verdict["alerting"]:
                                # the streaming SLO paged: capture at the
                                # breach, keyed per class so one flapping
                                # class can't spam (cooldown + dedup in
                                # the recorder; no-op without
                                # incident-dir)
                                self._incident_capture(
                                    "tbt-burn",
                                    {
                                        "source": "stream-slo",
                                        "objective": (
                                            f"tbt:{request.priority}"
                                        ),
                                        "tbt_p99_s": p99,
                                        "burn_rate_fast": verdict[
                                            "burn_rate_fast"
                                        ],
                                        "budget_remaining": verdict[
                                            "budget_remaining"
                                        ],
                                        "target": verdict["target"],
                                    },
                                    dedup_key=request.priority,
                                )
            self._journey(
                request, "finish",
                reason=(
                    "stop" if is_eos or request.stop_matched else "length"
                ),
                tokens=len(request.generated),
                model=self.config.model,
            )
            if request.trace is not None:
                # materialize the request's phases as child spans from the
                # timestamps above — no extra clocks in the decode loop,
                # and record_span never raises into the serving path
                svc = f"engine:{self.config.model}"
                record_span("engine.queue", svc, request.trace,
                            request.enqueue_time, admit)
                record_span("engine.prefill", svc, request.trace, admit, first,
                            attributes={
                                "prompt-tokens": len(request.prompt_tokens)
                            })
                record_span("engine.decode", svc, request.trace, first, done_t,
                            attributes={"tokens": len(request.generated)})
            if not request.future.done():
                request.future.set_result(
                    {
                        "tokens": request.generated,
                        "text": text,
                        "logprobs": request.logprobs,
                        "num_prompt_tokens": len(request.prompt_tokens),
                        "num_completion_tokens": len(request.generated),
                        "ttft": timing["ttft"],
                        "queue_wait": timing["queue_wait"],
                        "prefill": timing["prefill"],
                        "finish_reason": (
                            "stop"
                            if is_eos or request.stop_matched
                            else "length"
                        ),
                    }
                )


def flight_report(
    summary_only: bool = False, samples: int = 240
) -> list[dict[str, Any]]:
    """Flight-recorder payload for every live engine (the pod's ``/flight``
    and ``/flight/summary`` endpoints serve this; the control plane fans it
    in per application). One entry per engine: model, rollup summary, and —
    unless ``summary_only`` — the recent sample window and event tail."""
    with TpuServingEngine._instances_lock:
        engines = list(TpuServingEngine._instances.values())
    report: list[dict[str, Any]] = []
    for engine in engines:
        entry: dict[str, Any] = {
            "model": engine.config.model,
            "slots": engine.config.slots,
            "summary": engine.flight.summary(),
            # admission-policy state (per-class counters + tenant throttle
            # counts under QoS): included in /flight/summary too, so the
            # control-plane /qos route needs no extra engine surface
            "scheduler": engine.scheduler.stats(),
            # watchdog verdict (serving/health.py): rides /flight/summary
            # so the control-plane /health route and engine_top need no
            # extra engine surface — and a saved dump self-diagnoses a
            # wedge post mortem (engine_top --analyze)
            "health": engine.health(),
            # drain posture: the autoscaler's fan-in reads draining/shed
            # counts off the same summary (no extra engine surface)
            "drain": engine._drain_section(),
            # pool role + handoff counters: the router and per-pool
            # autoscalers classify replicas off this same summary
            "pool_role": engine.config.pool_role,
            "kvtransfer": engine.kv_transfer_section(),
            # device-survival posture (docs/RESILIENCE.md): the
            # autoscaler reads pool-shrink pressure off this same
            # summary, engine_top renders the survival panel from it
            "survival": engine.survival_section(),
        }
        if engine.prefix_store is not None:
            # tier hit/byte/budget posture: rides /flight/summary so
            # engine_top's prefix panel and the control-plane fan-in
            # need no extra engine surface
            entry["prefixstore"] = engine.prefix_store_section()
        if engine.adapter_store is not None:
            # multi-LoRA tier posture: rides /flight/summary so
            # engine_top's adapters panel and the router's affinity
            # fan-in need no extra engine surface
            entry["adapters"] = engine.adapter_store_section()
        if engine.config.streaming:
            # per-class TBT digests + the cancellation ledger: rides
            # /flight/summary so engine_top's streaming panel and
            # --analyze need no extra engine surface. Streaming-
            # configured engines only — the default payload stays
            # byte-identical (the non-streaming pin)
            entry["streaming"] = engine.streaming_section()
        if engine.config.speculative_drafts > 0:
            # fused decode-tail speculation posture: accept/uplift/
            # auto-disable state rides /flight/summary so engine_top's
            # speculation panel and --analyze thrash detection need no
            # extra engine surface. Spec-configured engines only — the
            # default payload stays byte-identical
            entry["speculative"] = engine.speculative_section()
        if engine.incidents is not None:
            # incident-capture posture (docs/OBSERVABILITY.md "Incident
            # bundles & exemplars"): rides /flight/summary so engine_top's
            # incidents panel and the control-plane fan-in need no extra
            # engine surface. Present only when incident-dir is
            # configured — the default payload stays byte-identical
            entry["incidents"] = {
                **engine.incidents.stats(),
                "recent": engine.incidents.list()[-4:],
            }
        slo = engine.slo_status()
        if slo is not None:
            entry["slo"] = slo
        if not summary_only:
            entry["samples"] = engine.flight.recent(samples)
            entry["events"] = engine.flight.recent_events()
        report.append(entry)
    return report


def attribution_report() -> list[dict[str, Any]]:
    """Per-engine device-attribution payloads for the pod
    ``/attribution`` and ``/memory`` endpoints and the control-plane
    fan-in. Wait-free by contract (graftcheck OBS505): the instance map
    is snapshotted WITHOUT ``_instances_lock`` — the same rationale as
    :func:`health_report` (a ledger poll during an incident must never
    queue behind an engine constructor holding the lock), and a torn
    read at worst misses a brand-new engine for one poll."""
    return [
        engine.attribution_section()
        for engine in list(TpuServingEngine._instances.values())
    ]


def health_report() -> list[dict[str, Any]]:
    """Per-engine health verdicts for the pod's ``/healthz``/``/ready``
    probes. Wait-free by contract (graftcheck OBS504): the instance map
    is snapshotted WITHOUT ``_instances_lock`` — a liveness probe must
    never queue behind an engine constructor/close holding it (the probe
    runs exactly when the process is suspect), and a torn read of the
    dict copy at worst reports an engine twice or a brand-new one not at
    all, both harmless for a health poll."""
    return [
        engine.health() for engine in list(TpuServingEngine._instances.values())
    ]


def incident_report(bundle_id: str | None = None) -> list[dict[str, Any]]:
    """Per-engine incident payloads for the pod ``GET /incidents``
    endpoint and the control-plane fan-in: the bounded bundle index per
    engine (plus capture stats), or — with ``bundle_id`` — the full
    bundle from whichever engine holds it. The instance map is
    snapshotted WITHOUT ``_instances_lock`` (the :func:`health_report`
    rationale — an evidence poll during an incident is exactly when the
    lock might be held); the recorder's own table lock is the serving
    thread's, never the hot path's."""
    report: list[dict[str, Any]] = []
    for engine in list(TpuServingEngine._instances.values()):
        rec = engine.incidents
        if rec is None:
            continue
        entry: dict[str, Any] = {"model": engine.config.model}
        if bundle_id is not None:
            bundle = rec.get(bundle_id)
            if bundle is None:
                continue
            entry["bundle"] = bundle
        else:
            entry["incidents"] = rec.list()
            entry["stats"] = rec.stats()
        report.append(entry)
    return report


def kick_warmups() -> None:
    """Begin warmup for every ``warmup_on_start`` engine that hasn't
    started it yet. The readiness probe calls this: a freshly scheduled
    serving pod compiles its variants inside the not-ready window
    instead of on the first real request, and ``/ready`` flips 200 only
    once the warmup task completes. Task creation only — non-blocking
    (OBS504); must run on the engines' event loop (in-pod there is one
    loop)."""
    for engine in list(TpuServingEngine._instances.values()):
        if (
            engine.config.warmup_on_start
            and engine._warmup_task is None
            and not engine._stop
        ):
            engine._warmup_begun()


async def drain_engines(grace_s: float = 30.0) -> dict[str, Any]:
    """Drain every live serving engine (the pod ``/drain`` endpoint and
    the k8s preStop hook land here): per-model drain reports, each with
    requeued/completed/shed counts. ``grace_s`` budgets the WHOLE pod,
    not each engine: every preStop/terminationGracePeriod/drain-HTTP
    timeout upstream is sized to one grace, so a multi-model pod must
    fit the same envelope — each engine drains under the time remaining
    to the shared deadline (a small floor keeps the last engines' sweep:
    their leftovers still fail explicitly, never silently). Engines
    drain sequentially — they share one event loop and one device, so a
    concurrent drain buys nothing and interleaves the flight evidence."""
    with TpuServingEngine._instances_lock:
        engines = list(TpuServingEngine._instances.values())
    deadline = time.monotonic() + grace_s
    reports: dict[str, Any] = {}
    for engine in engines:
        remaining = max(0.5, deadline - time.monotonic())
        reports[engine.config.model] = await engine.drain(remaining)
    return reports


def take_kv_export(request_id: str) -> dict[str, Any] | None:
    """Pop one KV handoff export entry — ``{"payload", "bytes",
    "trace", "journey", ...}`` — from whichever live engine holds it
    (the pod ``GET /kv/export/{request}`` handler; the stashed trace
    rides back as the response's ``langstream-trace`` header). Wait-free
    (POOL701): instance-map snapshot + one dict pop per engine."""
    for engine in list(TpuServingEngine._instances.values()):
        entry = engine.take_export_entry(request_id)
        if entry is not None:
            return entry
    return None


async def import_kv_handoff(
    payload: bytes,
    trace_header: str | None = None,
    deadline_header: str | None = None,
) -> dict[str, Any]:
    """Route one KV handoff payload to this pod's matching engine (the
    ``POST /kv/import`` handler): the header's fingerprint model picks
    the engine, decode-role engines first (a combined paged engine also
    accepts — the dev/test posture). ``trace_header`` is the pod HTTP
    request's ``langstream-trace`` value — the fallback trace parent
    when the payload header carries none. The result echoes the
    effective trace so the chainer (and the pod response header) can
    keep propagating it. Raises
    :class:`~langstream_tpu.serving.kvtransfer.LayoutMismatch` when no
    engine here can take it."""
    from langstream_tpu.serving.kvtransfer import LayoutMismatch, peek_header

    header = peek_header(payload)
    model = (header.get("fingerprint") or {}).get("model")
    candidates = [
        engine
        for engine in list(TpuServingEngine._instances.values())
        if engine.config.model == model
        and engine.block_mgr is not None
        and engine.config.pool_role != "prefill"
    ]
    if not candidates:
        raise LayoutMismatch(
            f"no decode-capable paged engine for model {model!r} in this pod"
        )
    candidates.sort(
        key=lambda e: 0 if e.config.pool_role == "decode" else 1
    )
    # the peeked header rides along so the token-list JSON parses once;
    # the pod's langstream-deadline request header is the fallback
    # budget when the wire header predates the deadline plane
    result = await candidates[0].import_handoff(
        payload, header=header, trace_header=trace_header,
        deadline=parse_deadline(deadline_header),
    )
    trace = header.get("trace") or trace_header
    if trace and "trace" not in result:
        result = {**result, "trace": trace}
    return result


def profile_engines(action: str, trace_dir: str | None = None) -> dict[str, bool]:
    """Start/stop jax.profiler capture on every live engine (the pod's
    ``/profile/{start,stop}`` debug endpoint drives this)."""
    if action not in ("start", "stop"):
        raise ValueError(f"unknown profile action {action!r} (start|stop)")
    with TpuServingEngine._instances_lock:
        engines = list(TpuServingEngine._instances.values())
    results: dict[str, bool] = {}
    for engine in engines:
        if action == "start":
            results[engine.config.model] = engine.profiler.start_trace(trace_dir)
        else:
            results[engine.config.model] = engine.profiler.stop_trace()
    return results


# ---------------------------------------------------------------------------
# embeddings
# ---------------------------------------------------------------------------


class EmbeddingEngine:
    """Batched encoder serving (drives ``compute-ai-embeddings``)."""

    _instances: dict[Any, "EmbeddingEngine"] = {}
    _instances_lock = threading.Lock()

    @classmethod
    def get_or_create(cls, model: str = "minilm-l6", tokenizer: str | None = None,
                      checkpoint: str | None = None, mesh: dict | None = None) -> "EmbeddingEngine":
        key = (model, tokenizer, checkpoint, tuple((mesh or {}).items()))
        with cls._instances_lock:
            if key not in cls._instances:
                cls._instances[key] = cls(model, tokenizer, checkpoint, mesh)
            return cls._instances[key]

    @classmethod
    def reset_instances(cls) -> None:
        with cls._instances_lock:
            cls._instances.clear()

    def __init__(self, model: str, tokenizer: str | None, checkpoint: str | None,
                 mesh: dict | None):
        if model in ("tiny", "tiny-encoder"):
            self.config = EncoderConfig.tiny()
        else:
            self.config = EncoderConfig.minilm_l6()
        self.tokenizer = load_tokenizer(tokenizer)
        if checkpoint:
            from langstream_tpu.models.encoder import load_from_sentence_transformers

            self.config, self.params = load_from_sentence_transformers(checkpoint)
        else:
            self.params = init_encoder_params(self.config)
        self.mesh = None
        if mesh:
            from langstream_tpu.parallel.mesh import make_mesh
            from jax.sharding import NamedSharding, PartitionSpec as P

            self.mesh = make_mesh(dict(mesh))
            specs = encoder_param_specs(self.config)
            self.params = jax.tree.map(
                lambda p, s: jax.device_put(p, NamedSharding(self.mesh, s)),
                self.params,
                specs,
                is_leaf=lambda x: isinstance(x, P),
            )
        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="tpu-embed")
        self._m_embeddings = PrometheusMetricsReporter(
            prefix="langstream_serving", agent_id=model
        ).counter("embeddings_total", "embedding vectors computed")
        cfg = self.config

        @jax.jit
        def _encode(params, tokens, mask):
            return encode(cfg, params, tokens, mask)

        self._encode_fn = _encode

    async def embed(self, texts: list[str]) -> list[list[float]]:
        if not texts:
            return []
        max_pos = self.config.max_position
        ids = [self.tokenizer.encode(t)[: max_pos] for t in texts]
        # clip ids into the encoder vocab (byte fallback on a tiny vocab)
        V = self.config.vocab_size
        ids = [[t % V for t in row] for row in ids]
        bucket = _bucket(max(len(r) for r in ids), lo=16, hi=max_pos)
        B = len(ids)
        # pad rows to a power of two: the time-flushed micro-batcher emits
        # arbitrary batch sizes, and compiling one encoder per exact size
        # is a mid-traffic compile per new size (tens of seconds on TPU) —
        # log2 buckets bound the variants. All-zero-mask padding rows are
        # safe (pooling and norm are guarded) and sliced off below.
        Bp = _pow2(B)
        tokens = np.zeros((Bp, bucket), dtype=np.int32)
        mask = np.zeros((Bp, bucket), dtype=np.int32)
        for i, row in enumerate(ids):
            tokens[i, : len(row)] = row
            mask[i, : len(row)] = 1
        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(
            self._executor,
            lambda: np.asarray(
                self._encode_fn(self.params, jnp.asarray(tokens), jnp.asarray(mask))
            ),
        )
        self._m_embeddings(len(texts))
        return out[:B].tolist()
