"""Deterministic fault-injection plane for the serving engine.

BENCH rounds r03/r04 were lost to the device outright (RESOURCE_EXHAUSTED
cascades; a pod unresponsive after 150 s) and nothing could *reproduce*
those failures on demand — every survival mechanism shipped untested
against the exact shape it exists for. This module makes device failure a
first-class, scriptable test input (docs/RESILIENCE.md):

- :class:`FaultPlan` — one declared fault: the engine **site** it fires at
  (``pool-grow`` / ``prefill`` / ``scatter`` / ``fetch``), how many passes
  through the site to skip first (``after``), how many times it fires
  before disarming (``count``), and its **shape** — ``oom`` raises a
  synthetic allocator failure whose message matches the real jaxlib
  RESOURCE_EXHAUSTED spellings, ``hang`` stalls the call for ``hang_ms``
  (the r03 unresponsive-device shape: the dispatch never returns, the
  watchdog heartbeat stops, ``/healthz`` must flip).
- :class:`FaultInjector` — the armed registry the engine's device-touching
  seams consult. Arming is explicit (``ServingConfig.faults`` or the
  ``LS_TPU_FAULTS`` env var, **tests and chaos drills only**); a
  production engine carries ``None`` and every seam check compiles down
  to one attribute test. Every fired fault is returned to the engine so
  it emits a ``fault-injected`` flight event — chaos assertions read the
  event ring, they never guess whether the fault actually landed.

Determinism contract: ``after``/``count`` are plain pass counters per
plan, bumped at the site (single-threaded per site: the engine loop or
the one dispatch thread), so a chaos test can aim a fault at exactly the
N-th pool-grow of a flood and get the same burst every run. The module
never imports jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

#: engine seams a plan may target (docs/RESILIENCE.md fault-site table).
#: The first four are device seams (PR 14); the network seams make the
#: cross-replica failure domain scriptable too — ``http-export`` /
#: ``http-import`` are the handoff chainer's pickup/offer HTTP calls
#: (serving/handoff.py), ``t2-get`` the prefix hydrator's object-storage
#: fetch (serving/prefixstore.py), ``route`` the replica router's pick
#: (gateway/router.py).
FAULT_SITES = (
    "pool-grow", "prefill", "scatter", "fetch",
    "http-export", "http-import", "t2-get", "route",
)

#: fault shapes: a synthetic allocator refusal, a stalled dispatch, and
#: the three network shapes — ``drop`` (connection refused/reset before
#: any HTTP answer), ``delay-ms`` (the call completes ``hang_ms`` late:
#: the deadline/timeout plane must absorb it), ``error`` (a synthetic
#: HTTP 500 — the pod answered, wrongly)
FAULT_SHAPES = ("oom", "hang", "drop", "delay-ms", "error")

#: shapes that stall for ``hang_ms`` and therefore require it > 0
_TIMED_SHAPES = ("hang", "delay-ms")

#: the default synthetic message — spelled like the real jaxlib failure so
#: the engine's ``_resource_exhausted`` classifier treats injected and
#: genuine faults identically (that equivalence is the whole point)
_DEFAULT_MESSAGE = "RESOURCE_EXHAUSTED: injected device allocator failure"


class InjectedFault(RuntimeError):
    """A synthetic device failure raised at an armed engine seam. Carries
    the site so the shrink machinery's evidence names where it fired."""

    def __init__(self, site: str, message: str):
        super().__init__(message)
        self.fault_site = site


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One declared fault (frozen/hashable: rides ``ServingConfig``)."""

    site: str
    shape: str = "oom"
    #: passes through the site to let through before the first fire
    after: int = 0
    #: times the fault fires before disarming (fail-then-recover)
    count: int = 1
    #: stall duration for ``shape="hang"`` (milliseconds)
    hang_ms: float = 0.0
    message: str = _DEFAULT_MESSAGE

    def __post_init__(self):
        if self.site not in FAULT_SITES:
            raise ValueError(
                f"fault site must be one of {list(FAULT_SITES)}, "
                f"got {self.site!r}"
            )
        if self.shape not in FAULT_SHAPES:
            raise ValueError(
                f"fault shape must be one of {list(FAULT_SHAPES)}, "
                f"got {self.shape!r}"
            )
        if self.after < 0:
            raise ValueError("fault after must be >= 0")
        if self.count < 1:
            raise ValueError("fault count must be >= 1")
        if self.shape in _TIMED_SHAPES and self.hang_ms <= 0:
            raise ValueError(f"{self.shape} faults need hang-ms > 0")

    def to_dict(self) -> dict[str, Any]:
        return {
            "site": self.site,
            "shape": self.shape,
            "after": self.after,
            "count": self.count,
            "hang-ms": self.hang_ms,
            "message": self.message,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FaultPlan":
        if isinstance(d, FaultPlan):
            return d
        if not isinstance(d, dict):
            raise ValueError(
                f"fault plan must be a mapping, got {type(d).__name__}"
            )
        return cls(
            site=str(d.get("site", "")),
            shape=str(d.get("shape", "oom")),
            after=int(d.get("after", 0)),
            count=int(d.get("count", 1)),
            hang_ms=float(d.get("hang-ms", d.get("hang_ms", 0.0))),
            message=str(d.get("message", _DEFAULT_MESSAGE)),
        )


def plans_from_env(env: dict | None = None) -> tuple[FaultPlan, ...]:
    """Parse ``LS_TPU_FAULTS`` (a JSON list of plan dicts) — the arm path
    for chaos drills against a deployed pod. Malformed JSON raises: a
    chaos run whose faults silently failed to arm would assert against a
    healthy engine and "pass"."""
    raw = (env if env is not None else os.environ).get("LS_TPU_FAULTS", "")
    if not raw.strip():
        return ()
    parsed = json.loads(raw)
    if not isinstance(parsed, list):
        raise ValueError("LS_TPU_FAULTS must be a JSON list of fault plans")
    return tuple(FaultPlan.from_dict(p) for p in parsed)


@dataclasses.dataclass
class FaultAction:
    """What the engine must do for one fired fault."""

    site: str
    shape: str
    hang_ms: float
    message: str
    #: 1-based fire index within the plan (event evidence)
    seq: int


class FaultInjector:
    """The armed per-engine registry. ``fire(site)`` is consulted at each
    seam pass — the seams span the engine loop AND the dispatch thread,
    so the pass/fire counters live under one tiny lock (uncontended:
    the two threads alternate by construction, and the injector only
    exists at all when a test armed it), returning the
    :class:`FaultAction` to perform or ``None``. One plan fires per pass
    even when several target the same site (deterministic ordering:
    declaration order)."""

    def __init__(self, plans: tuple[FaultPlan, ...]):
        import threading

        self.plans = tuple(plans)
        self._lock = threading.Lock()
        self._seen = [0] * len(self.plans)
        self._fired = [0] * len(self.plans)

    def fire(self, site: str) -> FaultAction | None:
        with self._lock:
            for i, plan in enumerate(self.plans):
                if plan.site != site:
                    continue
                self._seen[i] += 1
                if self._seen[i] <= plan.after:
                    continue
                if self._fired[i] >= plan.count:
                    continue  # disarmed: fail-then-recover
                self._fired[i] += 1
                return FaultAction(
                    site=site,
                    shape=plan.shape,
                    hang_ms=plan.hang_ms,
                    message=plan.message,
                    seq=self._fired[i],
                )
        return None

    def stats(self) -> list[dict[str, Any]]:
        with self._lock:
            return [
                {
                    "site": plan.site,
                    "shape": plan.shape,
                    "after": plan.after,
                    "count": plan.count,
                    "seen": self._seen[i],
                    "fired": self._fired[i],
                    "armed": self._fired[i] < plan.count,
                }
                for i, plan in enumerate(self.plans)
            ]
