"""Engine flight recorder: per-dispatch telemetry ring + stall attribution.

The vLLM-style engine stats loop, grown into a bounded time series: PR-2's
traces explain *one request's* journey; this module records *every
dispatched burst* the engine runs — the aggregate signal that localizes
systemic stalls (Dapper's lesson: per-request traces don't find the 16 ms
of host overhead that every step pays).

One :class:`FlightRecorder` per engine. The engine loop records a **sample**
per dispatched decode/prefill/verify burst and a **stall** sample for every
idle gap, so the samples tile the engine-loop timeline contiguously:

- ``wall_ms`` — time since the previous recorded boundary (the full slice
  of engine-loop wall clock this burst accounts for);
- ``device_ms`` — the slice of wall spent *under device execution*: the
  blocked device wait (measured at the dispatch's block boundary — the
  fetch/``block_until_ready`` call) PLUS any host work the pipelined loop
  ran in the shadow of an in-flight dispatch (``host_overlapped_ms``,
  also carried per sample). Host time hidden behind device compute costs
  nothing, so it is credited to the device-busy share rather than to
  host overhead — and reported separately so the overlap win is visible;
- ``host_overlapped_ms`` — the host share of ``device_ms``: detokenize/
  stop-check/emit work the pipelined loop ran while the next chunk
  executed on device (0 for the sequential loop). The engine bounds the
  credit with non-blocking device-readiness probes (``is_ready``), so
  host work that outlives the shadowing dispatch stays EXPOSED — a
  host-bound engine cannot masquerade as device-bound. Never
  double-counted: it lives inside ``device_ms``, never inside
  ``host_ms``;
- ``host_ms`` — ``wall − device`` (clamped ≥ 0): the *exposed* host time
  — Python dispatch, numpy packing, emit callbacks, block accounting
  that ran with the device idle — the "unattributed host overhead"
  bucket BENCH r05 could not see;
- ``stall`` — why queued work is not being admitted at this boundary
  (``no-free-slot`` / ``no-kv-blocks`` / ``prefill-in-flight`` /
  ``queue-empty``), plus batch occupancy, queue depth, tokens emitted,
  KV-pool reserved ratio (the admission pressure), prefix-cache hits,
  and speculative accept/reject.

Because the samples tile the timeline, the rollup decomposes total wall
time **exactly** into ``device + host + stall`` — the property the bench
acceptance checks against its own measured wall clock. Stall attribution
is kept in two disjoint dictionaries so a saturated engine never reads
as "stalled": ``stall_s_by_reason`` (engine-loop idle time; sums to
``stall_ms``) vs ``blocked_s_by_reason`` (busy-dispatch wall during
which queued work could not be admitted — queue pressure).

Discrete **events** ride a second small ring: ``recompile`` (a jit variant
or prefill bucket compiled for the first time — the 30 s mid-traffic
convoy-maker on TPU), ``pool-grow`` (decode-time KV block allocation),
``warmup``, ``preempt`` (a QoS preemption under KV pressure, or in-flight
work failed — the ``reason`` field tells them apart), ``resume`` (a
preempted request re-admitted), ``shed`` (a request refused by QoS
policy: tenant throttle or full class queue), ``lockstep-divergence``,
``health`` (a watchdog state transition — ok/degraded/wedged, with the
stall evidence; serving/health.py), ``alert`` (an SLO objective's
multi-window burn rate crossed the page threshold, or recovered), and
the device-survival plane's events (docs/RESILIENCE.md): ``pool-shrink``
(a device allocator failure shrank the KV admission budget — site,
withheld/freed bytes, victims preempted, the new budget),
``pool-restore`` (the recovery probe returned a shrink quantum),
``fault-injected`` (a chaos-drill fault fired at an engine seam —
serving/faults.py), and ``journal-replay``/``journal-evict`` (the
crash-requeue journal replayed recovered work / shed its oldest entry
at the bound).
Under a QoS scheduler each sample additionally carries ``queue_by_class``
(per-priority-class queue depths — what ``engine_top --analyze`` watches
for sustained interactive-class growth).

Hot-path discipline (graftcheck rule OBS503 gates this): the record path
is append-only on GIL-atomic deques — **no locks, no I/O, nothing that can
block the engine loop**. Rollups snapshot with ``list(deque)``.

Sizing: ``LS_TPU_FLIGHT_BUFFER`` samples (default 4096, min 64). Cumulative
totals (wall/device/host/stall, per-phase step counts, stall seconds by
reason, token counts) are plain counters maintained alongside the ring, so
the rollup stays exact even after the ring starts evicting; percentiles
and rates come from the retained window.

Exposure: the pod serves ``/flight`` (recent samples + events + rollup)
and ``/flight/summary`` next to ``/metrics`` and ``/traces``; the control
plane fans pods in under ``/api/applications/{t}/{n}/flight``; and
``tools/engine_top.py`` renders the same payload as a live console or a
post-mortem breakdown. See ``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any

#: admission-stall reasons a sample may carry (the attribution vocabulary)
STALL_REASONS = (
    "no-free-slot",
    "no-kv-blocks",
    "prefill-in-flight",
    "queue-empty",
)

#: dispatch phases (a "stall" sample is the fifth, non-dispatch kind)
PHASES = ("prefill", "decode", "verify")


def _buffer_size() -> int:
    try:
        return max(64, int(os.environ.get("LS_TPU_FLIGHT_BUFFER", "4096")))
    except ValueError:
        return 4096


def _pct(sorted_values: list, q: float):
    """Nearest-rank percentile of an already-sorted list (None when empty)."""
    if not sorted_values:
        return None
    return sorted_values[min(len(sorted_values) - 1, int(q * len(sorted_values)))]


class FlightRecorder:
    """Bounded per-engine telemetry ring. Single writer (the engine loop;
    events may also arrive from the dispatch thread), many readers."""

    def __init__(self, slots: int = 0, maxlen: int | None = None):
        self.slots = slots
        self.capacity = maxlen if maxlen is not None else _buffer_size()
        self._samples: deque[dict[str, Any]] = deque(maxlen=self.capacity)
        self._events: deque[dict[str, Any]] = deque(maxlen=512)
        self._seq = 0
        self._event_seq = 0
        self._last_mark = time.monotonic()
        # cumulative counters: exact over the engine's whole life, immune
        # to ring eviction (plain attributes — engine loop is the only
        # sample writer, and CPython attribute updates don't interleave)
        self.recorded = 0
        self.wall_ms = 0.0
        self.device_ms = 0.0
        self.host_ms = 0.0
        self.host_overlapped_ms = 0.0
        self.stall_ms = 0.0
        self.tokens = 0
        self.recompiles = 0
        self.steps_by_phase: dict[str, int] = {}
        # two distinct attributions (they must not be conflated, or a
        # saturated engine reads as 100% stalled):
        # - stall_s_by_reason: engine-loop STALL time (stall samples only)
        #   — decomposes totals.stall_ms exactly;
        # - blocked_s_by_reason: wall time of dispatch samples annotated
        #   with an admission-stall reason — the engine was BUSY, but
        #   queued work waited that long for that reason (queue pressure)
        self.stall_s_by_reason: dict[str, float] = {}
        self.blocked_s_by_reason: dict[str, float] = {}
        self.events_by_type: dict[str, int] = {}
        self.spec_accepted = 0
        self.spec_rejected = 0

    # -- recording (engine hot path: appends + counter bumps only) -------

    def mark(self) -> None:
        """Reset the timeline boundary (e.g. when the engine loop starts
        after a long construction gap, so the gap isn't billed as host)."""
        self._last_mark = time.monotonic()

    def sample(
        self,
        phase: str,
        *,
        device_s: float = 0.0,
        overlapped_s: float = 0.0,
        tokens: int = 0,
        occupancy: int = 0,
        queue_depth: int = 0,
        stall: str | None = None,
        kv_used: float | None = None,
        prefix_hits: int = 0,
        spec_accepted: int = 0,
        spec_rejected: int = 0,
        queue_by_class: dict[str, int] | None = None,
        program: str | None = None,
    ) -> dict[str, Any]:
        """Record one dispatched burst. ``wall`` is the time since the
        previous boundary. ``overlapped_s`` is host work the pipelined
        loop ran under an in-flight dispatch's device shadow: it is
        credited to the device-busy share (``device = wait + overlapped``,
        clamped to wall) and reported per sample, so
        ``host = wall − device`` stays the *exposed* host time and the
        wall decomposition remains exact. ``queue_by_class`` (QoS engines
        only) keeps the sample schema unchanged for FIFO engines by being
        omitted when None. ``program`` keys the sample by the compiled
        program variant that ran (the attribution ledger's id,
        serving/attribution.py) — omitted when unknown so pre-attribution
        consumers see an unchanged schema."""
        now = time.monotonic()
        wall_ms = (now - self._last_mark) * 1000.0
        self._last_mark = now
        wait_ms = max(0.0, min(device_s * 1000.0, wall_ms))
        overlapped_ms = max(0.0, min(overlapped_s * 1000.0, wall_ms - wait_ms))
        device_ms = wait_ms + overlapped_ms
        host_ms = wall_ms - device_ms
        self._seq += 1
        entry: dict[str, Any] = {
            "seq": self._seq,
            # wall-clock anchor for display alignment across pods only;
            # every duration above is monotonic
            # graftcheck: disable=OBS501 display anchor, never subtracted
            "t_ms": round(time.time() * 1000.0, 3),
            "phase": phase,
            "wall_ms": round(wall_ms, 3),
            "device_ms": round(device_ms, 3),
            "host_ms": round(host_ms, 3),
            "host_overlapped_ms": round(overlapped_ms, 3),
            "occupancy": occupancy,
            "slots": self.slots,
            "tokens": tokens,
            "queue_depth": queue_depth,
            "stall": stall,
            "kv_used": round(kv_used, 4) if kv_used is not None else None,
            "prefix_hits": prefix_hits,
        }
        if spec_accepted or spec_rejected:
            entry["spec_accepted"] = spec_accepted
            entry["spec_rejected"] = spec_rejected
        if queue_by_class is not None:
            entry["queue_by_class"] = dict(queue_by_class)
        if program is not None:
            entry["program"] = program
        self._samples.append(entry)
        self.recorded += 1
        self.wall_ms += wall_ms
        self.device_ms += device_ms
        self.host_ms += host_ms
        self.host_overlapped_ms += overlapped_ms
        self.tokens += tokens
        self.steps_by_phase[phase] = self.steps_by_phase.get(phase, 0) + 1
        if stall:
            # the engine dispatched work this slice, so this is BLOCKED
            # (queued work waiting while busy), not engine stall
            self.blocked_s_by_reason[stall] = (
                self.blocked_s_by_reason.get(stall, 0.0) + wall_ms / 1000.0
            )
        self.spec_accepted += spec_accepted
        self.spec_rejected += spec_rejected
        return entry

    def stall(
        self,
        reason: str,
        *,
        occupancy: int = 0,
        queue_depth: int = 0,
        kv_used: float | None = None,
        queue_by_class: dict[str, int] | None = None,
    ) -> dict[str, Any]:
        """Record an idle/blocked gap (no dispatch): its whole wall slice
        is stall time attributed to ``reason``."""
        now = time.monotonic()
        wall_ms = (now - self._last_mark) * 1000.0
        self._last_mark = now
        self._seq += 1
        entry: dict[str, Any] = {
            "seq": self._seq,
            # graftcheck: disable=OBS501 display anchor, never subtracted
            "t_ms": round(time.time() * 1000.0, 3),
            "phase": "stall",
            "wall_ms": round(wall_ms, 3),
            "device_ms": 0.0,
            "host_ms": 0.0,
            "host_overlapped_ms": 0.0,
            "occupancy": occupancy,
            "slots": self.slots,
            "tokens": 0,
            "queue_depth": queue_depth,
            "stall": reason,
            "kv_used": round(kv_used, 4) if kv_used is not None else None,
            "prefix_hits": 0,
        }
        if queue_by_class is not None:
            entry["queue_by_class"] = dict(queue_by_class)
        self._samples.append(entry)
        self.recorded += 1
        self.wall_ms += wall_ms
        self.stall_ms += wall_ms
        self.stall_s_by_reason[reason] = (
            self.stall_s_by_reason.get(reason, 0.0) + wall_ms / 1000.0
        )
        return entry

    def event(self, kind: str, **detail: Any) -> None:
        """Record a discrete event (recompile / pool-grow / warmup /
        preempt / lockstep-divergence). Safe from any thread."""
        self.events_by_type[kind] = self.events_by_type.get(kind, 0) + 1
        if kind == "recompile":
            self.recompiles += 1
        # per-recorder monotonic event sequence: same-millisecond events
        # stay totally ordered, so tail consumers (the watchdog's 256-event
        # window, incident capture) dedup by seq instead of timestamp ties
        self._event_seq += 1
        self._events.append(
            {
                "seq": self._event_seq,
                # graftcheck: disable=OBS501 display anchor, never subtracted
                "t_ms": round(time.time() * 1000.0, 3),
                # monotonic stamp for the live health predicates
                # (serving/health.py recompile_storm): recency judgments
                # must survive NTP steps, which t_ms cannot
                "m_s": round(time.monotonic(), 3),
                "kind": kind,
                **detail,
            }
        )

    # -- reading (snapshots; never block the writer) ---------------------
    #
    # Cross-thread safety: readers snapshot with list(deque) / dict(d) —
    # single C-level copies of containers holding plain dicts, which never
    # release the GIL or call back into Python, so a concurrent append
    # from the engine loop or dispatch thread cannot interleave mid-copy.
    # All derived math then runs on the snapshot.

    def recent(self, n: int = 240) -> list[dict[str, Any]]:
        samples = list(self._samples)
        return samples[-n:] if n else samples

    def recent_events(self, n: int = 64) -> list[dict[str, Any]]:
        events = list(self._events)
        return events[-n:] if n else events

    @property
    def dropped(self) -> int:
        """Samples evicted from the ring (0 until ``recorded`` exceeds
        ``LS_TPU_FLIGHT_BUFFER``)."""
        return self.recorded - len(self._samples)

    def summary(self) -> dict[str, Any]:
        """Rollup: exact cumulative totals + window percentiles/rates.

        ``totals.device_ms + totals.host_ms + totals.stall_ms ==
        totals.wall_ms`` by construction — the decomposition the bench
        acceptance compares against its measured wall clock.
        """
        window = list(self._samples)
        dispatch = [s for s in window if s["phase"] != "stall"]
        walls = sorted(s["wall_ms"] for s in dispatch)
        hosts = sorted(s["host_ms"] for s in dispatch)
        devices = sorted(s["device_ms"] for s in dispatch)
        overlaps = sorted(
            s.get("host_overlapped_ms", 0.0) for s in dispatch
        )
        # window overlap ratio: the share of host work the pipelined loop
        # hid behind device compute (None when the window did no host work)
        overlapped_sum = sum(overlaps)
        host_sum = overlapped_sum + sum(hosts)
        overlap_ratio = (
            round(overlapped_sum / host_sum, 4) if host_sum > 0 else None
        )
        queue_depths = sorted(s["queue_depth"] for s in window)
        # the samples tile the timeline, so the retained window's span is
        # the (monotonic) sum of its wall slices — no wall-clock arithmetic
        span_s = sum(s["wall_ms"] for s in window) / 1000.0
        window_tokens = sum(s["tokens"] for s in dispatch)
        kv_last = next(
            (s["kv_used"] for s in reversed(window) if s["kv_used"] is not None),
            None,
        )
        out: dict[str, Any] = {
            "capacity": self.capacity,
            "recorded": self.recorded,
            "dropped": self.dropped,
            "totals": {
                "wall_ms": round(self.wall_ms, 3),
                "device_ms": round(self.device_ms, 3),
                "host_ms": round(self.host_ms, 3),
                "host_overlapped_ms": round(self.host_overlapped_ms, 3),
                "stall_ms": round(self.stall_ms, 3),
                "tokens": self.tokens,
                "steps_by_phase": dict(self.steps_by_phase),
                "stall_s_by_reason": {
                    k: round(v, 4) for k, v in self.stall_s_by_reason.items()
                },
                "blocked_s_by_reason": {
                    k: round(v, 4)
                    for k, v in self.blocked_s_by_reason.items()
                },
                "recompiles": self.recompiles,
                "events_by_type": dict(self.events_by_type),
                "spec_accepted": self.spec_accepted,
                "spec_rejected": self.spec_rejected,
            },
            "window": {
                "samples": len(window),
                "span_s": round(span_s, 3),
                "tokens": window_tokens,
                "tok_s": round(window_tokens / span_s, 1) if span_s else None,
                "step_ms_p50": _pct(walls, 0.50),
                "step_ms_p95": _pct(walls, 0.95),
                "host_overhead_ms_p50": _pct(hosts, 0.50),
                # the pipelined-loop naming of the same split: exposed =
                # host_ms (kept under its legacy key above for old
                # consumers), overlapped = host work under device shadow
                "host_exposed_ms_p50": _pct(hosts, 0.50),
                "host_overlapped_ms_p50": _pct(overlaps, 0.50),
                "overlap_ratio": overlap_ratio,
                "device_ms_p50": _pct(devices, 0.50),
                "queue_depth_p95": _pct(queue_depths, 0.95),
                "occupancy_mean": (
                    round(sum(s["occupancy"] for s in dispatch) / len(dispatch), 2)
                    if dispatch
                    else None
                ),
                "kv_used_ratio_last": kv_last,
            },
        }
        return out


def bench_rollup(summary: dict[str, Any]) -> dict[str, Any]:
    """The subset of a flight summary a bench record snapshots (BENCH_r06
    keys — enough for ``engine_top --analyze`` to decompose a run)."""
    totals = summary.get("totals", {})
    window = summary.get("window", {})
    return {
        "host_overhead_ms_p50": window.get("host_overhead_ms_p50"),
        "host_exposed_ms_p50": window.get("host_exposed_ms_p50"),
        "overlap_ratio": window.get("overlap_ratio"),
        "step_ms_p50": window.get("step_ms_p50"),
        "stall_s_by_reason": totals.get("stall_s_by_reason"),
        "blocked_s_by_reason": totals.get("blocked_s_by_reason"),
        "queue_depth_p95": window.get("queue_depth_p95"),
        "recompile_count": totals.get("recompiles"),
        "totals": {
            k: totals.get(k)
            for k in (
                "wall_ms",
                "device_ms",
                "host_ms",
                "host_overlapped_ms",
                "stall_ms",
                "tokens",
                "steps_by_phase",
            )
        },
    }
