"""Cross-replica failure domain: deadlines, retry/re-route, breakers.

PR 11 built the KV handoff plane and PR 14 made a single engine survive
its own device — but the moment a request crosses a replica boundary
(prefill→decode KV handoff, T2 prefix hydration, router-stamped record
bounces) there was no deadline, no retry/backoff discipline, and no
breaker: a decode pod that died mid-handoff stranded the export forever.
This module is the distributed-resilience plane (docs/RESILIENCE.md
"Distributed failure domain"):

- **End-to-end deadlines** — the ``langstream-deadline`` header value is
  an absolute wall-clock epoch timestamp (seconds, decimal string),
  stamped at the gateway from a per-class QoS default or a client value
  and carried through record headers, the kvtransfer wire header, and
  ``/kv/import``. :func:`remaining_s` clamps to non-negative (a skewed
  clock must read as "expired now", never as a negative socket timeout),
  and :func:`socket_timeout_s` derives every cross-replica HTTP call's
  timeout from the remaining budget — a call that cannot finish inside
  the deadline is not worth starting.
- **Retry with re-route** — :class:`RetryPolicy` is capped exponential
  backoff with *deterministic* jitter (hashed from the request id +
  attempt, so a chaos run replays the exact same schedule) honoring
  ``Retry-After`` hints.
- **Circuit breakers** — :class:`CircuitBreaker` is the classic
  CLOSED→OPEN→HALF_OPEN→CLOSED machine over a rolling failure window;
  the router holds one per replica (gateway/router.py) so a dead decode
  pod is excluded from ``pick`` until a half-open probe proves it back.
- **The handoff chainer** — :class:`HandoffChainer` drives one exported
  handoff to completion: POST the payload to the router's decode pick,
  re-offer to the next healthy replica on 404/timeout/refused with
  backoff, and after the cap fall back to **local decode** of the
  payload on the prefill engine itself (the serialized snapshot is the
  complete state, so the slot rejoins the combined path byte-identically
  — the same invariant the QoS preemption resume proved).

Failure taxonomy the chainer enforces (docs/DISAGG.md refusal table):
409 (layout mismatch) and 504 (deadline exceeded) are *terminal* — no
sibling replica will answer differently; 503 + Retry-After is a *hold* —
that replica is not re-offered until the hint elapses; timeouts and
connection errors are *breaker food* — retried elsewhere, counted
against the replica's window.

Stdlib-only except for the optional aiohttp default transport (resolved
lazily); never imports jax. Every synchronous method is dict/float
arithmetic — the breaker and deadline helpers run on produce/admission
hot paths.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import logging
import time
from typing import Any, Awaitable, Callable

log = logging.getLogger(__name__)

#: record/HTTP header carrying the absolute wall-clock deadline (epoch
#: seconds, decimal string). Wall clock, not monotonic: the value must
#: mean the same thing on every replica that reads it.
DEADLINE_HEADER = "langstream-deadline"

#: floor/cap for deadline-derived socket timeouts: the floor keeps a
#: nearly-expired budget from degenerating into a 0-second connect (the
#: refusal should come from the deadline check, not ECONNABORTED); the
#: cap bounds deadline-less calls so NET1201's no-timeout class can
#: never reappear through this helper
SOCKET_TIMEOUT_FLOOR_S = 0.05
SOCKET_TIMEOUT_CAP_S = 30.0


class DeadlineExceeded(Exception):
    """The request's end-to-end budget is spent (or provably cannot
    cover the work about to be dispatched). 504-shaped by contract:
    the pod ``/kv/import`` handler maps it to HTTP 504 and the engine
    refuses BEFORE any device work — never a silent late completion."""

    def __init__(self, detail: str = "", overrun_s: float = 0.0):
        super().__init__(detail or "deadline exceeded")
        self.overrun_s = overrun_s


def parse_deadline(value: Any) -> float | None:
    """An epoch-seconds deadline out of a header/option value, or None.
    Malformed values are None, never an error — a garbage deadline must
    degrade to "no deadline", not refuse a request the budget allows."""
    if value is None:
        return None
    try:
        deadline = float(value)
    except (TypeError, ValueError):
        return None
    return deadline if deadline > 0 else None


def remaining_s(deadline: float | None, now: float | None = None) -> float | None:
    """Seconds of budget left (None = no deadline). Clamped to >= 0:
    clock skew between replicas can put a freshly-stamped deadline in
    this host's past, and a negative budget must read "expired now" —
    never flow into a timeout/backoff computation as a negative."""
    if deadline is None:
        return None
    # graftcheck: disable=OBS501 deadlines are wall-clock epoch stamps by design
    return max(0.0, deadline - (time.time() if now is None else now))


def socket_timeout_s(
    deadline: float | None,
    now: float | None = None,
    floor: float = SOCKET_TIMEOUT_FLOOR_S,
    cap: float = SOCKET_TIMEOUT_CAP_S,
) -> float:
    """The socket timeout one cross-replica HTTP call may spend: the
    remaining deadline budget, floored (a near-expired budget still gets
    a real connect; the deadline check itself does the refusing) and
    capped (deadline-less calls must still carry an explicit bound —
    graftcheck NET1201 polices the unbounded spelling)."""
    left = remaining_s(deadline, now)
    if left is None:
        return cap
    return max(floor, min(left, cap))


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with deterministic jitter.

    ``attempts`` bounds the re-offers before the chainer falls back to
    local decode. Jitter is hashed from ``(key, attempt)`` instead of
    drawn from a PRNG so a chaos test replays the exact schedule —
    determinism is the whole fault plane's contract."""

    attempts: int = 3
    backoff_s: float = 0.05
    backoff_cap_s: float = 2.0
    jitter: float = 0.25

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError("retry attempts must be >= 1")
        if self.backoff_s <= 0 or self.backoff_cap_s < self.backoff_s:
            raise ValueError("need 0 < backoff-s <= backoff-cap-s")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")

    def delay_s(self, attempt: int, key: str = "") -> float:
        """Backoff before re-offer ``attempt`` (0-based): base * 2^n,
        capped, +/- jitter derived from blake2b(key, attempt)."""
        base = min(self.backoff_s * (2.0 ** attempt), self.backoff_cap_s)
        if not self.jitter:
            return base
        h = hashlib.blake2b(
            f"{key}:{attempt}".encode(), digest_size=4
        ).digest()
        # uniform in [-jitter, +jitter], deterministic in (key, attempt)
        frac = (int.from_bytes(h, "little") / 0xFFFFFFFF) * 2.0 - 1.0
        return max(0.0, base * (1.0 + self.jitter * frac))


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BreakerSpec:
    """Rolling-window breaker tuning: ``failures`` inside ``window_s``
    flip OPEN; after ``open_s`` the breaker goes HALF_OPEN and grants
    ``half_open_probes`` probe picks — one success closes it, one
    failure re-opens it."""

    failures: int = 3
    window_s: float = 30.0
    open_s: float = 5.0
    half_open_probes: int = 1

    def __post_init__(self):
        if self.failures < 1:
            raise ValueError("breaker failures must be >= 1")
        if self.window_s <= 0 or self.open_s <= 0:
            raise ValueError("breaker window-s and open-s must be > 0")
        if self.half_open_probes < 1:
            raise ValueError("breaker half-open-probes must be >= 1")


CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Per-target failure breaker (CLOSED→OPEN→HALF_OPEN→CLOSED).

    Wait-free by construction (deque + float compares — it sits on the
    router's pick hot path). The caller owns the clock so the state
    machine is a pure function of the recorded history — the unit tests
    drive it with a fake clock."""

    def __init__(
        self,
        spec: BreakerSpec | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.spec = spec or BreakerSpec()
        self._clock = clock
        self.state = CLOSED
        # rolling failure stamps (monotonic seconds); successes clear it
        self._failures: list[float] = []
        self._opened_at: float | None = None
        self._probes_granted = 0
        self._probe_granted_at: float | None = None
        self.opens = 0
        self.closes = 0
        self.failure_count = 0
        self.timeout_count = 0
        self.last_kind: str | None = None

    def _trim(self, now: float) -> None:
        cutoff = now - self.spec.window_s
        self._failures = [t for t in self._failures if t >= cutoff]

    def record_failure(self, kind: str = "error") -> str:
        """Count one failure/timeout against the window; returns the
        state after the transition (the router turns OPEN edges into
        breaker-open events)."""
        now = self._clock()
        self.failure_count += 1
        if kind == "timeout":
            self.timeout_count += 1
        self.last_kind = kind
        if self.state == HALF_OPEN:
            # the probe failed: straight back to OPEN for a fresh window
            self.state = OPEN
            self._opened_at = now
            self.opens += 1
            self._failures = []
            return self.state
        self._failures.append(now)
        self._trim(now)
        if self.state == CLOSED and len(self._failures) >= self.spec.failures:
            self.state = OPEN
            self._opened_at = now
            self.opens += 1
        return self.state

    def record_success(self) -> str:
        """A call to the target succeeded: a half-open probe closes the
        breaker; in CLOSED the failure window clears (the window counts
        CONSECUTIVE trouble, not lifetime totals)."""
        if self.state in (HALF_OPEN, OPEN):
            # OPEN success = a call raced the transition; proof of life
            # either way
            self.state = CLOSED
            self.closes += 1
        self._failures = []
        self._probes_granted = 0
        self._probe_granted_at = None
        self._opened_at = None
        return self.state

    def can_serve(self, now: float | None = None) -> bool:
        """Non-consuming eligibility check: CLOSED serves; OPEN past its
        cooldown flips HALF_OPEN; HALF_OPEN serves while probe budget
        remains. Does NOT burn a probe — :meth:`note_probe` does, and
        only when the caller actually routed to the target (a stats poll
        must never eat the probe budget)."""
        now = self._clock() if now is None else now
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if (
                self._opened_at is not None
                and now - self._opened_at >= self.spec.open_s
            ):
                self.state = HALF_OPEN
                self._probes_granted = 0
                return True
            return False
        # HALF_OPEN: serve while probe budget remains. A granted probe
        # whose outcome never reports back (a picker with no feedback
        # path — the gateway's produce route — or a caller that died
        # mid-call) RELEASES after another open_s: a breaker must never
        # exclude forever (the zombie-exclusion refusal,
        # docs/RESILIENCE.md)
        if self._probes_granted >= self.spec.half_open_probes:
            if (
                self._probe_granted_at is not None
                and now - self._probe_granted_at >= self.spec.open_s
            ):
                self._probes_granted = 0
                self._probe_granted_at = None
                return True
            return False
        return True

    def note_probe(self) -> None:
        """The caller routed real traffic to a HALF_OPEN target: one
        probe slot is spent until its success/failure reports back (or
        its grant ages out after another ``open_s`` — see
        :meth:`can_serve`)."""
        if self.state == HALF_OPEN:
            self._probes_granted += 1
            self._probe_granted_at = self._clock()

    def stats(self) -> dict[str, Any]:
        return {
            "state": self.state,
            "window_failures": len(self._failures),
            "failures": self.failure_count,
            "timeouts": self.timeout_count,
            "opens": self.opens,
            "closes": self.closes,
            "last_kind": self.last_kind,
        }


# ---------------------------------------------------------------------------
# the handoff chainer
# ---------------------------------------------------------------------------

#: transport contract: ``await transport(replica, payload, headers,
#: timeout_s)`` → ``(status, body_dict, response_headers)``. Connection
#: failures raise ``(ConnectionError, OSError, asyncio.TimeoutError)``.
Transport = Callable[
    [str, bytes, dict[str, str], float],
    Awaitable[tuple[int, dict[str, Any], dict[str, str]]],
]


class HandoffLost(RuntimeError):
    """The export payload is gone (consumed, evicted, or never made) —
    nothing to re-offer AND nothing to decode locally. The journal (when
    configured) still holds the accepted request, so a restart replays
    it as fresh work; this error makes the loss loud in the meantime."""


def http_transport(
    resolve: Callable[[str], str],
    session_factory: Callable[[], Any] | None = None,
) -> Transport:
    """The production transport: POST the payload to the replica's
    ``/kv/import`` over aiohttp, socket timeout supplied per call by the
    chainer (deadline-derived — NET1201's explicit-timeout contract).
    ``resolve`` maps a replica name to its base URL (in-cluster: the
    headless-service pod DNS name the StatefulSet split publishes)."""
    import aiohttp

    async def _offer(
        session, replica: str, payload: bytes, headers: dict[str, str],
        timeout_s: float,
    ) -> tuple[int, dict[str, Any], dict[str, str]]:
        async with session.post(
            f"{resolve(replica).rstrip('/')}/kv/import",
            data=payload,
            headers=headers,
            timeout=aiohttp.ClientTimeout(total=timeout_s),
        ) as resp:
            try:
                body = await resp.json(content_type=None)
            except ValueError:
                body = {}
            return resp.status, body or {}, dict(resp.headers)

    async def _post(
        replica: str, payload: bytes, headers: dict[str, str], timeout_s: float
    ) -> tuple[int, dict[str, Any], dict[str, str]]:
        if session_factory is not None:
            # the caller OWNS the session (and its lifecycle): never
            # close it here — a shared session must survive the next
            # offer
            return await _offer(
                session_factory(), replica, payload, headers, timeout_s
            )
        async with aiohttp.ClientSession() as session:
            return await _offer(session, replica, payload, headers, timeout_s)

    return _post


class HandoffChainer:
    """Drives one prefill export to a completed generation, surviving
    the decode side (docs/RESILIENCE.md "Distributed failure domain").

    The chainer is the prefill side's agent-layer consumer of handoff
    tickets (ROADMAP item 3): ``chain(ticket)`` re-offers the payload to
    the router's decode picks under :class:`RetryPolicy`, feeds the
    router's per-replica breakers with every outcome, honors 503
    ``Retry-After`` as a per-replica hold, derives every socket timeout
    from the deadline budget, and — when the cap is reached or no
    healthy decode replica remains — imports the payload back into the
    prefill engine itself (``local_fallback``): the serialized snapshot
    is the complete request state, so local decode is byte-identical to
    the disaggregated path. Every outcome lands in the engine's flight
    ring (``handoff-retry`` / ``handoff-fallback`` / ``breaker-*``
    events) and counters — a re-offer is never invisible."""

    def __init__(
        self,
        engine,
        router=None,
        transport: Transport | None = None,
        policy: RetryPolicy | None = None,
        sleep: Callable[[float], Awaitable[None]] = asyncio.sleep,
    ):
        self.engine = engine
        self.router = router
        self.transport = transport
        self.policy = policy or RetryPolicy()
        self._sleep = sleep
        self.completed = 0
        self.retries = 0
        self.fallbacks = 0
        if router is not None and getattr(router, "on_breaker_event", None) is None:
            # breaker transitions become flight events on the prefill
            # engine: the one ring chaos assertions already read
            router.on_breaker_event = self._breaker_event

    def _breaker_event(self, kind: str, replica: str, detail: dict) -> None:
        self.engine.flight.event(kind, replica=replica, **detail)
        self.engine.note_breaker_open(
            open_replicas=detail.get("open_replicas", 0)
        )

    async def _net_fault(self, site: str) -> tuple[int, dict, dict] | None:
        """Network fault seam (serving/faults.py): consult the engine's
        injector at the chainer's HTTP boundaries. ``drop`` raises the
        connection away, ``delay-ms`` stalls the call, ``error`` answers
        a synthetic HTTP 500 — each a deterministic chaos input."""
        injector = getattr(self.engine, "_faults", None)
        if injector is None:
            return None
        action = injector.fire(site)
        if action is None:
            return None
        self.engine.note_fault_fired(
            site=site, shape=action.shape, fire=action.seq,
            hang_ms=action.hang_ms if action.shape == "delay-ms" else None,
        )
        if action.shape == "drop":
            raise ConnectionError(action.message)
        if action.shape == "delay-ms":
            await self._sleep(action.hang_ms / 1000.0)
            return None
        if action.shape == "error":
            return 500, {"error": action.message}, {}
        return None

    @staticmethod
    def _retry_after(body: dict, headers: dict) -> float:
        for source in (headers.get("Retry-After"), headers.get("retry-after"),
                       body.get("retry_after_s")):
            try:
                if source is not None:
                    return max(0.0, float(source))
            except (TypeError, ValueError):
                continue
        return 1.0

    async def chain(self, ticket: dict[str, Any] | str) -> dict[str, Any]:
        """One handoff ticket (the ``finish_reason: "handoff"`` result
        of ``generate()`` on a prefill-role engine, or the bare request
        id) to a completed generation result."""
        rid = ticket if isinstance(ticket, str) else ticket.get("handoff")
        if not rid:
            raise ValueError("not a handoff ticket (no 'handoff' id)")
        # settle=False: the chainer's pickup is NOT the answer — the
        # journal entry stays live until the decode side's outcome
        # arrives (the pull-model pod pickup settles at take, where no
        # later feedback exists)
        entry = self.engine.take_export_entry(rid, settle=False)
        if entry is None:
            raise HandoffLost(
                f"export {rid!r} is gone (already taken or evicted); "
                f"the journal replay covers it on restart"
            )
        payload: bytes = entry["payload"]
        deadline = parse_deadline(entry.get("deadline"))
        headers: dict[str, str] = {}
        if entry.get("trace"):
            headers["langstream-trace"] = str(entry["trace"])
        if deadline is not None:
            headers[DEADLINE_HEADER] = repr(deadline)
        # exclusion is ONE pick deep (the replica that just failed):
        # durable exclusion belongs to the breaker/hold machinery, and a
        # replica whose breaker is still CLOSED deserves another offer
        # after the backoff — that second failure is what trips it
        exclude: set[str] = set()
        attempt = 0
        while attempt < self.policy.attempts:
            target = None
            if self.router is not None:
                target = self.router.pick(phase="decode", exclude=exclude)
                if target is None and exclude:
                    # the just-failed replica is the whole pool: after
                    # the backoff it deserves the re-offer itself
                    # (breaker/hold permitting) — a sole decode replica
                    # must not lose the handoff to one transient blip
                    target = self.router.pick(phase="decode")
            exclude = set()
            if target is None:
                break  # no healthy decode replica left: local decode
            if self.transport is None:
                # a local configuration error: raised OUTSIDE the offer
                # try, or it would be misread as a replica refusal and
                # poison healthy replicas' breakers
                raise ValueError(
                    f"HandoffChainer has no transport to offer "
                    f"{rid!r} to replica {target!r}"
                )
            terminal: Exception | None = None
            try:
                injected = await self._net_fault("http-import")
                if injected is not None:
                    status, body, resp_headers = injected
                else:
                    status, body, resp_headers = await self.transport(
                        target, payload, headers,
                        socket_timeout_s(deadline),
                    )
            except asyncio.TimeoutError:
                self.router.report_failure(target, "timeout")
                self._note_retry(rid, target, attempt, "timeout")
                exclude = {target}
                await self._sleep(self.policy.delay_s(attempt, rid))
                attempt += 1
                continue
            except (ConnectionError, OSError) as e:
                self.router.report_failure(target, "error")
                self._note_retry(rid, target, attempt, f"refused: {e}")
                exclude = {target}
                await self._sleep(self.policy.delay_s(attempt, rid))
                attempt += 1
                continue
            if status == 200:
                self.router.report_success(target)
                self.engine.handoff_settled(rid)
                self.completed += 1
                return body
            if status == 503:
                # an explicit shed with a hint: the replica ANSWERED —
                # alive, just saturated. Proof of life closes/feeds its
                # breaker (a half-open probe answered 503 must re-admit
                # the replica once the hold lapses, not strand it); the
                # hold, not the breaker, owns the backpressure
                self.router.report_success(target)
                hint = self._retry_after(body, resp_headers)
                self.router.hold(target, hint)
                self._note_retry(
                    rid, target, attempt, f"shed (retry-after {hint:g}s)"
                )
                exclude = {target}
                attempt += 1
                continue
            if status == 409:
                terminal = LookupError(
                    f"decode pool refused the handoff layout: "
                    f"{body.get('error')}"
                )
            elif status == 504:
                terminal = DeadlineExceeded(
                    str(body.get("error") or "deadline exceeded in transit")
                )
            if terminal is not None:
                # refusals no sibling will answer differently: the
                # decode side ANSWERED (409/504 + its own flight event),
                # so the journal entry retires — a replay would only
                # repeat the refusal later — and the answering replica
                # is alive (a probe that drew a refusal still closes
                # the breaker)
                self.router.report_success(target)
                self.engine.handoff_settled(rid)
                raise terminal
            # 404/5xx: the pod is up but wrong (restarted mid-handoff,
            # import route broken) — breaker food, try the next replica
            self.router.report_failure(target, "error")
            self._note_retry(rid, target, attempt, f"http {status}")
            exclude = {target}
            await self._sleep(self.policy.delay_s(attempt, rid))
            attempt += 1
        # ---- local-decode fallback -----------------------------------
        self.fallbacks += 1
        self.engine.note_handoff_fallback(rid, attempts=attempt)
        result = await self.engine.import_handoff(payload, local_fallback=True)
        # the local finish retires the journal entry by journey id; this
        # drops the rid mapping too so unsettled_handoffs reads true
        self.engine.handoff_settled(rid)
        self.completed += 1
        return result

    def _note_retry(self, rid: str, target: str, attempt: int, why: str) -> None:
        self.retries += 1
        self.engine.note_handoff_retry(
            rid, replica=target, attempt=attempt, reason=why
        )

    def stats(self) -> dict[str, Any]:
        return {
            "completed": self.completed,
            "retries": self.retries,
            "fallbacks": self.fallbacks,
            "policy_attempts": self.policy.attempts,
        }
