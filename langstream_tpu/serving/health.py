"""Engine health plane: watchdog, degradation predicates, SLO burn rates.

The flight recorder (serving/flight.py) measures everything; this module
is the layer that *judges* it — the closing of ROADMAP item 5's loop
("two of five bench rounds lost to an unresponsive device no probe ever
noticed"):

- :class:`EngineWatchdog` — a loop-side heartbeat (last-step-completed
  monotonic stamp + queue depth at stamp time, written by the engine loop
  at every flight boundary) plus a **wait-free** checker. An engine is
  ``wedged`` when no step progress has occurred for ``wedge_window_s``
  while work is queued or in flight — exactly the r03 failure shape
  ("device unresponsive after 150s"): the loop is stuck awaiting a
  dispatch that will never return, so the heartbeat stops while the
  queue does not. It is ``degraded`` on sustained anomaly windows — the
  ``engine_top --analyze`` heuristics run as live predicates over the
  flight ring (recompile storms, KV-reservation saturation, pipeline
  overlap collapse).
- :class:`SloTracker` — objectives (TTFT p-quantile, queue-wait
  p-quantile, shed rate, availability) declared in the app's
  ``tpu-serving-configuration`` resource, evaluated engine-side with
  Google-SRE-style **multi-window burn rates**: burn = (bad fraction in
  window) / (1 − target). An objective pages (``alert`` flight event)
  when BOTH the fast and slow windows burn above ``fast_burn`` — the
  fast window confirms the problem is still happening, the slow window
  that it is material (the classic 5m/1h multi-window multi-burn-rate
  pair).

Wait-free contract (graftcheck rule OBS504 gates this module and the pod
probe handlers): everything here is arithmetic over snapshots — deque
appends, attribute reads, list scans. **No device syncs, no blocking
I/O, no lock acquisition.** A liveness probe that itself touched the
device would hang exactly when the device does, which is the one moment
it must not; a probe that took an engine lock could deadlock against the
wedged dispatch holding it. Clocks are ``time.monotonic()`` throughout
(OBS501): health windows are durations, never timestamps.

The module never imports jax — the control plane and tools import it
without touching a device. Kubernetes wiring: the pod serves
``/healthz`` (liveness: 503 when any engine is wedged) and ``/ready``
(readiness: agent init done, engines warmed, nothing wedged);
``k8s/resources.py`` points both probes at them. See
``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable

#: health states, best → worst (rank order for fleet aggregation)
HEALTH_STATES = ("ok", "degraded", "wedged")

_STATE_RANK = {name: i for i, name in enumerate(HEALTH_STATES)}


def worst_state(states) -> str:
    """Fleet aggregate: the worst member state wins (unknown strings rank
    as ``wedged`` — a member reporting garbage is not healthy)."""
    worst = "ok"
    for state in states:
        rank = _STATE_RANK.get(state, _STATE_RANK["wedged"])
        if rank > _STATE_RANK[worst]:
            worst = HEALTH_STATES[rank]
    return worst


# ---------------------------------------------------------------------------
# degradation predicates: engine_top --analyze heuristics, live
# ---------------------------------------------------------------------------


def recompile_storm(
    events: list[dict[str, Any]],
    now_s: float,
    k: int = 3,
    span_s: float = 2.0,
    horizon_s: float = 60.0,
) -> str | None:
    """≥ ``k`` recompile events within ``span_s`` of each other, the
    newest within ``horizon_s`` of now — each compile is a potential
    multi-second convoy on TPU, and a *cluster* of them means the shape
    variety is unbounded (prompt buckets, sampler modes). Uses the
    events' monotonic ``m_s`` stamps (old payloads without them never
    flag — absence of evidence is not degradation)."""
    stamps = sorted(
        e["m_s"]
        for e in events
        if e.get("kind") == "recompile" and e.get("m_s") is not None
    )
    recent = [s for s in stamps if now_s - s <= horizon_s]
    for i in range(len(recent) - k + 1):
        if recent[i + k - 1] - recent[i] <= span_s:
            return (
                f"recompile storm: {len(recent)} compiles in the last "
                f"{horizon_s:.0f}s with >={k} inside {span_s:.0f}s"
            )
    return None


def shrink_pressure(
    events: list[dict[str, Any]],
    now_s: float,
    k: int = 2,
) -> str | None:
    """Sustained device memory pressure (docs/RESILIENCE.md): ≥ ``k``
    ``pool-shrink`` events inside one recovery window of now — the
    engine is adapting faster than it can recover, so the autoscaler
    and ``/healthz`` must see DEGRADED, not a quietly shrinking budget.
    The window comes from the events themselves (each carries its
    ``recovery_s``); payloads without ``m_s`` stamps never flag."""
    shrinks = [
        e
        for e in events
        if e.get("kind") == "pool-shrink" and e.get("m_s") is not None
    ]
    if not shrinks:
        return None
    window = max(float(e.get("recovery_s") or 30.0) for e in shrinks)
    recent = [e for e in shrinks if now_s - e["m_s"] <= window]
    if len(recent) >= k:
        last = max(e["m_s"] for e in recent)
        return (
            f"device memory pressure: {len(recent)} pool-shrink events "
            f"inside one {window:.0f}s recovery window (last "
            f"{now_s - last:.1f}s ago) — the KV budget is shrinking "
            f"faster than it recovers"
        )
    return None


def kv_saturation(
    samples: list[dict[str, Any]],
    frac: float = 0.95,
    share: float = 0.25,
    min_samples: int = 8,
) -> str | None:
    """KV-reservation pressure sustained across the sample window: more
    than ``share`` of the recent samples report the pool above ``frac``
    reserved — the regime where every admission stalls on
    ``no-kv-blocks`` and preemption churns."""
    vals = [s.get("kv_used") for s in samples if s.get("kv_used") is not None]
    if len(vals) < min_samples:
        return None
    hot = sum(1 for v in vals if v > frac)
    if hot > len(vals) * share:
        return (
            f"KV reservation saturation: pool >{frac:.0%} reserved in "
            f"{hot}/{len(vals)} recent samples"
        )
    return None


def overlap_collapse(samples: list[dict[str, Any]], min_decode: int = 8) -> str | None:
    """Pipeline overlap collapse, the live twin of the ``engine_top``
    post-mortem flag: a loaded engine (occupancy above half its slots)
    whose decode host work is overwhelmingly exposed (<5% overlapped)
    has lost the depth-2 pipeline. Light load is exempt — the sequential
    light-chunk regime is by design."""
    decode = [s for s in samples if s.get("phase") == "decode"]
    if len(decode) < min_decode:
        return None
    if not any("host_overlapped_ms" in s for s in decode):
        return None  # pre-pipeline samples never carried the split
    overlapped = sum(s.get("host_overlapped_ms") or 0.0 for s in decode)
    host = sum(s.get("host_ms") or 0.0 for s in decode)
    slots = max((s.get("slots") or 0) for s in decode)
    occ = sum(s.get("occupancy") or 0 for s in decode) / len(decode)
    if (
        host + overlapped > 0
        and overlapped / (host + overlapped) < 0.05
        and slots
        and occ > slots / 2
    ):
        return (
            f"pipeline overlap collapse: {overlapped:.1f}ms of "
            f"{host + overlapped:.1f}ms decode host time overlapped (<5%) "
            f"at occupancy {occ:.1f}/{slots}"
        )
    return None


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


class EngineWatchdog:
    """Loop-side heartbeat + wait-free health checker for one engine.

    The engine loop calls :meth:`beat` at every flight-recorder boundary
    (every dispatched burst AND every idle stall sample — an idle engine
    beats about once a second, so idleness never reads as a wedge). The
    checker (:meth:`evaluate`) may run from any thread — probe handlers,
    ``stats()``, the flight report — and performs only snapshot reads
    and arithmetic. State lives on plain attributes: concurrent
    evaluations can at worst observe the same transition twice (benign
    duplicate ``health`` events), never block each other.

    ``wedge_window_s`` must exceed the engine's worst single
    loop-boundary gap — on TPU that is the first XLA compile of a
    variant (tens of seconds), which is why the default is 60 s and why
    ``warmup_on_start`` pods (compiles moved into the readiness window)
    can run it much tighter.
    """

    def __init__(
        self,
        wedge_window_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.wedge_window_s = float(wedge_window_s)
        self._clock = clock
        self.last_step = clock()
        self.queue_at_stamp = 0
        self.state = "ok"
        self.transitions = 0

    def beat(self, queue_depth: int = 0) -> None:
        """Stamp step progress (engine loop only; two attribute writes —
        wait-free by construction)."""
        self.queue_at_stamp = queue_depth
        self.last_step = self._clock()

    def evaluate(
        self,
        queued: int,
        occupancy: int,
        samples: list[dict[str, Any]] | None = None,
        events: list[dict[str, Any]] | None = None,
        stopped: bool = False,
        extra_reasons: tuple = (),
    ) -> dict[str, Any]:
        """Judge the engine now. Returns the health verdict::

            {state, previous, transition, reasons, last_step_age_s,
             queued, occupancy, wedge_window_s}

        ``transition`` is True when the state changed since the last
        evaluation — the caller records it as a ``health`` flight event
        (the watchdog itself holds no reference to the recorder, so the
        predicates stay trivially pure)."""
        now = self._clock()
        age = now - self.last_step
        pending = max(queued, occupancy, self.queue_at_stamp)
        reasons: list[str] = []
        if stopped:
            # a stopped engine (lockstep group broken) can never serve
            # again in this process — report it wedged so the liveness
            # probe recycles the pod and the slice restarts as a unit
            state = "wedged"
            reasons.append(
                "engine stopped serving (lockstep group broken or closed "
                "mid-flight): only a pod restart recovers it"
            )
        elif age > self.wedge_window_s and pending > 0:
            state = "wedged"
            reasons.append(
                f"no step progress for {age:.1f}s (window "
                f"{self.wedge_window_s:.1f}s) with {queued} queued and "
                f"{occupancy} in flight"
            )
        else:
            for reason in (
                recompile_storm(events or [], now),
                kv_saturation(samples or []),
                overlap_collapse(samples or []),
                shrink_pressure(events or [], now),
            ):
                if reason:
                    reasons.append(reason)
            # caller-evaluated predicates (e.g. the engine's per-class
            # TBT burn trackers): pre-judged strings, appended so the
            # watchdog stays pure arithmetic over its own inputs
            reasons.extend(extra_reasons)
            state = "degraded" if reasons else "ok"
        previous = self.state
        transition = state != previous
        if transition:
            self.state = state
            self.transitions += 1
        return {
            "state": state,
            "previous": previous,
            "transition": transition,
            "reasons": reasons,
            "last_step_age_s": round(age, 3),
            "queued": queued,
            "occupancy": occupancy,
            "wedge_window_s": self.wedge_window_s,
        }


# ---------------------------------------------------------------------------
# SLO objectives + tracker
# ---------------------------------------------------------------------------

#: objective vocabulary: what the engine records against each name.
#: "tbt" is the streaming time-between-tokens objective (one event per
#: finished stream, measured as the request's p99 inter-chunk interval
#: — docs/OBSERVABILITY.md Streaming & TBT); per-QoS-class targets
#: (qos.classes.<name>.tbt-p99-s) build one tracker per class with this
#: same machinery.
OBJECTIVES = ("ttft", "queue-wait", "tbt", "shed-rate", "availability")

#: objectives whose good/bad split needs a latency threshold
LATENCY_OBJECTIVES = ("ttft", "queue-wait", "tbt")


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One objective: ``target`` is the required good fraction (0.99 =
    "99% of events good" — for latency objectives that IS the p99), and
    ``threshold_ms`` draws the good/bad line for latency events."""

    name: str
    target: float
    threshold_ms: float | None = None

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"target": self.target}
        if self.threshold_ms is not None:
            out["threshold-ms"] = self.threshold_ms
        return out


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """The declared SLO policy. Frozen and tuple-valued so a
    :class:`~langstream_tpu.serving.engine.ServingConfig` carrying it
    stays hashable (engines are singleton-cached by config), and
    round-trips through the ``tpu-serving-configuration`` resource's
    ``slo`` section via :meth:`to_dict`/:meth:`from_dict`."""

    objectives: tuple[SloObjective, ...] = ()
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn: float = 14.4

    def to_dict(self) -> dict[str, Any]:
        return {
            "fast-window-s": self.fast_window_s,
            "slow-window-s": self.slow_window_s,
            "fast-burn": self.fast_burn,
            "objectives": {o.name: o.to_dict() for o in self.objectives},
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any] | None) -> "SloSpec | None":
        """Parse (and validate) the ``slo:`` section. ``None``/missing →
        no SLO tracking. Raises :class:`ValueError` on malformed config —
        the control plane calls this at deploy validation so a bad policy
        fails the deploy (HTTP 400), not the first request."""
        if d is None:
            return None
        if isinstance(d, SloSpec):
            return d
        if not isinstance(d, dict):
            raise ValueError(
                f"slo section must be a mapping, got {type(d).__name__}"
            )
        raw_objectives = d.get("objectives")
        if not isinstance(raw_objectives, dict) or not raw_objectives:
            raise ValueError(
                "slo.objectives must be a non-empty mapping of objective "
                f"name → {{target, threshold-ms}}; known: {list(OBJECTIVES)}"
            )
        objectives: list[SloObjective] = []
        for name in OBJECTIVES:  # stable order regardless of config order
            if name not in raw_objectives:
                continue
            raw = raw_objectives[name] or {}
            if not isinstance(raw, dict):
                raise ValueError(f"slo.objectives.{name} must be a mapping")
            if "target" not in raw:
                raise ValueError(f"slo.objectives.{name}.target is required")
            target = float(raw["target"])
            if not 0.0 < target < 1.0:
                raise ValueError(
                    f"slo.objectives.{name}.target must be in (0, 1) — it "
                    f"is the required good fraction, e.g. 0.99"
                )
            threshold = raw.get("threshold-ms", raw.get("threshold_ms"))
            if name in LATENCY_OBJECTIVES:
                if threshold is None:
                    raise ValueError(
                        f"slo.objectives.{name}.threshold-ms is required "
                        f"(the latency that counts as good)"
                    )
                threshold = float(threshold)
                if threshold <= 0:
                    raise ValueError(
                        f"slo.objectives.{name}.threshold-ms must be > 0"
                    )
            elif threshold is not None:
                raise ValueError(
                    f"slo.objectives.{name} takes no threshold-ms (it is "
                    f"a rate objective)"
                )
            objectives.append(SloObjective(name, target, threshold))
        unknown = set(raw_objectives) - set(OBJECTIVES)
        if unknown:
            raise ValueError(
                f"slo.objectives: unknown objective(s) {sorted(unknown)}; "
                f"known: {list(OBJECTIVES)}"
            )
        fast = float(d.get("fast-window-s", d.get("fast_window_s", 300.0)))
        slow = float(d.get("slow-window-s", d.get("slow_window_s", 3600.0)))
        burn = float(d.get("fast-burn", d.get("fast_burn", 14.4)))
        if fast <= 0 or slow <= 0:
            raise ValueError("slo windows must be > 0 seconds")
        if fast >= slow:
            raise ValueError(
                f"slo.fast-window-s ({fast}) must be smaller than "
                f"slo.slow-window-s ({slow})"
            )
        if burn <= 1.0:
            raise ValueError(
                "slo.fast-burn must be > 1 (a burn rate of 1 exhausts the "
                "budget exactly at the window's end — alerting below it "
                "pages on compliant service)"
            )
        return cls(
            objectives=tuple(objectives),
            fast_window_s=fast,
            slow_window_s=slow,
            fast_burn=burn,
        )


class SloTracker:
    """Multi-window burn-rate evaluation over time-bucketed good/bad
    counts.

    Single writer (the engine loop records completions, sheds, and
    failures), many readers. Recording is a deque append plus integer
    bumps; evaluation sums a bounded bucket window (≤ ``slow_window_s /
    BUCKET_S`` entries) — arithmetic only, wait-free (OBS504).

    Burn rate over a window = (bad / (good + bad)) / (1 − target): 1.0
    means the error budget is being consumed exactly at the rate that
    exhausts it at the window's end; ``fast_burn`` (default 14.4, the
    Google SRE page threshold for a 5m/1h pair against a 30-day budget)
    over BOTH windows fires the alert. ``budget_remaining`` is
    ``1 − burn_slow``: the slow window's budget left, negative when
    overspent.
    """

    BUCKET_S = 5.0

    def __init__(
        self,
        spec: SloSpec,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.spec = spec
        self._clock = clock
        depth = int(spec.slow_window_s // self.BUCKET_S) + 2
        # per objective: deque of [bucket_start_s, good, bad]
        self._buckets: dict[str, deque] = {
            o.name: deque(maxlen=depth) for o in spec.objectives
        }
        self._objectives = {o.name: o for o in spec.objectives}
        self.alerting: dict[str, bool] = {
            o.name: False for o in spec.objectives
        }
        self.totals: dict[str, dict[str, int]] = {
            o.name: {"good": 0, "bad": 0} for o in spec.objectives
        }

    def record(self, name: str, good: bool) -> dict[str, Any] | None:
        """Record one event against ``name`` and return the objective's
        fresh evaluation (None for names the spec doesn't declare — the
        engine records unconditionally and the spec decides what
        counts)."""
        obj = self._objectives.get(name)
        if obj is None:
            return None
        dq = self._buckets[name]
        now = self._clock()
        start = now - (now % self.BUCKET_S)
        if not dq or dq[-1][0] != start:
            dq.append([start, 0, 0])
        dq[-1][1 if good else 2] += 1
        self.totals[name]["good" if good else "bad"] += 1
        return self._evaluate(obj, now)

    def record_latency(self, name: str, ms: float) -> dict[str, Any] | None:
        """Record one latency event: good iff ``ms`` is within the
        objective's declared ``threshold-ms``. The good/bad line lives
        here with the spec — callers report what they measured, never
        what it means. No-op for undeclared or non-latency objectives."""
        obj = self._objectives.get(name)
        if obj is None or obj.threshold_ms is None:
            return None
        return self.record(name, ms <= obj.threshold_ms)

    @staticmethod
    def _window_counts(
        snapshot: list, now: float, window_s: float
    ) -> tuple[int, int]:
        cutoff = now - window_s
        good = bad = 0
        for start, g, b in snapshot:
            if start >= cutoff:
                good += g
                bad += b
        return good, bad

    @staticmethod
    def _burn(good: int, bad: int, target: float) -> float | None:
        total = good + bad
        if total == 0:
            return None  # no evidence, no burn
        return (bad / total) / (1.0 - target)

    def _evaluate(
        self, obj: SloObjective, now: float, commit: bool = True
    ) -> dict[str, Any]:
        """One objective's verdict. ``commit=True`` (the record path —
        the single writer) edge-detects against the committed alert
        state and updates it; read paths (:meth:`status`) pass
        ``commit=False`` so a scrape between records can never swallow
        a transition the next record would otherwise report."""
        snapshot = list(self._buckets[obj.name])
        gf, bf = self._window_counts(snapshot, now, self.spec.fast_window_s)
        gs, bs = self._window_counts(snapshot, now, self.spec.slow_window_s)
        burn_fast = self._burn(gf, bf, obj.target)
        burn_slow = self._burn(gs, bs, obj.target)
        budget = 1.0 - burn_slow if burn_slow is not None else 1.0
        alerting = (
            burn_fast is not None
            and burn_slow is not None
            and burn_fast >= self.spec.fast_burn
            and burn_slow >= self.spec.fast_burn
        )
        if commit:
            was = self.alerting[obj.name]
            self.alerting[obj.name] = alerting
            transition = alerting != was
        else:
            transition = False
        return {
            "objective": obj.name,
            "target": obj.target,
            "threshold_ms": obj.threshold_ms,
            "burn_rate_fast": (
                round(burn_fast, 4) if burn_fast is not None else None
            ),
            "burn_rate_slow": (
                round(burn_slow, 4) if burn_slow is not None else None
            ),
            "budget_remaining": round(budget, 4),
            "window_good": gs,
            "window_bad": bs,
            "alerting": alerting,
            "transition": transition,
        }

    def status(self) -> dict[str, Any]:
        """Full SLO section for ``stats()`` / ``/flight/summary`` — one
        evaluation per declared objective plus the window parameters."""
        now = self._clock()
        objectives = {}
        for name, obj in self._objectives.items():
            verdict = self._evaluate(obj, now, commit=False)
            verdict.pop("transition", None)
            verdict["total_good"] = self.totals[name]["good"]
            verdict["total_bad"] = self.totals[name]["bad"]
            objectives[name] = verdict
        return {
            "fast_window_s": self.spec.fast_window_s,
            "slow_window_s": self.spec.slow_window_s,
            "fast_burn": self.spec.fast_burn,
            "objectives": objectives,
            # the LIVE view (burn can age in or out of the fast window
            # between records); `alert` flight events stay edge-detected
            # at record time against the committed state
            "alerting": sorted(
                name
                for name, verdict in objectives.items()
                if verdict["alerting"]
            ),
        }


def validate_application_slo(application) -> None:
    """Deploy-time validation: parse every ``tpu-serving-configuration``
    resource's ``slo`` section so a malformed objective fails the deploy
    (HTTP 400) instead of the first request — the same contract
    :func:`~langstream_tpu.serving.qos.validate_application_qos` keeps
    for the ``qos`` section."""
    for name, res in (getattr(application, "resources", None) or {}).items():
        if getattr(res, "type", None) != "tpu-serving-configuration":
            continue
        try:
            SloSpec.from_dict((res.configuration or {}).get("slo"))
        except ValueError as e:
            raise ValueError(f"resource {name!r}: invalid slo section: {e}") from e
