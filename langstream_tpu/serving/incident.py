"""SLO-triggered incident bundles: the flight-data-recorder capture plane.

Every measurement plane this engine carries — flight ring, journey
ledgers, attribution, streaming TBT digests, burn-rate paging in
``health()`` — is *live state*: when a page actually fires, a human must
race to point ``engine_top`` at the pod before the rings age out. This
module closes that race (docs/OBSERVABILITY.md, *Incident bundles &
exemplars*): the moment a breach predicate trips, the engine snapshots
the evidence it already holds into a bounded **incident bundle** on
disk, so the post-mortem starts from the breach instant, not from
whenever a human arrived.

Triggers (wired in ``serving/engine.py``):

- a health-state transition out of OK (``health-degraded`` /
  ``health-wedged``), with the watchdog's reasons as evidence;
- ``shrink-pressure`` — the device-memory-pressure reason specifically
  (repeated pool shrinks inside one recovery window);
- ``slo-fast-burn`` — an SLO objective's multi-window burn rate crossed
  the page threshold (serving/slo.py);
- ``tbt-burn`` — the streaming time-between-tokens objective paged
  (PR 17's plane);
- ``breaker-storm`` — ≥ ``k`` ``breaker-open`` events inside one window
  of the engine's event ring (:func:`breaker_storm` below);
- ``adapter-storm`` — ONE adapter evicted ≥ ``k`` times inside one
  hydrate window (:func:`adapter_eviction_storm` below): the multi-LoRA
  tier budgets are too small for the live adapter mix, and every
  eviction buys a re-load or re-hydration the next request pays for
  (docs/ADAPTERS.md).

Capture discipline (graftcheck rule INC1601 gates this): the observe
side — :meth:`IncidentRecorder.should_capture`, the bundle handoff
:meth:`IncidentRecorder.submit`, and the engine's assembly method —
runs inside ``health()`` / the finish path / the SLO emit path, all of
which sit on or adjacent to the engine hot loop. It is therefore
**wait-free**: cooldown stamps and suppression counters live in plain
dicts (GIL-atomic; the trigger vocabulary bounds them), the bundle is
assembled from sections that are wait-free by contract (flight
summary, journey-ledger snapshots, attribution/survival/kvtransfer
sections), and the handoff is a deque append + event set — the exact
shape ``journal.py`` proved. The writer thread owns ALL file I/O and
the bundle table; ``list()``/``get()``/``stats()`` read that table
under one uncontended lock from the pod's serving thread (never the
hot path).

Durability: one JSON file per bundle, write-then-rename
(``incident-<n>-<kind>.json``), bounded to ``max_bundles`` on disk and
in memory — the oldest bundle is evicted LOUDLY (``on_evict`` → an
``incident-evict`` flight event). A flapping predicate cannot spam:
captures dedup per ``(kind, dedup key)`` under a cooldown, and
suppressed breaches are counted, not silently dropped. Bundles already
on disk at construction are re-indexed, so a restarted pod still
serves its history under ``GET /incidents``.

Event-tail dedup: flight events carry a per-recorder monotonic ``seq``,
and the recorder keeps a high-water mark — overlapping captures slice
the tail at ``seq > watermark``, so two bundles seconds apart never
double-report the same event.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable

log = logging.getLogger(__name__)

# IncidentRecorder.list() shadows the builtin inside the class body
builtin_list = list

#: capture-trigger kinds (the cooldown/dedup vocabulary — bounds the
#: stamp dicts by construction)
TRIGGER_KINDS = (
    "health-degraded",
    "health-wedged",
    "shrink-pressure",
    "slo-fast-burn",
    "tbt-burn",
    "breaker-storm",
    "adapter-storm",
)

#: trigger kind → the journey segment it indicts: worst-K ledgers are
#: ranked by time spent THERE, so a TBT page surfaces the slowest
#: streamers, not the longest prompts. None ranks by total journey time.
OFFENDING_SEGMENT: dict[str, str | None] = {
    "health-degraded": None,
    "health-wedged": None,
    "shrink-pressure": "decode",
    "slo-fast-burn": "queue",
    "tbt-burn": "stream",
    "breaker-storm": "transfer",
    "adapter-storm": "adapter-hydrate",
}


def breaker_storm(
    events: list[dict[str, Any]],
    now_s: float,
    k: int = 3,
    window_s: float = 30.0,
) -> dict[str, Any] | None:
    """The breaker-storm predicate: ≥ ``k`` ``breaker-open`` events whose
    monotonic stamp falls inside the trailing ``window_s`` of the event
    tail. Returns the evidence dict (count + the opens) or None. Pure
    function over an already-snapshotted tail — wait-free."""
    opens = [
        e
        for e in events
        if e.get("kind") == "breaker-open"
        and e.get("m_s") is not None
        and now_s - e["m_s"] <= window_s
    ]
    if len(opens) < k:
        return None
    return {
        "count": len(opens),
        "window_s": window_s,
        "replicas": sorted(
            {e.get("replica") for e in opens if e.get("replica")}
        ),
        "opens": opens[-k:],
    }


def adapter_eviction_storm(
    events: list[dict[str, Any]],
    now_s: float,
    k: int = 3,
    window_s: float = 30.0,
) -> dict[str, Any] | None:
    """The adapter eviction-storm predicate: ONE adapter evicted ≥ ``k``
    times inside the trailing ``window_s`` (the caller passes the hydrate
    window) of the event tail — thrash, not turnover: distinct adapters
    cycling through T0 rows is the LRU doing its job, the SAME adapter
    bouncing means the tier budgets are undersized for the live mix and
    every bounce re-pays a device load or a T2 hydration. Returns the
    evidence dict (adapter, count + the evictions) or None. Pure
    function over an already-snapshotted tail — wait-free (INC1601,
    the LORA1701 plane's breach observer)."""
    by_adapter: dict[str, list[dict[str, Any]]] = {}
    for e in events:
        if (
            e.get("kind") == "adapter-evict"
            and e.get("m_s") is not None
            and now_s - e["m_s"] <= window_s
            and e.get("adapter")
        ):
            by_adapter.setdefault(str(e["adapter"]), []).append(e)
    worst: tuple[str, list[dict[str, Any]]] | None = None
    for name, evictions in by_adapter.items():
        if worst is None or len(evictions) > len(worst[1]):
            worst = (name, evictions)
    if worst is None or len(worst[1]) < k:
        return None
    name, evictions = worst
    return {
        "adapter": name,
        "count": len(evictions),
        "window_s": window_s,
        "evictions": evictions[-k:],
    }


def worst_journeys(kind: str, k: int = 3) -> list[dict[str, Any]]:
    """The worst-``k`` journey ledgers ranked by time spent in the
    trigger's offending segment (:data:`OFFENDING_SEGMENT`; total
    journey time when the trigger indicts no one segment). Snapshot
    reads over the bounded global ledger — wait-free by the ledger's
    contract."""
    from langstream_tpu.serving.journey import JOURNEYS, segments

    segment = OFFENDING_SEGMENT.get(kind)
    ranked: list[tuple[float, str, list, list]] = []
    for jid in JOURNEYS.ids():
        events = JOURNEYS.events(jid)
        if not events:
            continue
        segs = segments(events)
        total = sum(s.get("ms", 0.0) for s in segs)
        if segment is None:
            score = total
        else:
            score = sum(
                s.get("ms", 0.0) for s in segs if s.get("segment") == segment
            )
        ranked.append((score, jid, segs, events))
    ranked.sort(key=lambda t: t[0], reverse=True)
    out = []
    for score, jid, segs, events in ranked[:k]:
        out.append(
            {
                "journey": jid,
                "offending_segment": segment,
                "offending_ms": round(score, 3),
                "segments": segs,
                "events": events,
            }
        )
    return out


class IncidentRecorder:
    """Bounded on-disk incident-bundle store with a wait-free capture
    side. One instance per engine (``incident-dir`` config)."""

    def __init__(
        self,
        directory: str,
        max_bundles: int = 32,
        cooldown_s: float = 60.0,
        on_evict: Callable[[str], None] | None = None,
    ):
        self.directory = directory
        self.max_bundles = max(1, int(max_bundles))
        self.cooldown_s = float(cooldown_s)
        self._on_evict = on_evict
        os.makedirs(directory, exist_ok=True)
        # -- observe-side state: GIL-atomic containers, NO lock ----------
        # (INC1601 polices should_capture/submit — a lock here would put
        # a wait on the health()/finish paths)
        self._last_capture: dict[str, float] = {}
        self.suppressed: dict[str, int] = {}
        self.captured = 0
        #: flight-event seq high-water mark (overlap dedup across bundles)
        self.last_event_seq = 0
        # -- writer-side state: bundle table + counters under one lock ---
        self._lock = threading.Lock()
        self._bundles: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        self._written = 0
        self._evicted = 0
        self._write_errors = 0
        self._seq = self._load_existing()
        self._pending: deque = deque()
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._closed = threading.Event()
        self._writer = threading.Thread(
            target=self._run_writer,
            name="incident-recorder",
            daemon=True,
        )
        self._writer.start()

    # -- construction-time reload (single-threaded) ----------------------

    def _load_existing(self) -> int:
        """Re-index bundles a previous life left on disk (oldest beyond
        the bound deleted loudly), returning the next bundle sequence
        number. Unreadable files are skipped, never fatal."""
        names = sorted(
            n
            for n in os.listdir(self.directory)
            if n.startswith("incident-") and n.endswith(".json")
        )
        seq = 0
        for name in names:
            path = os.path.join(self.directory, name)
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    bundle = json.load(fh)
            except (OSError, ValueError) as e:
                log.warning("skipping unreadable incident bundle %s: %s", path, e)
                continue
            bid = bundle.get("id") or name[: -len(".json")]
            self._bundles[bid] = bundle
            try:
                seq = max(seq, int(bid.split("-")[1]))
            except (IndexError, ValueError):
                pass
        while len(self._bundles) > self.max_bundles:
            old_id, _ = self._bundles.popitem(last=False)
            self._evicted += 1
            self._remove_file(old_id)
        return seq

    # -- wait-free capture side ------------------------------------------

    def should_capture(self, kind: str, dedup_key: str | None = None) -> bool:
        """Cooldown/dedup gate, called at the breach site. Wait-free:
        one monotonic read plus GIL-atomic dict ops on a dict whose key
        space is the trigger vocabulary (× per-trigger dedup keys such
        as the SLO objective name) — bounded by construction."""
        if self._closed.is_set():
            return False
        key = kind if dedup_key is None else f"{kind}:{dedup_key}"
        now_s = time.monotonic()
        last = self._last_capture.get(key)
        if last is not None and now_s - last < self.cooldown_s:
            self.suppressed[kind] = self.suppressed.get(kind, 0) + 1
            return False
        self._last_capture[key] = now_s
        return True

    def submit(self, bundle: dict[str, Any]) -> str:
        """Hand an assembled bundle to the writer thread: stamp its id,
        append, wake. Wait-free — the same handoff shape as
        ``journal.admit``."""
        self.captured += 1
        bundle_id = "incident-%06d-%s" % (
            self._seq + self.captured,
            bundle.get("trigger", {}).get("kind", "unknown"),
        )
        bundle["id"] = bundle_id
        self._pending.append(bundle)
        self._idle.clear()
        self._wake.set()
        return bundle_id

    # -- serving-side reads (pod HTTP thread; one uncontended lock) ------

    def list(self) -> list[dict[str, Any]]:
        """Bounded bundle summaries, oldest first — the ``GET
        /incidents`` index payload."""
        with self._lock:
            bundles = builtin_list(self._bundles.values())
        return [
            {
                "id": b.get("id"),
                "kind": b.get("trigger", {}).get("kind"),
                "captured_at_ms": b.get("captured_at_ms"),
                "reasons": b.get("trigger", {}).get("reasons"),
                "journeys": len(b.get("worst_journeys") or ()),
                "events": len(b.get("events") or ()),
            }
            for b in bundles
        ]

    def get(self, bundle_id: str) -> dict[str, Any] | None:
        with self._lock:
            return self._bundles.get(bundle_id)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            live = len(self._bundles)
            written = self._written
            evicted = self._evicted
            write_errors = self._write_errors
        return {
            "dir": self.directory,
            "live": live,
            "captured": self.captured,
            "written": written,
            "evicted": evicted,
            "write_errors": write_errors,
            "suppressed": dict(self.suppressed),
            "pending": len(self._pending),
            "cooldown_s": self.cooldown_s,
            "max_bundles": self.max_bundles,
        }

    # -- writer thread ---------------------------------------------------

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every submitted bundle reached disk (tests, drain)."""
        return self._idle.wait(timeout)

    def close(self, timeout: float = 5.0) -> None:
        if self._closed.is_set():
            return
        self.flush(timeout)
        self._closed.set()
        self._wake.set()
        self._writer.join(timeout)

    def _run_writer(self) -> None:
        while True:
            self._wake.wait()
            self._wake.clear()
            try:
                self._drain()
            except OSError as e:
                # disk trouble must never take the engine down: the
                # capture plane degrades loudly, serving continues
                log.error("incident bundle write failed: %s", e)
                self._write_errors += 1
            if not self._pending:
                self._idle.set()
                if self._closed.is_set():
                    return

    def _drain(self) -> None:
        while self._pending:
            bundle = self._pending.popleft()
            bundle_id = bundle["id"]
            path = os.path.join(self.directory, bundle_id + ".json")
            # write-then-rename: a crash mid-write leaves no torn bundle
            tmp = f"{path}.tmp.{os.getpid()}"
            evicted: list[str] = []
            try:
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(bundle, fh, sort_keys=True, default=str)
                    fh.flush()
                os.replace(tmp, path)
            except OSError:
                self._write_errors += 1
                raise
            with self._lock:
                self._bundles[bundle_id] = bundle
                self._written += 1
                while len(self._bundles) > self.max_bundles:
                    old_id, _ = self._bundles.popitem(last=False)
                    self._evicted += 1
                    evicted.append(old_id)
            for old_id in evicted:
                # file removal + callbacks OUTSIDE the lock (the callback
                # appends a flight event)
                self._remove_file(old_id)
                if self._on_evict is not None:
                    self._on_evict(old_id)

    def _remove_file(self, bundle_id: str) -> None:
        try:
            os.remove(os.path.join(self.directory, bundle_id + ".json"))
        except OSError:
            pass
