"""Crash-requeue request journal: admitted-but-unfinished work on disk.

A genuine engine death (OOM-killed pod, segfaulted jaxlib, kernel OOM)
used to silently drop every queued and in-flight request — the callers'
futures die with the process, and nothing anywhere records that the work
was ever accepted. This module closes that hole (docs/RESILIENCE.md):

- every accepted submission is **journaled at admit** (prompt tokens,
  sampling params, stop strings, QoS identity — exactly the fields the
  QoS resume path needs to re-run it) and **retired at finish/shed/fail**
  (an explicitly failed request was *answered*, not lost);
- a restarting engine replays the journal's live entries through the QoS
  **front-of-class** resume path (``Scheduler.requeue_front`` — the same
  machinery drain/preemption already proved byte-identical), so accepted
  work survives the process that accepted it.

Durability model, stated honestly: appends are buffered through a
dedicated writer thread (the admit path runs on the engine's event loop
and the retire path inside OBS503-policed hot-loop methods — neither may
touch disk), so a crash can lose the last few *unflushed* ops. That
window is bounded and flushable (:meth:`RequestJournal.flush` — tests and
drain paths sync it); what can never happen is an *unbounded silent*
loss: everything the writer flushed replays.

Format: one JSON line per op (``{"op": "admit", "id": ..., ...}`` /
``{"op": "retire", "id": ...}``), append-only. The file is **bounded**:
when the op count outgrows ``4 × max_entries`` the writer compacts it to
just the live entries, and when the live set itself outgrows
``max_entries`` the oldest live entry is evicted LOUDLY (``on_evict``
callback → a ``journal-evict`` flight event) — a bounded journal that
sheds visibly beats an unbounded one that fills the disk. Torn trailing
lines (the crash landed mid-append) are skipped on load, never fatal.

Thread model: ``admit``/``retire`` are wait-free handoffs (deque append
+ event set) from the event loop or the dispatch thread; the writer
thread owns ALL file I/O and the live-entry table. The table and its
counters are read by ``depth()``/``stats()`` from the engine side, so
every access goes through one uncontended lock (RACE801 pairwise
discipline); the shutdown flag is a ``threading.Event``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import OrderedDict, deque
from typing import Any, Callable

log = logging.getLogger(__name__)

_JOURNAL_FILE = "requests.jsonl"


def request_entry(request) -> dict[str, Any]:
    """The journaled snapshot of one accepted request — the fields the
    replay path needs to rebuild an equivalent ``_Request`` (prompt +
    sampling params + QoS identity; engine-local state like futures and
    slot ids is rebuilt, never persisted)."""
    return {
        "id": request.journey_id,
        "prompt": list(request.prompt_tokens),
        "max-tokens": request.max_tokens,
        "temperature": request.temperature,
        "top-k": request.top_k,
        "top-p": request.top_p,
        "presence-penalty": request.presence_penalty,
        "frequency-penalty": request.frequency_penalty,
        "stop": list(request.stop),
        "tenant": request.tenant,
        "priority": request.priority,
        # end-to-end deadline (serving/handoff.py): absolute epoch
        # seconds, or None. A replayed entry keeps its ORIGINAL budget —
        # the restarted engine's admission gate sheds it loudly if the
        # crash outlived it (an expired replay must not complete
        # silently late)
        "deadline": getattr(request, "deadline", None),
    }


class RequestJournal:
    """Bounded on-disk journal of admitted-but-unfinished submissions.

    One instance per engine. ``pending()`` — the replay surface — reads
    the entries recovered at construction time, in admit order.
    """

    def __init__(
        self,
        directory: str,
        max_entries: int = 4096,
        on_evict: Callable[[str], None] | None = None,
        fingerprint: dict[str, Any] | None = None,
    ):
        self.directory = directory
        self.path = os.path.join(directory, _JOURNAL_FILE)
        self.max_entries = max(1, int(max_entries))
        self._on_evict = on_evict
        # engine-identity stamp (model + tokenizer): entries journaled
        # under a DIFFERENT identity must never replay — their token ids
        # mean nothing to this model, and a "successful" replay would be
        # garbage output (the kvtransfer layout-fingerprint refusal
        # pattern, applied to the journal). The journal dir is
        # engine-private by contract; the stamp protects against the
        # config CHANGING across restarts.
        self._fp = (
            json.dumps(fingerprint, sort_keys=True)
            if fingerprint
            else None
        )
        os.makedirs(directory, exist_ok=True)
        # shared writer-thread/engine-side state: live entries (insertion
        # order = admit order) + cumulative counters, under one lock
        self._lock = threading.Lock()
        self._live: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        self._appended = 0
        self._retired = 0
        self._evicted = 0
        self._replayed = 0
        self.mismatched = 0
        # _ops_written counts the ops ON DISK (seeded from the file, not
        # the live set — a crash-looping pod journals a few hundred ops
        # per life, and seeding from the small live set would reset the
        # compaction threshold every restart, growing the file without
        # bound in exactly the restart-heavy regime the bound exists for)
        self._all_loaded: list[dict[str, Any]] = []
        self._recovered, self._ops_written = self._load()
        # the live table keeps EVERY loaded entry — including
        # fingerprint-mismatched ones, which are never replayed but
        # still count against the bound and survive compaction until
        # evicted loudly (never silently erased)
        for entry in self._all_loaded:
            self._live[entry["id"]] = entry
        self._ops: deque = deque()
        if self._ops_written > max(256, 4 * self.max_entries):
            # the previous life left an oversized file: compact before
            # the writer starts (single-threaded here)
            self._compact()
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._closed = threading.Event()
        self._writer = threading.Thread(
            target=self._run_writer,
            name="request-journal",
            daemon=True,
        )
        self._writer.start()

    # -- load / replay surface ------------------------------------------

    def _load(self) -> tuple[list[dict[str, Any]], int]:
        """Rebuild the live set from the file. Returns ``(replayable
        entries, ops on disk)`` — entries stamped with a DIFFERENT
        engine fingerprint are kept live (they still count against the
        bound and are evicted loudly if orphaned) but never offered for
        replay."""
        if not os.path.exists(self.path):
            return [], 0
        live: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        ops = 0
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        op = json.loads(line)
                    except ValueError:
                        # torn trailing line: the crash landed mid-append.
                        # Skip — the op it carried was inside the bounded
                        # unflushed window the module docstring documents.
                        continue
                    ops += 1
                    rid = op.get("id")
                    if not rid:
                        continue
                    if op.get("op") == "admit":
                        live[rid] = op
                    elif op.get("op") == "retire":
                        live.pop(rid, None)
        except OSError as e:
            log.error("request journal unreadable at %s: %s", self.path, e)
            return [], 0
        replayable: list[dict[str, Any]] = []
        for entry in live.values():
            stamp = entry.get("fp")
            if (
                self._fp is not None
                and stamp is not None
                and stamp != self._fp
            ):
                # journaled under a different model/tokenizer: its token
                # ids mean nothing here — refuse to replay, loudly
                self.mismatched += 1
                continue
            replayable.append(entry)
        if self.mismatched:
            log.warning(
                "request journal at %s holds %d entr(ies) from a "
                "DIFFERENT engine identity: refusing to replay them "
                "(they age out at the journal bound)",
                self.path, self.mismatched,
            )
        self._all_loaded = list(live.values())
        return replayable, ops

    def pending(self) -> list[dict[str, Any]]:
        """Entries recovered from the previous process, admit order —
        what a restarting engine replays front-of-class (fingerprint-
        mismatched entries are excluded)."""
        return list(self._recovered)

    def note_replayed(self, n: int) -> None:
        with self._lock:
            self._replayed += n

    # -- wait-free record surface ---------------------------------------

    def admit(self, entry: dict[str, Any]) -> None:
        if self._closed.is_set() or not entry.get("id"):
            return
        op = {"op": "admit", **entry}
        if self._fp is not None:
            op["fp"] = self._fp
        self._ops.append(op)
        self._idle.clear()
        self._wake.set()

    def retire(self, rid: str | None) -> None:
        """Idempotent: retiring an id the journal never admitted (or
        already retired) is a no-op — finish/shed/fail paths can all
        retire without coordinating."""
        if self._closed.is_set() or not rid:
            return
        self._ops.append({"op": "retire", "id": rid})
        self._idle.clear()
        self._wake.set()

    def depth(self) -> int:
        """Live entries plus ops not yet applied (a gauge, so the two
        reads need not be atomic with respect to each other)."""
        with self._lock:
            live = len(self._live)
        return live + len(self._ops)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "live": len(self._live),
                "pending_ops": len(self._ops),
                "appended": self._appended,
                "retired": self._retired,
                "evicted": self._evicted,
                "replayed": self._replayed,
                "mismatched": self.mismatched,
                "max_entries": self.max_entries,
            }

    # -- writer thread ---------------------------------------------------

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every queued op reached the file (tests, drain)."""
        return self._idle.wait(timeout)

    def close(self, timeout: float = 5.0) -> None:
        if self._closed.is_set():
            return
        self.flush(timeout)
        self._closed.set()
        self._wake.set()
        self._writer.join(timeout)

    def _run_writer(self) -> None:
        while True:
            self._wake.wait()
            self._wake.clear()
            try:
                self._drain_ops()
            except OSError as e:
                # disk trouble must never take the engine down with it:
                # the journal degrades (loss window grows), serving
                # continues, the error is loud in the logs
                log.error("request journal write failed: %s", e)
            if not self._ops:
                self._idle.set()
                if self._closed.is_set():
                    return

    def _drain_ops(self) -> None:
        # apply every queued op to the live table under the lock (dict
        # ops only), collecting the lines to append; ALL file I/O then
        # happens outside the lock, so an engine-side depth()/stats()
        # read can never block behind disk latency. A crash between the
        # two halves loses only the unwritten lines — the same bounded
        # unflushed window the durability model already documents.
        evicted: list[str] = []
        lines: list[str] = []
        with self._lock:
            while self._ops:
                op = self._ops.popleft()
                rid = op["id"]
                if op["op"] == "admit":
                    self._live[rid] = op
                    self._appended += 1
                    while len(self._live) > self.max_entries:
                        evicted_id, _ = self._live.popitem(last=False)
                        self._evicted += 1
                        evicted.append(evicted_id)
                        lines.append(
                            json.dumps({"op": "retire", "id": evicted_id})
                        )
                else:
                    if self._live.pop(rid, None) is None:
                        continue  # unknown/double retire: no-op
                    self._retired += 1
                lines.append(json.dumps(op))
        if lines:
            with open(self.path, "a", encoding="utf-8") as fh:
                for line in lines:
                    fh.write(line + "\n")
                fh.flush()
            self._ops_written += len(lines)
        for evicted_id in evicted:
            # callbacks OUTSIDE the lock (they append flight events)
            if self._on_evict is not None:
                self._on_evict(evicted_id)
        if self._ops_written > max(256, 4 * self.max_entries):
            self._compact()

    def _compact(self) -> None:
        """Rewrite the file down to the live set (write-then-rename so a
        crash mid-compaction leaves the old file intact)."""
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with self._lock:
            entries = list(self._live.values())
        with open(tmp, "w", encoding="utf-8") as fh:
            for entry in entries:
                fh.write(json.dumps(entry) + "\n")
            fh.flush()
        os.replace(tmp, self.path)
        self._ops_written = len(entries)
