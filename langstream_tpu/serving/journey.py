"""Per-request journey ledger: lifecycle edges + cross-pod stitching.

The flight recorder (serving/flight.py) answers "what is the ENGINE
doing"; the trace buffer (core/tracing.py) answers "where did this
request's spans go". Neither reconstructs one request's end-to-end PATH
once the lifecycle spans replicas (docs/DISAGG.md): prefill on one pod,
KV handoff over HTTP, decode on another, with preempt/resume,
drain-requeue, and router bounces in between. This module is that third
surface — one append-only event list per request, written at the
existing flight-event sites, that the control plane can STITCH across
pods into a single ordered timeline and decompose into named TTFT
segments (queue vs prefill vs transfer vs decode-admission vs first
decode step — the decomposition BENCH_r05's 7.8 s gateway TTFT p99
could not name).

Identity: the journey key IS the trace id (core/tracing.py) when the
request is traced — the one id that already rides the record headers,
the gateway's responses, and (since the journey plane) the kvtransfer
wire header — so ``/journey/{trace_id}`` on every pod returns that
pod's partial ledger and the control-plane fan-in merges them. Untraced
requests get a fresh id of the same shape from
:func:`~langstream_tpu.core.tracing.fresh_trace_id`; warmup probes get
no journey at all.

Event schema (one dict per lifecycle edge)::

    {"seq", "t_ms", "m_s", "kind", **detail}

``t_ms`` is a WALL-clock anchor — the only timestamp comparable across
pods, which is exactly what stitching needs (same rule as the span
buffer's ``start_ms``; cross-pod skew shows up as a negative edge and
is flagged, never hidden). ``m_s`` is the in-process monotonic stamp
for same-pod math. Kinds (the lifecycle vocabulary)::

    gateway-produce  bounce  submit  admit  preempt  resume
    hydrate-begin  hydrate-done  adapter-hydrate  adapter-hydrate-done
    first-token  export  export-taken  import-received  import
    first-step  first-emit  last-emit  finish  shed  fail  cancelled

Hot-path discipline (graftcheck **OBS506**, the journey plane's OBS503/
POOL701 twin): every write is a GIL-atomic container append plus plain
counter bumps — **no locks, no I/O, no device sync** on the engine
dispatch path — and every read is a ``list()``/``dict()`` snapshot
copy. Bounded two ways: ``LS_TPU_JOURNEY_BUFFER`` journeys (default
1024, FIFO eviction with an ``evicted_requests`` counter) and
``LS_TPU_JOURNEY_EVENTS`` events per journey (default 128; the deque
drops oldest-first and ``dropped_events`` counts the loss — eviction is
accounted, never silent).

Exposure: the pod serves ``/journey`` (index) and ``/journey/{id}``
(this process's partial event list); the control plane stitches the
pods' partials under ``/api/applications/{t}/{n}/journey/{id}``;
``tools/journey.py`` renders the stitched timeline as a waterfall and
computes the TTFT critical path. See ``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict, deque
from typing import Any

#: event kinds that end a journey (used by completeness checks)
TERMINAL_KINDS = ("finish", "shed", "fail", "cancelled")

#: the canonical lifecycle chain (first occurrences must appear in this
#: order once stitched — a violation means cross-pod clock skew moved
#: an edge across a pod boundary, since each pod's own ledger is
#: monotone by construction)
LIFECYCLE_CHAIN = (
    "gateway-produce",
    "submit",
    "admit",
    "first-token",
    "export",
    "export-taken",
    "import-received",
    "import",
    "first-step",
    "first-emit",
    "last-emit",
    "finish",
)

#: canonical segment names, in lifecycle order — the TTFT decomposition
#: vocabulary the bench records and perf_diff track
SEGMENT_ORDER = (
    "ingest",
    "queue",
    "prefix-hydrate",
    "adapter-hydrate",
    "prefill",
    "export",
    "handoff-wait",
    "transfer",
    "decode-admission",
    "first-step",
    "decode",
    "stream",
    "preempted",
)

#: (previous kind, next kind) → segment name. The interval between two
#: consecutive events is labeled by what the request was WAITING ON
#: during it; unknown pairs fall back to an "a->b" label so the timeline
#: still tiles (gap-free by construction) even when the vocabulary
#: grows.
EDGE_SEGMENTS: dict[tuple[str, str], str] = {
    ("gateway-produce", "submit"): "ingest",   # broker + agent hop
    ("bounce", "submit"): "ingest",
    ("gateway-produce", "bounce"): "ingest",
    ("bounce", "bounce"): "ingest",
    ("submit", "admit"): "queue",
    ("submit", "shed"): "queue",
    # tiered prefix store (docs/PREFIX.md): an admission stashed while
    # the hydrator pulls its prompt's T2 blobs into T1 — the interval
    # the warm-start either pays instead of prefill or writes off at
    # the hydrate timeout
    ("submit", "hydrate-begin"): "queue",
    ("hydrate-begin", "hydrate-done"): "prefix-hydrate",
    ("hydrate-done", "admit"): "queue",
    # tiered adapter store (docs/ADAPTERS.md): an admission stashed
    # while the hydrator pulls the request's LoRA factors T2→T1 — the
    # cold-start interval an adapter pays once per replica, or writes
    # off at the hydrate timeout (a cold refusal: no recompute fallback)
    ("submit", "adapter-hydrate"): "queue",
    ("hydrate-done", "adapter-hydrate"): "queue",
    ("adapter-hydrate", "adapter-hydrate-done"): "adapter-hydrate",
    ("adapter-hydrate-done", "admit"): "queue",
    ("adapter-hydrate", "cancelled"): "adapter-hydrate",
    ("admit", "first-token"): "prefill",
    ("first-token", "export"): "export",       # gather + serialize
    ("export", "export-taken"): "handoff-wait",
    ("export-taken", "import-received"): "transfer",
    ("export", "import-received"): "transfer",  # direct import, no pickup
    ("import-received", "import"): "decode-admission",
    ("import", "first-step"): "first-step",
    ("first-step", "finish"): "decode",
    ("first-token", "finish"): "decode",        # combined engine
    ("preempt", "resume"): "preempted",
    ("resume", "admit"): "requeue",
    ("first-token", "preempt"): "decode",
    ("first-step", "preempt"): "decode",
    # a request resumed after a mid-decode preemption re-admits and runs
    # straight to finish (its first-token edge was already recorded):
    # that interval is decode-phase recovery — re-prefill included
    ("admit", "finish"): "decode",
    # streaming chunk delivery (docs/OBSERVABILITY.md Streaming & TBT):
    # first-emit → last-emit is the STREAM segment — the interval the
    # client was actually receiving tokens, the product latency TBT
    # quantifies. The flanking edges are flush-boundary bookkeeping
    # (first token → its chunk's delivery; final chunk → finish) and
    # stay labeled decode so the TTFT decomposition is unchanged.
    ("first-token", "first-emit"): "decode",
    ("first-step", "first-emit"): "decode",
    ("first-emit", "last-emit"): "stream",
    ("last-emit", "finish"): "decode",
    # a one-chunk generation emits first and last in the same flush
    ("first-emit", "finish"): "decode",
    # a disconnect mid-stream cancels between emits: the open stream is
    # what the client abandoned
    ("first-emit", "cancelled"): "stream",
    ("last-emit", "cancelled"): "decode",
}


def classify_edge(prev_kind: str, next_kind: str) -> str:
    """Segment name for the interval between two consecutive events."""
    return EDGE_SEGMENTS.get(
        (prev_kind, next_kind), f"{prev_kind}->{next_kind}"
    )


def segments(events: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """The consecutive-pair decomposition of an ordered event list: one
    entry per inter-event interval, labeled via :func:`classify_edge`.
    The entries TILE the timeline — their ``ms`` sum exactly the last
    event's ``t_ms`` minus the first's — which is what makes the
    acceptance's "segment sum equals end-to-end wall" property hold by
    construction. Pure arithmetic over a snapshot (OBS506)."""
    out: list[dict[str, Any]] = []
    for prev, nxt in zip(events, events[1:]):
        out.append(
            {
                "segment": classify_edge(
                    str(prev.get("kind")), str(nxt.get("kind"))
                ),
                "from": prev.get("kind"),
                "to": nxt.get("kind"),
                "t_ms": prev.get("t_ms"),
                "ms": round(
                    float(nxt.get("t_ms") or 0.0)
                    - float(prev.get("t_ms") or 0.0),
                    3,
                ),
            }
        )
    return out


def stitch(
    journey_id: str, partials: list[list[dict[str, Any]]]
) -> dict[str, Any]:
    """Merge partial per-pod event lists into ONE ordered timeline.

    Events sort by their wall anchor ``t_ms`` (stable, so each pod's
    own order survives ties); the stitched payload carries the merged
    events, the tiling segment decomposition, per-segment totals, and
    structural anomalies — a negative edge (cross-pod clock skew), an
    export with no matching import (a lost or still-in-transit
    handoff), a preempt never resumed. ``complete`` is True when the
    timeline has a ``submit`` and a terminal edge. Pure arithmetic over
    snapshots (OBS506)."""
    tagged: list[tuple[float, int, int, dict[str, Any]]] = []
    for pi, part in enumerate(partials):
        for idx, event in enumerate(part or []):
            if isinstance(event, dict):
                tagged.append(
                    (float(event.get("t_ms") or 0.0), pi, idx, event)
                )
    tagged.sort(key=lambda t: (t[0], t[1], t[2]))
    events = [t[3] for t in tagged]
    segs = segments(events)
    by_segment: dict[str, float] = {}
    for seg in segs:
        by_segment[seg["segment"]] = round(
            by_segment.get(seg["segment"], 0.0) + seg["ms"], 3
        )
    kinds = [str(e.get("kind")) for e in events]
    anomalies: list[str] = []
    # the sort makes every edge non-negative by construction, so clock
    # skew between pods surfaces as lifecycle edges crossing each other
    # instead: the FIRST occurrence of each canonical kind must appear
    # in chain order (each pod's own ledger is monotone; only a skewed
    # merge can invert the chain)
    first_idx: dict[str, int] = {}
    for i, kind in enumerate(kinds):
        first_idx.setdefault(kind, i)
    chain_idx = [first_idx[k] for k in LIFECYCLE_CHAIN if k in first_idx]
    if chain_idx != sorted(chain_idx):
        anomalies.append(
            "lifecycle edges out of canonical order: cross-pod clock "
            "skew reordered the stitched timeline"
        )
    if "export" in kinds and "import" not in kinds:
        anomalies.append(
            "export without matching import: handoff lost or still in "
            "transit"
        )
    terminal = any(k in kinds for k in TERMINAL_KINDS)
    if kinds.count("preempt") > kinds.count("resume") and terminal:
        anomalies.append("preempt without matching resume")
    total_ms = (
        round(
            float(events[-1].get("t_ms") or 0.0)
            - float(events[0].get("t_ms") or 0.0),
            3,
        )
        if events
        else 0.0
    )
    return {
        "journey": journey_id,
        "events": events,
        "segments": segs,
        "by_segment_ms": by_segment,
        "total_ms": total_ms,
        "complete": "submit" in kinds and terminal,
        "anomalies": anomalies,
    }


def _buffer_size() -> int:
    try:
        return max(16, int(os.environ.get("LS_TPU_JOURNEY_BUFFER", "1024")))
    except ValueError:
        return 1024


def _events_cap() -> int:
    try:
        return max(8, int(os.environ.get("LS_TPU_JOURNEY_EVENTS", "128")))
    except ValueError:
        return 128


class JourneyLedger:
    """Bounded per-request event ledger. Writers are the engine loop,
    the dispatch thread, and gateway/runner tasks; readers are the pod
    ``/journey`` endpoints and the control-plane stitcher. The record
    path is GIL-atomic container ops + counter bumps only (OBS506 —
    no locks, no I/O, no device sync); readers snapshot with
    ``list()`` copies exactly like the flight recorder."""

    def __init__(
        self, max_requests: int | None = None, max_events: int | None = None
    ):
        self.max_requests = (
            max_requests if max_requests is not None else _buffer_size()
        )
        self.max_events = (
            max_events if max_events is not None else _events_cap()
        )
        # insertion-ordered: FIFO eviction when the journey cap is hit
        self._entries: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        self._seq = 0
        self.recorded_events = 0
        self.evicted_requests = 0
        self.dropped_events = 0

    # -- recording (hot path: appends + counter bumps only) --------------

    def record(self, journey_id: str | None, kind: str, **detail: Any) -> None:
        """Append one lifecycle edge. A falsy journey id records nothing
        (warmup probes, untraced legacy paths)."""
        if not journey_id:
            return
        entry = self._entries.get(journey_id)
        if entry is None:
            entry = {"events": deque(maxlen=self.max_events), "recorded": 0}
            self._entries[journey_id] = entry
            while len(self._entries) > self.max_requests:
                self._entries.popitem(last=False)
                self.evicted_requests += 1
        events: deque = entry["events"]
        if len(events) >= self.max_events:
            # the deque drops oldest-first on append; account the loss
            self.dropped_events += 1
        self._seq += 1
        events.append(
            {
                "seq": self._seq,
                # wall anchor: the ONE timestamp comparable across pods,
                # which is what cross-pod stitching orders by — durations
                # derived from it are display/stitch math, never engine
                # latency measurement (those stay monotonic)
                # graftcheck: disable=OBS501 cross-pod stitch anchor, same rule as span start_ms
                "t_ms": round(time.time() * 1000.0, 3),
                "m_s": round(time.monotonic(), 3),
                "kind": kind,
                **detail,
            }
        )
        entry["recorded"] += 1
        self.recorded_events += 1

    # -- reading (snapshots; never block the writers) --------------------

    def events(self, journey_id: str) -> list[dict[str, Any]]:
        """One journey's events, oldest first (empty when unknown)."""
        entry = self._entries.get(journey_id)
        if entry is None:
            return []
        return list(entry["events"])

    def ids(self) -> list[str]:
        return list(self._entries)

    def summaries(self) -> list[dict[str, Any]]:
        """The ``/journey`` index: per journey, event count, retained vs
        recorded, and the first/last edge."""
        out = []
        for journey_id, entry in list(self._entries.items()):
            events = list(entry["events"])
            out.append(
                {
                    "journey": journey_id,
                    "events": len(events),
                    "recorded": entry["recorded"],
                    "first": events[0].get("kind") if events else None,
                    "last": events[-1].get("kind") if events else None,
                    "t_ms": events[0].get("t_ms") if events else None,
                }
            )
        return out

    def stats(self) -> dict[str, Any]:
        return {
            "requests": len(self._entries),
            "max_requests": self.max_requests,
            "max_events": self.max_events,
            "recorded_events": self.recorded_events,
            "evicted_requests": self.evicted_requests,
            "dropped_events": self.dropped_events,
        }

    def clear(self) -> None:
        self._entries.clear()


#: the process-global ledger the pod ``/journey`` endpoints serve (one
#: pod = one process = one ledger, the SPANS/flight pattern)
JOURNEYS = JourneyLedger()
