"""KV-block handoff plane: serialize a request's paged-KV state for
disaggregated prefill/decode pools (docs/DISAGG.md).

Production engines split prefill and decode into separate pools
(DistServe/Splitwise): a prefill replica computes a prompt's KV blocks,
then hands the request to a decode replica so one long prompt can never
steal a decode step. This module is the wire between the pools — the
serialization half of ROADMAP item 3, carried over the existing pod HTTP
plane (``POST /kv/import`` / ``GET /kv/export/{request}``; a
device-to-device path can ride the same header later).

Wire format (version |WIRE_VERSION|)::

    b"LSKV" | u32 version | u32 header_len | header JSON | raw arrays

The JSON header carries the **layout fingerprint** (model, dtype,
kv-quantize mode, block size, cache geometry — the facts that decide
whether a foreign pool's rows can land in ours at all), the **prompt
digest** (chained blake2b, same construction as the prefix cache's
block digests), the generated-token snapshot, the per-request sampling
params, the **trace context** (``trace``: the ``langstream-trace``
header value, so the decode pool's ``engine.kv-import``/``engine.decode``
spans join the prefill-side trace; ``journey``: the request-journey
ledger key, serving/journey.py) with the prefill-side span ``timings``
(queue-wait / prefill / ttft), and an array manifest
(name/dtype/shape/byte offsets). Arrays
follow as raw bytes in manifest order: the K and V rows of the slot's
live positions, gathered dense from the paged pool — ``{"k","v"}`` for
bf16/f32 pools, ``{"k.q","k.s","v.q","v.s"}`` for int8 pools (the
quantized rows travel verbatim, so an export→import round trip is
bit-exact: no dequant/requant ever happens in transit).

Import is admission, not prefill: the receiving engine allocates blocks
through its :class:`~langstream_tpu.models.paged.BlockManager`, scatters
the rows back with :func:`~langstream_tpu.models.paged.write_rows`, and
the request joins the decode batch directly — greedy output is
byte-identical to a co-located run (pinned by test; the generated
tokens + sampling params + KV rows ARE the complete state, exactly the
invariant the QoS preemption snapshot already proved).

Hot-path discipline (graftcheck POOL701, OBS504's shape over this
module): serialization is header JSON plus ``tobytes`` on HOST arrays —
no blocking I/O, no locks, and the ONE device sync lives in the
sanctioned fetch point :func:`fetch_rows` (called on the engine's
dispatch thread and timed, like the engine's ``_fetch_chunk``).
"""

from __future__ import annotations

import hashlib
import json
import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from langstream_tpu.models.paged import gather_kv, write_rows

WIRE_MAGIC = b"LSKV"
WIRE_VERSION = 1

#: fingerprint keys that must match exactly between pools — a mismatch
#: on any of them means the raw rows are garbage in the other layout
FINGERPRINT_KEYS = (
    "model",
    "dtype",
    "kv-quantize",
    "kv-block-size",
    "layers",
    "kv-heads",
    "head-dim",
    "max-seq-len",
)


class LayoutMismatch(ValueError):
    """The payload cannot land in this engine: wrong magic/version, or a
    layout fingerprint that disagrees on any geometry/dtype fact. The
    pod ``/kv/import`` handler maps this to HTTP 409 — a refusal, never
    a retry (no decode replica of the same fleet will accept it either)."""


def trace_context(header: dict[str, Any]):
    """The handoff header's trace coordinate back as a
    :class:`~langstream_tpu.core.tracing.TraceContext` (None when the
    header carries none, or a malformed one — a bad trace must never
    refuse a handoff the layout accepts)."""
    from langstream_tpu.core.tracing import TraceContext

    return TraceContext.parse(header.get("trace"))


def journey_id(header: dict[str, Any]) -> str | None:
    """The request-journey ledger key riding the header: the explicit
    ``journey`` field, falling back to the trace id (they are the same
    value for traced requests — serving/journey.py)."""
    jid = header.get("journey")
    if isinstance(jid, str) and jid:
        return jid
    ctx = trace_context(header)
    return ctx.trace_id if ctx is not None else None


def prompt_digest(tokens) -> str:
    """Content digest of a prompt (blake2b over int64 token bytes) — the
    header's identity check and the flight events' request key."""
    h = hashlib.blake2b(digest_size=16)
    h.update(np.asarray(list(tokens), dtype=np.int64).tobytes())
    return h.hexdigest()


def check_fingerprint(ours: dict[str, Any], theirs: dict[str, Any]) -> None:
    """Raise :class:`LayoutMismatch` naming every disagreeing key."""
    bad = [
        k
        for k in FINGERPRINT_KEYS
        if ours.get(k) != theirs.get(k)
    ]
    if bad:
        detail = ", ".join(
            f"{k}: ours={ours.get(k)!r} theirs={theirs.get(k)!r}" for k in bad
        )
        raise LayoutMismatch(f"KV layout fingerprint mismatch ({detail})")


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def _np_dtype(name: str) -> np.dtype:
    """Resolve a manifest dtype string, including the ml_dtypes names
    (``bfloat16``) numpy alone does not know. An unresolvable name is a
    :class:`LayoutMismatch` — a refusal the pod maps to 409 — never a
    raw AttributeError that would drop the connection with no HTTP
    answer (the prefill side must be able to tell "don't retry" from
    "pod crashed")."""
    try:
        return np.dtype(name)
    except TypeError:
        try:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, str(name)))
        except (AttributeError, TypeError, ImportError) as e:
            raise LayoutMismatch(
                f"unknown handoff array dtype {name!r}: {e}"
            ) from e


def serialize_handoff(
    header: dict[str, Any], arrays: dict[str, np.ndarray]
) -> bytes:
    """Pack header + arrays into the versioned wire format. Array order
    is the manifest order (sorted by name, so the bytes are a pure
    function of the content)."""
    manifest = []
    chunks: list[bytes] = []
    for name in sorted(arrays):
        arr = np.ascontiguousarray(arrays[name])
        manifest.append(
            {
                "name": name,
                "dtype": arr.dtype.name,
                "shape": list(arr.shape),
                "nbytes": int(arr.nbytes),
            }
        )
        chunks.append(arr.tobytes())
    full = {**header, "arrays": manifest}
    hjson = json.dumps(full, separators=(",", ":")).encode()
    head = (
        WIRE_MAGIC
        + WIRE_VERSION.to_bytes(4, "little")
        + len(hjson).to_bytes(4, "little")
    )
    return head + hjson + b"".join(chunks)


def peek_header(data: bytes) -> dict[str, Any]:
    """Parse and return the JSON header only (cheap, wait-free) —
    validates magic + version, never touches the array bytes."""
    if len(data) < 12 or data[:4] != WIRE_MAGIC:
        raise LayoutMismatch(
            "not a KV handoff payload (bad magic; expected LSKV)"
        )
    version = int.from_bytes(data[4:8], "little")
    if version != WIRE_VERSION:
        raise LayoutMismatch(
            f"unsupported KV handoff wire version {version} "
            f"(this engine speaks {WIRE_VERSION})"
        )
    hlen = int.from_bytes(data[8:12], "little")
    if len(data) < 12 + hlen:
        raise LayoutMismatch("truncated KV handoff payload (header)")
    try:
        header = json.loads(data[12 : 12 + hlen])
    except ValueError as e:
        raise LayoutMismatch(f"malformed KV handoff header: {e}") from e
    if not isinstance(header, dict):
        raise LayoutMismatch("malformed KV handoff header: not an object")
    return header


def deserialize_handoff(
    data: bytes, header: dict[str, Any] | None = None
) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
    """Header + named arrays back from the wire. Arrays are zero-copy
    read-only views over ``data`` (the scatter's ``jnp.asarray`` copies
    to device anyway). A caller that already ran :func:`peek_header`
    (the pod's engine-routing step) passes it back so the header JSON —
    which embeds the full token lists — parses exactly once per
    import."""
    if header is None:
        header = peek_header(data)
    hlen = int.from_bytes(data[8:12], "little")
    offset = 12 + hlen
    arrays: dict[str, np.ndarray] = {}
    for entry in header.get("arrays") or []:
        nbytes = int(entry["nbytes"])
        if len(data) < offset + nbytes:
            raise LayoutMismatch(
                f"truncated KV handoff payload (array {entry['name']!r})"
            )
        arrays[entry["name"]] = np.frombuffer(
            data, dtype=_np_dtype(entry["dtype"]),
            count=int(np.prod(entry["shape"], dtype=np.int64)),
            offset=offset,
        ).reshape(entry["shape"])
        offset += nbytes
    return header, arrays


# ---------------------------------------------------------------------------
# gather (export side) — jit-pure + the sanctioned fetch point
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("num_blocks",))
def _gather_one(cache, tables, num_blocks: int):
    """Densify one slot's first ``num_blocks`` blocks (the paged
    reference read, batch of one)."""
    return gather_kv(cache, tables, num_blocks)


def gather_slot(cache_k, cache_v, table_row: np.ndarray, num_blocks: int):
    """Async-dispatch the gather of one slot's K and V blocks. Returns
    device arrays ``(L, 1, num_blocks*bs, KhD)`` (int8 pools: the
    ``{"q","s"}`` tree each) — call :func:`fetch_rows` to sync + slice."""
    tables = jnp.asarray(
        np.asarray(table_row, dtype=np.int32)[None, :num_blocks]
    )
    return (
        _gather_one(cache_k, tables, num_blocks),
        _gather_one(cache_v, tables, num_blocks),
    )


def _fetch_rows(gathered_k, gathered_v, rows: int):
    """The designated device fetch of the export path (graftcheck
    POOL701 polices syncs anywhere else in this module; the ``_fetch``
    prefix marks it a fetch stage for the whole-graph INV902 too): ONE
    timed block-and-copy per export, run on the engine's dispatch thread
    like ``_fetch_chunk``. Returns ``({name: host array},
    device_seconds)`` with arrays sliced to the slot's live ``rows``
    positions."""
    t_dev = time.monotonic()
    jax.block_until_ready((gathered_k, gathered_v))
    device_s = time.monotonic() - t_dev

    def _host(tree, prefix: str) -> dict[str, np.ndarray]:
        if isinstance(tree, dict):
            return {
                f"{prefix}.{leaf}": np.asarray(tree[leaf])[:, 0, :rows]
                for leaf in sorted(tree)
            }
        return {prefix: np.asarray(tree)[:, 0, :rows]}

    arrays = {**_host(gathered_k, "k"), **_host(gathered_v, "v")}
    return arrays, device_s


#: public spelling of the sanctioned fetch stage
fetch_rows = _fetch_rows


# ---------------------------------------------------------------------------
# scatter (import side) — jit-pure, donates the pools
# ---------------------------------------------------------------------------


@partial(jax.jit, donate_argnums=(0, 1))
def _scatter_pools(cache_k, cache_v, k_rows, v_rows, tables, starts, valid):
    """Write one imported slot's rows into both pools (donated — the
    caller rebinds, same contract as every engine dispatch)."""
    return (
        write_rows(cache_k, k_rows, tables, starts, valid),
        write_rows(cache_v, v_rows, tables, starts, valid),
    )


def _rows_tree(
    arrays: dict[str, np.ndarray], prefix: str, rows: int, padded: int
):
    """Rebuild one cache's row payload from the manifest arrays, padded
    to ``padded`` positions (pad rows are masked to the scratch block by
    ``valid``). int8 pools travel as the quantized ``{"q","s"}`` pair and
    scatter verbatim — bit-exact in transit."""

    def _pad(a: np.ndarray) -> jnp.ndarray:
        L = a.shape[0]
        out = np.zeros((L, 1, padded) + a.shape[2:], dtype=a.dtype)
        out[:, 0, :rows] = a[:, :rows]
        return jnp.asarray(out)

    if prefix in arrays:
        return _pad(arrays[prefix])
    quant = {
        leaf: _pad(arrays[f"{prefix}.{leaf}"])
        for leaf in ("q", "s")
        if f"{prefix}.{leaf}" in arrays
    }
    if set(quant) != {"q", "s"}:
        raise LayoutMismatch(
            f"handoff payload missing {prefix!r} rows "
            f"(have {sorted(arrays)})"
        )
    return quant


def scatter_slot(
    cache_k,
    cache_v,
    arrays: dict[str, np.ndarray],
    table_row: np.ndarray,
    rows: int,
    padded_rows: int,
):
    """Scatter an imported slot's rows into the (donated) pools via the
    slot's freshly allocated block table. Returns the new pool handles —
    async dispatch; the caller's dispatch-thread closure syncs/times."""
    k_rows = _rows_tree(arrays, "k", rows, padded_rows)
    v_rows = _rows_tree(arrays, "v", rows, padded_rows)
    tables = jnp.asarray(np.asarray(table_row, dtype=np.int32)[None, :])
    starts = jnp.zeros((1,), dtype=jnp.int32)
    valid = jnp.asarray((np.arange(padded_rows) < rows)[None, :])
    return _scatter_pools(
        cache_k, cache_v, k_rows, v_rows, tables, starts, valid
    )
