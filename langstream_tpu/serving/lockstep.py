"""Multi-host lockstep execution for the serving engine.

The problem (SURVEY §7 hard part (c)): a serving engine sharded over a
multi-host TPU slice is a JAX *multi-controller* program — *every* process
in the group must execute the same jitted computation in the same order, or
the first cross-host collective hangs. But only one host (the slice leader)
consumes requests from the broker, admits them into slots, and samples; the
followers know nothing about arrivals.

The design here: the leader broadcasts a compact **step descriptor** over a
TCP side channel before every jitted dispatch — the op kind (prefill /
decode variant), the static specialization (prompt bucket, attention window,
top-p flag) and the host-side inputs (token ids, lengths, slot masks,
sampling params, the split RNG key). Followers replay each descriptor as the
identical jit call on their shards of the same global arrays. Ordering is
TCP FIFO; the device collectives themselves ride ICI as usual — the side
channel carries only a few hundred bytes of control per chunk, so it is
never the bottleneck (one descriptor per ``decode_chunk`` steps, not per
token).

Why a TCP channel and not device-collective broadcast
(``multihost_utils.broadcast_one_to_all``): descriptor shapes vary by op
(prefill buckets, batch sizes), which a device broadcast must know ahead of
time on every host; a byte stream has no such constraint, keeps the control
plane off the devices entirely, and fails loudly (socket error) instead of
hanging a collective when a host dies.

Wire format (no pickle — the channel crosses pod boundaries):
``u32 big-endian frame length | JSON header | concatenated raw array
bytes``; the header maps argument names to dtype/shape/offset.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Any

import numpy as np

log = logging.getLogger(__name__)

DEFAULT_PORT = 7077


class LockstepBroken(RuntimeError):
    """The lockstep group lost a member (or the channel failed) — partial
    frame delivery is unrecoverable (survivors would run collectives the
    others never heard about), so the slice must restart as a unit. The
    engine fails in-flight work and stops serving when it sees this."""


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def encode_descriptor(desc: dict[str, Any]) -> bytes:
    """``desc``: flat dict of scalars (str/int/float/bool/None) and numpy
    arrays. Arrays are shipped raw; everything else rides the JSON header."""
    scalars: dict[str, Any] = {}
    arrays: dict[str, dict[str, Any]] = {}
    blobs: list[bytes] = []
    offset = 0
    for key, value in desc.items():
        if isinstance(value, np.ndarray):
            raw = np.ascontiguousarray(value)
            blob = raw.tobytes()
            arrays[key] = {
                "dtype": str(raw.dtype),
                "shape": list(raw.shape),
                "offset": offset,
                "nbytes": len(blob),
            }
            blobs.append(blob)
            offset += len(blob)
        else:
            scalars[key] = value
    header = json.dumps({"scalars": scalars, "arrays": arrays}).encode()
    payload = struct.pack(">I", len(header)) + header + b"".join(blobs)
    return struct.pack(">I", len(payload)) + payload


def decode_descriptor(payload: bytes) -> dict[str, Any]:
    (header_len,) = struct.unpack(">I", payload[:4])
    header = json.loads(payload[4 : 4 + header_len])
    out: dict[str, Any] = dict(header["scalars"])
    base = 4 + header_len
    for key, meta in header["arrays"].items():
        start = base + meta["offset"]
        out[key] = np.frombuffer(
            payload[start : start + meta["nbytes"]], dtype=meta["dtype"]
        ).reshape(meta["shape"])
    return out


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("lockstep peer closed the channel")
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock: socket.socket) -> dict[str, Any]:
    (length,) = struct.unpack(">I", _read_exact(sock, 4))
    return decode_descriptor(_read_exact(sock, length))


# ---------------------------------------------------------------------------
# leader
# ---------------------------------------------------------------------------


class LockstepLeader:
    """Process-0 side: accepts follower connections, handshakes the serving
    config, then fans every descriptor out in order. ``broadcast`` is called
    from the engine's single dispatch thread, so frames reach every follower
    in dispatch order.

    Membership is fixed at slice start: a follower that dies cannot rejoin
    (its JAX process left the distributed group; collectives with a fresh
    process would hang) — the slice restarts as a unit, which is the
    StatefulSet's job. Late/extra connectors get an explicit reject frame
    instead of a silent hang. Joins are authenticated with the shared
    ``token`` (``LS_LOCKSTEP_TOKEN``, injected by the manifest factory) so
    an arbitrary in-cluster connector can neither read prompt descriptors
    nor steal a membership slot."""

    def __init__(self, serving_config_dict: dict[str, Any],
                 expected_followers: int, port: int | None = None,
                 token: str = ""):
        self.expected = expected_followers
        self.handshake = serving_config_dict
        self.token = token
        self._followers: list[socket.socket] = []
        self._lock = threading.Lock()
        self._broken = False
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("0.0.0.0", port if port is not None else DEFAULT_PORT))
        self._server.listen(max(expected_followers, 1))
        self.port = self._server.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="lockstep-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, addr = self._server.accept()
            except OSError:
                return  # closed
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                join = read_frame(conn)
                if join.get("op") != "join" or join.get("token", "") != self.token:
                    log.warning("lockstep: rejecting unauthenticated %s", addr)
                    conn.sendall(encode_descriptor(
                        {"op": "reject", "reason": "bad token"}
                    ))
                    conn.close()
                    continue
                with self._lock:
                    if self._broken or len(self._followers) >= self.expected:
                        # a restarted follower is a fresh JAX process the
                        # group cannot re-admit — tell it so, loudly
                        conn.sendall(encode_descriptor({
                            "op": "reject",
                            "reason": "slice membership is full or broken; "
                                      "the whole slice must restart together",
                        }))
                        conn.close()
                        continue
                    conn.sendall(
                        encode_descriptor({"op": "handshake", **self.handshake})
                    )
                    self._followers.append(conn)
                    joined = len(self._followers)
                log.info(
                    "lockstep follower %s joined (%d/%d)",
                    addr, joined, self.expected,
                )
            except (OSError, ConnectionError) as e:
                log.warning("lockstep accept of %s failed: %s", addr, e)
                try:
                    conn.close()
                except OSError:
                    pass

    def wait_ready(self, timeout: float = 600.0) -> None:
        """Block until every follower is connected — the first multi-host
        dispatch would otherwise broadcast into the void and hang the
        devices waiting for processes that never heard about the step."""
        deadline = time.monotonic() + timeout
        while len(self._followers) < self.expected:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {len(self._followers)}/{self.expected} lockstep "
                    f"followers joined within {timeout}s"
                )
            time.sleep(0.05)

    def broadcast(self, desc: dict[str, Any]) -> None:
        """Send to every follower. Any send failure poisons the group:
        surviving followers may have replayed frames a dead one never saw,
        so the only safe outcome is a loud LockstepBroken — the engine
        stops serving and the slice restarts together."""
        frame = encode_descriptor(desc)
        failed: list[str] = []
        with self._lock:
            if self._broken:
                raise LockstepBroken("lockstep group already failed")
            for conn in self._followers:
                try:
                    conn.sendall(frame)
                except OSError as e:
                    failed.append(str(e))
            if failed:
                self._broken = True
                for conn in self._followers:
                    try:
                        conn.close()
                    except OSError:
                        pass
                self._followers.clear()
        if failed:
            raise LockstepBroken(
                f"lost lockstep follower(s): {failed}; slice must restart"
            )

    def close(self) -> None:
        try:
            self.broadcast({"op": "stop"})
        except (OSError, LockstepBroken):
            pass
        with self._lock:
            for conn in self._followers:
                try:
                    conn.close()
                except OSError:
                    pass
            self._followers.clear()
        try:
            self._server.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# follower
# ---------------------------------------------------------------------------


class LockstepFollower:
    """Non-leader host: connects to the leader, builds the *same* engine
    state (params, caches, compiled functions — identical construction path,
    so identical global arrays), then replays descriptors as jit calls until
    the leader says stop. Runs synchronously; call from the follower pod's
    main thread."""

    def __init__(self, leader_host: str, port: int | None = None,
                 connect_timeout: float = 600.0, token: str = ""):
        self.addr = (leader_host, port if port is not None else DEFAULT_PORT)
        self.connect_timeout = connect_timeout
        self.token = token
        self.engine = None
        # stop() is a cross-thread signal: the pod's event loop calls it
        # while run() blocks in recv on the replay thread — the flag is a
        # threading.Event (a designated handoff, RACE801) and the socket
        # handle is guarded so stop() never races the assignment in run()
        self._sock_lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._stopping = threading.Event()

    def stop(self) -> None:
        """Unblock a blocked ``run`` (SIGTERM path): closing the socket
        makes the pending recv raise, and ``run`` returns cleanly. Safe to
        call from any thread (the pod's loop calls it on SIGTERM while
        the replay thread owns the socket)."""
        self._stopping.set()
        with self._sock_lock:
            sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _connect(self) -> socket.socket:
        deadline = time.monotonic() + self.connect_timeout
        while True:
            if self._stopping.is_set():
                # stop() landed while we were still retrying the connect:
                # there is no socket to close yet, so the flag is the only
                # way out of the retry loop
                raise ConnectionAbortedError("lockstep follower stopping")
            try:
                sock = socket.create_connection(self.addr, timeout=10.0)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(None)
                return sock
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)

    def run(self, die_after_steps: int | None = None) -> int:
        """Returns the number of descriptors replayed (for tests/logs).

        ``die_after_steps`` is fault injection (the failure tests' analogue
        of the reference's mock fail-on-content agents): after replaying N
        descriptors the process dies via ``os._exit`` — no socket shutdown,
        no goodbye — exactly what a follower pod being OOM-killed mid-burst
        looks like to the leader."""
        import jax.numpy as jnp

        from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

        try:
            sock = self._connect()
        except ConnectionAbortedError:
            return 0  # stop() before any connection: nothing replayed
        with self._sock_lock:
            self._sock = sock
            stopping = self._stopping.is_set()
        if stopping:
            # stop() ran between _connect and the assignment above: it saw
            # _sock as None and closed nothing — close here or the recv
            # loop below would block forever with the flag already set
            try:
                sock.close()
            except OSError:
                pass
            return 0
        sock.sendall(encode_descriptor({"op": "join", "token": self.token}))
        handshake = read_frame(sock)
        if handshake.get("op") == "reject":
            raise RuntimeError(
                f"lockstep join rejected: {handshake.get('reason')}"
            )
        if handshake.get("op") != "handshake":
            raise RuntimeError(f"expected handshake, got {handshake.get('op')}")
        config = ServingConfig.from_dict(json.loads(handshake["config_json"]))
        # identical construction path as the leader's engine → identical
        # sharded params/caches/compiled fns on this host's shards
        self.engine = engine = TpuServingEngine(config, lockstep_role="follower")
        steps = 0
        log.info("lockstep follower ready (model %s)", config.model)
        # burst-scoped state: a "decode" descriptor opens a burst with full
        # host inputs; "decode_cont" chunks chain this process's own
        # device-resident tokens/lengths outputs, mirroring the leader's
        # speculative pipeline without any host round-trip
        burst: dict[str, Any] = {}
        carry_tokens = carry_lengths = None
        while True:
            try:
                desc = read_frame(sock)
            except (ConnectionError, OSError):
                if self._stopping.is_set():
                    break  # stop() closed the socket: clean local shutdown
                raise
            op = desc.get("op")
            if op == "stop":
                break
            if op in ("decode", "decode_cont"):
                if op == "decode":
                    burst = {
                        "sampler_mode": tuple(bool(x) for x in desc["sampler_mode"]),
                        "active": jnp.asarray(desc["active"]),
                        "temps": jnp.asarray(desc["temps"]),
                        "topks": jnp.asarray(desc["topks"]),
                        "topps": jnp.asarray(desc["topps"]),
                    }
                    tokens = jnp.asarray(desc["tokens"])
                    lengths = jnp.asarray(desc["lengths"])
                else:
                    tokens, lengths = carry_tokens, carry_lengths
                    if "active" in desc:
                        # pipelined finished-slot freeze: the leader
                        # refreshes the active mask mid-burst; followers
                        # must apply the same mask or their frozen slots'
                        # device state diverges from the leader's
                        burst["active"] = jnp.asarray(desc["active"])
                window = desc.get("window")
                pen = bool(desc.get("pen"))
                fn = engine._decode_fn(
                    burst["sampler_mode"], window, int(desc.get("k", 0)), pen
                )
                args = [
                    engine.params, engine.cache_k, engine.cache_v,
                    tokens, lengths, burst["active"],
                ]
                if engine.block_mgr is not None:
                    args.append(jnp.asarray(desc["tables"]))
                args += [
                    jnp.asarray(desc["key"]), burst["temps"],
                    burst["topks"], burst["topps"],
                ]
                if pen:
                    # penalty bursts are sequential on the leader, so every
                    # frame carries fresh pres/freq/counts host state
                    args += [
                        jnp.asarray(desc["pres"]), jnp.asarray(desc["freq"]),
                        jnp.asarray(desc["counts"]),
                    ]
                out = fn(*args)
                # out[0] is the packed tokens+logprobs array (sample-in-
                # program): followers never fetch it — only the leader
                # crosses the host boundary
                carry_tokens, carry_lengths = out[1], out[2]
                engine.cache_k, engine.cache_v = out[3], out[4]
            elif op == "prefill":
                fn = engine._prefill_fn(
                    tuple(bool(x) for x in desc["sampler_mode"])
                )
                out = fn(
                    engine.params, engine.cache_k, engine.cache_v,
                    jnp.asarray(desc["tokens"]), jnp.asarray(desc["lengths"]),
                    jnp.asarray(desc["sel"]), jnp.asarray(desc["key"]),
                    jnp.asarray(desc["temps"]), jnp.asarray(desc["topks"]),
                    jnp.asarray(desc["topps"]),
                )
                engine.cache_k, engine.cache_v = out[2], out[3]
            elif op == "spec_step":
                # fused draft+verify: drafting reads the device-resident
                # context rows, so the descriptor carries only control
                # state plus whichever rows the leader re-synced this step
                # — replay the same jit (same key, so sampled acceptance
                # matches bit-for-bit)
                if engine._ctx_dev is None:
                    engine._ctx_dev = jnp.zeros(
                        (engine.config.slots,
                         engine.model_config.max_seq_len),
                        dtype=jnp.int32,
                    )
                if "ctx_rows" in desc:
                    engine._ctx_dev = engine._ctx_dev.at[
                        jnp.asarray(desc["ctx_rows"])
                    ].set(jnp.asarray(desc["ctx_vals"]))
                fn = engine._spec_step_fn(
                    int(desc["nrb"]),
                    tuple(bool(x) for x in desc["sampler_mode"]),
                )
                out = fn(
                    engine.params, engine.cache_k, engine.cache_v,
                    engine._ctx_dev,
                    jnp.asarray(desc["current"]), jnp.asarray(desc["lengths"]),
                    jnp.asarray(desc["active"]), jnp.asarray(desc["tables"]),
                    jnp.asarray(desc["key"]), jnp.asarray(desc["temps"]),
                    jnp.asarray(desc["topks"]), jnp.asarray(desc["topps"]),
                )
                engine._ctx_dev = out[1]
                engine.cache_k, engine.cache_v = out[2], out[3]
            elif op == "prefill_continue":
                # prefix-cache suffix prefill: block adoption is host state
                # the leader already resolved — the follower just replays
                # the same jit with the same tables/starts
                fn = engine._prefill_continue_fn(
                    tuple(bool(x) for x in desc["sampler_mode"]),
                    int(desc["nrb"]),
                )
                out = fn(
                    engine.params, engine.cache_k, engine.cache_v,
                    jnp.asarray(desc["tokens"]), jnp.asarray(desc["starts"]),
                    jnp.asarray(desc["lengths"]), jnp.asarray(desc["sel"]),
                    jnp.asarray(desc["key"]), jnp.asarray(desc["temps"]),
                    jnp.asarray(desc["topks"]), jnp.asarray(desc["topps"]),
                )
                engine.cache_k, engine.cache_v = out[2], out[3]
            else:
                raise RuntimeError(f"unknown lockstep op {op!r}")
            steps += 1
            if die_after_steps is not None and steps >= die_after_steps:
                log.error("fault injection: follower dying after %d steps", steps)
                os._exit(3)
        sock.close()
        return steps
