"""Two-process lockstep serving on virtual CPU devices — the executable
proof that multi-host TP serving actually runs (leader consumes + samples,
follower replays collective programs; both execute the same jitted steps on
a mesh spanning both processes).

Run as two processes (the test and ``dryrun_multichip`` spawn these):

    python -m langstream_tpu.serving.lockstep_demo \
        --index 0 --num-processes 2 --coordinator-port P --lockstep-port Q \
        --out /tmp/leader.json
    python -m langstream_tpu.serving.lockstep_demo \
        --index 1 --num-processes 2 --coordinator-port P --lockstep-port Q

Each process owns 4 virtual CPU devices; the engine shards over the global
(dp=2, tp=4) mesh, so every prefill/decode crosses the process boundary
through XLA collectives. The leader writes its generated token streams to
``--out`` for the caller to compare against a single-process run.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
from pathlib import Path


def _force_cpu(devices_per_proc: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={devices_per_proc}"
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


PROMPTS = ["hello tpu world", "lockstep decode", "multi host serving"]


async def _drive(engine) -> list[list[int]]:
    max_tokens = int(os.environ.get("LS_DEMO_MAX_TOKENS", "6"))
    results = await asyncio.gather(
        *(engine.generate(p, {"max-tokens": max_tokens}) for p in PROMPTS)
    )
    if os.environ.get("LS_DEMO_LEADER_ABRUPT_EXIT") == "1":
        # leader-death injection: skip close() — a clean close broadcasts a
        # "stop" frame, which is exactly what a crashed leader never sends
        return [r["tokens"] for r in results]
    await engine.close()
    return [r["tokens"] for r in results]


def run_process(
    index: int,
    num_processes: int,
    coordinator_port: int,
    lockstep_port: int,
    out_path: str | None = None,
    devices_per_proc: int = 4,
) -> None:
    _force_cpu(devices_per_proc)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coordinator_port}",
        num_processes=num_processes,
        process_id=index,
    )
    # force backend init NOW: the multi-process topology exchange needs every
    # process to bring its backend up; a follower that first waits for the
    # lockstep handshake would deadlock the leader's own backend init
    jax.devices()
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    config = demo_config(num_processes * devices_per_proc)
    if index == 0:
        from langstream_tpu.serving.lockstep import LockstepBroken

        os.environ["LS_LOCKSTEP_PORT"] = str(lockstep_port)
        engine = TpuServingEngine(config)
        try:
            tokens = asyncio.run(_drive(engine))
        except LockstepBroken as e:
            # fail-loud contract (VERDICT r3 #8): in-flight work already
            # failed with this error; exit nonzero so the StatefulSet
            # restarts the whole slice together
            print(
                f"leader saw LockstepBroken: {e}; engine stopped serving: "
                f"{engine._stop}",
                file=sys.stderr, flush=True,
            )
            # os._exit: a normal exit would run jax.distributed's shutdown
            # barrier, which (with a dead member) aborts the process and
            # replaces this deliberate exit code
            os._exit(5)
        if out_path:
            Path(out_path).write_text(json.dumps(tokens))
        if os.environ.get("LS_DEMO_LEADER_ABRUPT_EXIT") == "1":
            # fault injection: die without broadcasting "stop" — what a
            # crashed leader pod looks like to the followers
            print("fault injection: leader abrupt exit", file=sys.stderr, flush=True)
            os._exit(4)
    else:
        from langstream_tpu.serving.lockstep import LockstepFollower

        die_after = int(os.environ.get("LS_DEMO_FOLLOWER_DIE_AFTER", "0"))
        steps = LockstepFollower("127.0.0.1", lockstep_port).run(
            die_after_steps=die_after or None
        )
        print(f"follower replayed {steps} steps", file=sys.stderr)


def demo_config(total_devices: int):
    from langstream_tpu.serving.engine import ServingConfig

    # LS_DEMO_KV=paged exercises the block-pool cache across the process
    # boundary (block tables ride the lockstep descriptors);
    # LS_DEMO_SPEC=N additionally runs greedy bursts speculatively (the
    # "verify" descriptor replays host drafts on the followers)
    kv_layout = os.environ.get("LS_DEMO_KV", "dense")
    spec = int(os.environ.get("LS_DEMO_SPEC", "0"))
    return ServingConfig(
        model="tiny",
        slots=4,
        max_seq_len=64,
        decode_chunk=4,
        prefill_batch=2,
        seed=0,
        kv_layout=kv_layout,
        kv_block_size=16,
        speculative_drafts=spec,
        # tiny model: 2 kv heads caps tp at 2; the rest of the devices go dp
        mesh=(("dp", total_devices // 2), ("tp", 2)),
    )


def run_single_process_reference(total_devices: int = 8) -> list[list[int]]:
    """The same workload on one process with ``total_devices`` virtual
    devices — the golden stream the 2-process run must reproduce."""
    from langstream_tpu.serving.engine import TpuServingEngine

    engine = TpuServingEngine(demo_config(total_devices))
    return asyncio.run(_drive(engine))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", action="store_true",
                    help="single-process golden run instead of a group role")
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--coordinator-port", type=int, default=0)
    ap.add_argument("--lockstep-port", type=int, default=0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    args = ap.parse_args()
    if args.reference:
        total = args.num_processes * args.devices_per_proc
        _force_cpu(total)
        import jax

        jax.config.update("jax_platforms", "cpu")
        tokens = run_single_process_reference(total)
        if args.out:
            Path(args.out).write_text(json.dumps(tokens))
        return
    run_process(
        args.index, args.num_processes, args.coordinator_port,
        args.lockstep_port, args.out, args.devices_per_proc,
    )


if __name__ == "__main__":
    main()
