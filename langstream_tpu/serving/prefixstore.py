"""Tiered prefix-KV store: HBM → host-RAM spill → object storage
(docs/PREFIX.md, ROADMAP item 4).

LangStream's premise is that millions of sessions share the same
pipeline — which on the serving side means the same system-prompt
prefix blocks recomputed everywhere. The paged engine's automatic
prefix cache (models/paged.py) already shares committed prompt blocks
*within one replica's HBM*; this module extends that cache into three
explicit tiers so shared prefixes survive HBM pressure and cross
replica boundaries:

- **T0 — device HBM**: the existing content-addressed prefix blocks in
  the paged pool, now under an explicit byte budget (``t0-bytes``) read
  off the PR 10 memory ledger's ``kv_pool_prefix_bytes`` sub-owner.
  When the cache outgrows the budget, the engine *demotes* LRU
  cache-only leaf blocks: their rows are gathered to host (one timed
  dispatch-thread fetch, like every other device sync) and handed to
  this store.
- **T1 — host-RAM spill**: an LRU byte-budgeted (``t1-bytes``) map of
  demoted blocks as pinned host arrays, keyed by the SAME chained
  block digests the T0 cache uses. An admission whose prompt chain
  extends past its T0 match *promotes* T1 entries back into freshly
  allocated pool blocks (a dispatch-thread scatter through the
  kvtransfer pack path) and prefills only the remaining suffix.
- **T2 — object storage**: T1 overflow serializes through the PR 11
  kvtransfer wire format — ``LSKV`` magic, layout fingerprint, digest
  chain metadata, raw rows — into a :class:`PrefixStorage` backend
  (local disk for tests, S3-shaped for fleets, modeled on
  core/codestorage.py). A *different replica* of the same fleet finds
  the blob by digest, fingerprint-checks it exactly like ``/kv/import``
  (mismatch → refused AND deleted, never half-hydrated), and hydrates
  it into its own T1 → T0 → suffix prefill: a cross-replica cold start
  of a shared system prompt hydrates instead of recomputing.

Threading model (graftcheck **PFX801**, the tier plane's OBS504/POOL701
twin): every T0/T1 lookup, promotion take, insertion, and
eviction-decision path is **wait-free** — GIL-atomic container ops plus
arithmetic, no locks, no I/O, no device syncs — because they run at the
engine loop's safe point, on the admission path. The ONLY blocking work
is T2 object-storage I/O, exempt by design because it lives on the
background **hydrator thread** (``_io_*`` methods): the engine loop
communicates with it exclusively through handoff deques (jobs in,
results out) and applies results — ledger moves, T1 inserts, refusals —
back on the loop at the next safe point. Byte ledgers are therefore
single-writer (loop-side) and always sum exactly: every demotion,
promotion, hydration, and eviction moves its bytes between named
ledgers and emits a flight event; loss is counted, never silent.
"""

from __future__ import annotations

import abc
import dataclasses
import hashlib
import logging
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from pathlib import Path
from typing import Any, Callable

import numpy as np

from langstream_tpu.serving.kvtransfer import (
    LayoutMismatch,
    check_fingerprint,
    deserialize_handoff,
    serialize_handoff,
)

log = logging.getLogger(__name__)

#: blob kind stamped into every T2 header: a prefix-block blob is NOT a
#: request handoff, and an import path must be able to tell them apart
BLOB_KIND = "prefix-block"


# ---------------------------------------------------------------------------
# spec (the `prefix-store` section of tpu-serving-configuration)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PrefixStoreSpec:
    """Frozen, hashable tier policy (rides :class:`ServingConfig`, so it
    follows the same kebab ``to_dict``/``from_dict`` round-trip and
    deploy-time validation contract as qos/slo/autoscale specs)."""

    enabled: bool = True
    # T0 budget over the prefix sub-owner of the paged pool
    # (kv_pool_prefix_bytes); None = unbudgeted, no demotion pressure
    t0_bytes: int | None = None
    # T1 host-RAM budget (LRU eviction past it; overflow demotes to T2
    # when one is configured, else evicts — counted, never silent)
    t1_bytes: int = 256 << 20
    # T2 object-storage budget; None = unbudgeted (storage-side lifecycle
    # rules may still apply)
    t2_bytes: int | None = None
    # T2 backend config as sorted (key, value) pairs so the spec stays
    # hashable; () disables T2 (T1 overflow evicts). See
    # :func:`make_prefix_storage` for the schema.
    t2: tuple[tuple[str, str], ...] = ()
    # how long an admission may wait for a T2 hydration before falling
    # back to cold compute (the request is stashed, not head-blocking)
    hydrate_timeout_s: float = 5.0
    # hydrator-thread T2 index rescan period: how quickly this replica
    # notices blobs OTHER replicas published
    t2_rescan_s: float = 5.0

    def t2_config(self) -> dict[str, str] | None:
        return dict(self.t2) if self.t2 else None

    def to_dict(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "t0-bytes": self.t0_bytes,
            "t1-bytes": self.t1_bytes,
            "t2-bytes": self.t2_bytes,
            "t2": self.t2_config(),
            "hydrate-timeout-s": self.hydrate_timeout_s,
            "t2-rescan-s": self.t2_rescan_s,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any] | None) -> "PrefixStoreSpec | None":
        if d is None:
            return None
        if not isinstance(d, dict):
            raise ValueError("prefix-store section must be a mapping")
        known = {
            "enabled", "t0-bytes", "t0_bytes", "t1-bytes", "t1_bytes",
            "t2-bytes", "t2_bytes", "t2", "hydrate-timeout-s",
            "hydrate_timeout_s", "t2-rescan-s", "t2_rescan_s",
        }
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(f"unknown prefix-store keys: {unknown}")

        def _opt_bytes(kebab: str, snake: str) -> int | None:
            v = d.get(kebab, d.get(snake))
            if v is None:
                return None
            v = int(v)
            if v < 0:
                raise ValueError(f"prefix-store {kebab} must be >= 0")
            return v

        t1 = int(d.get("t1-bytes", d.get("t1_bytes", cls.t1_bytes)))
        if t1 <= 0:
            raise ValueError("prefix-store t1-bytes must be > 0")
        t2_cfg = d.get("t2")
        t2: tuple[tuple[str, str], ...] = ()
        if t2_cfg:
            if not isinstance(t2_cfg, dict):
                raise ValueError("prefix-store t2 must be a mapping")
            t2_type = str(t2_cfg.get("type", "local"))
            if t2_type not in ("local", "s3"):
                raise ValueError(
                    f"unknown prefix-store t2 type {t2_type!r} "
                    f"(known: local, s3)"
                )
            t2 = tuple(sorted((str(k), str(v)) for k, v in t2_cfg.items()))
        hydrate = float(
            d.get("hydrate-timeout-s",
                  d.get("hydrate_timeout_s", cls.hydrate_timeout_s))
        )
        rescan = float(
            d.get("t2-rescan-s", d.get("t2_rescan_s", cls.t2_rescan_s))
        )
        if hydrate <= 0 or rescan <= 0:
            raise ValueError(
                "prefix-store hydrate-timeout-s and t2-rescan-s must be > 0"
            )
        enabled = d.get("enabled", True)
        if isinstance(enabled, str):
            enabled = enabled.strip().lower() in ("1", "true", "yes", "on")
        return cls(
            enabled=bool(enabled),
            t0_bytes=_opt_bytes("t0-bytes", "t0_bytes"),
            t1_bytes=t1,
            t2_bytes=_opt_bytes("t2-bytes", "t2_bytes"),
            t2=t2,
            hydrate_timeout_s=hydrate,
            t2_rescan_s=rescan,
        )


def validate_application_prefix_store(application) -> None:
    """Deploy-time validation: parse every ``tpu-serving-configuration``
    resource's ``prefix-store`` section so a malformed tier policy fails
    the deploy (HTTP 400) instead of the first request — the same
    contract qos/slo/autoscale validation keeps."""
    for name, res in (getattr(application, "resources", None) or {}).items():
        if getattr(res, "type", None) != "tpu-serving-configuration":
            continue
        try:
            PrefixStoreSpec.from_dict(
                (res.configuration or {}).get("prefix-store")
            )
        except ValueError as e:
            raise ValueError(
                f"resource {name!r}: invalid prefix-store section: {e}"
            ) from e


# ---------------------------------------------------------------------------
# T2 storage backends (modeled on core/codestorage.py)
# ---------------------------------------------------------------------------


class PrefixStorage(abc.ABC):
    """Where T2 prefix-block blobs live. Keys are digest hexes (content
    addresses) — immutable blobs, so PUT/GET need no versioning. All
    methods are blocking I/O by design: they run ONLY on the hydrator
    thread (PFX801 exempts the backends wholesale)."""

    @abc.abstractmethod
    def put(self, key: str, blob: bytes) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> bytes | None: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    def list_keys(self) -> list[str]: ...

    def close(self) -> None: ...


class LocalDiskPrefixStorage(PrefixStorage):
    """Filesystem-backed T2 (shared volume / PV in-cluster, tmpdir in
    tests). One file per block: ``<root>/<digest>.kvp``."""

    SUFFIX = ".kvp"

    def __init__(self, root: Path | str):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        if not key or any(c in key for c in "/\\.") or ".." in key:
            raise ValueError(f"illegal prefix-storage key {key!r}")
        return self.root / f"{key}{self.SUFFIX}"

    def put(self, key: str, blob: bytes) -> None:
        # write-then-rename: a reader (another replica on a shared
        # volume) must never see a torn blob. The tmp name is
        # writer-unique — two replicas demoting the SAME digest
        # concurrently each rename their own file (content-addressed,
        # so last-writer-wins is identical bytes); a shared tmp name
        # would make the loser's rename fail and falsely ledger its
        # bytes as evicted
        path = self._path(key)
        tmp = path.with_name(f"{key}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp")
        tmp.write_bytes(blob)
        tmp.replace(path)

    def get(self, key: str) -> bytes | None:
        try:
            return self._path(key).read_bytes()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        self._path(key).unlink(missing_ok=True)

    def list_keys(self) -> list[str]:
        return sorted(
            p.name[: -len(self.SUFFIX)]
            for p in self.root.glob(f"*{self.SUFFIX}")
        )


class S3PrefixStorage(PrefixStorage):
    """S3/MinIO-backed T2 over the in-tree SigV4 REST client — the same
    posture :class:`~langstream_tpu.core.codestorage.S3CodeStorage`
    keeps (no SDK, lazy bucket creation)."""

    def __init__(self, configuration: dict[str, Any]):
        from langstream_tpu.agents.s3_impl import SyncS3Client

        self.bucket = configuration.get(
            "bucket-name", "langstream-prefix-store"
        )
        self.key_prefix = configuration.get("key-prefix", "prefix-kv")
        region = configuration.get("region", "") or "us-east-1"
        endpoint = (
            configuration.get("endpoint")
            or f"https://s3.{region}.amazonaws.com"
        )
        self.client = SyncS3Client(
            endpoint=endpoint,
            access_key=configuration.get("access-key", ""),
            secret_key=configuration.get("secret-key", ""),
            region=region,
        )
        self._bucket_ready = False

    def _key(self, key: str) -> str:
        return f"{self.key_prefix}/{key}.kvp"

    def put(self, key: str, blob: bytes) -> None:
        if not self._bucket_ready:
            if not self.client.bucket_exists(self.bucket):
                self.client.create_bucket(self.bucket)
            self._bucket_ready = True
        self.client.put_object(self.bucket, self._key(key), blob)

    def get(self, key: str) -> bytes | None:
        try:
            return self.client.get_object(self.bucket, self._key(key))
        except Exception:
            return None

    def delete(self, key: str) -> None:
        self.client.delete_object(self.bucket, self._key(key))

    def list_keys(self) -> list[str]:
        import urllib.parse

        from langstream_tpu.agents.s3_impl import _parse_list_objects

        out: list[str] = []
        token: str | None = None
        quoted_prefix = urllib.parse.quote(f"{self.key_prefix}/", safe="")
        while True:
            qs = f"?list-type=2&prefix={quoted_prefix}"
            if token:
                qs += "&continuation-token=" + urllib.parse.quote(token, safe="")
            _, body = self.client._request(
                "GET", f"/{self.bucket}{qs}", ok=(200,)
            )
            objects, token = _parse_list_objects(body)
            for obj in objects:
                name = str(obj.get("key") or "").rsplit("/", 1)[-1]
                if name.endswith(".kvp"):
                    out.append(name[: -len(".kvp")])
            if not token:
                return sorted(out)


def make_prefix_storage(
    configuration: dict[str, Any] | None,
) -> PrefixStorage | None:
    """Factory keyed by ``type`` (codestorage's registry shape). None /
    empty config = no T2 tier."""
    if not configuration:
        return None
    storage_type = configuration.get("type", "local")
    if storage_type == "local":
        path = configuration.get("path")
        if not path:
            raise ValueError("local prefix storage requires 'path'")
        return LocalDiskPrefixStorage(path)
    if storage_type == "s3":
        return S3PrefixStorage(configuration)
    raise ValueError(f"unknown prefix storage type {storage_type!r}")


# ---------------------------------------------------------------------------
# the tier store
# ---------------------------------------------------------------------------


class PrefixStore:
    """T1 host-RAM spill + T2 object-storage hydration for prefix
    blocks, with exact byte ledgers.

    Single-writer discipline: ALL ledger/counter/T1 mutations happen on
    the engine-loop side (:meth:`insert_t1` / :meth:`take_t1` /
    :meth:`apply_results`, called at the loop's safe point); the
    hydrator thread only performs storage I/O on job payloads and hands
    results back through ``_results``. That is what makes every read
    path wait-free (PFX801) and the ledgers exactly summing — there is
    no second writer to race.

    Conservation invariant (pinned by the property test)::

        t1_bytes + in_transit_bytes + t2_bytes
            == inserted + discovered - taken - evicted

    where every term is a monotone counter (``inserted`` counts every
    T1 arrival — demotions AND hydrations; ``hydrated_bytes`` is the
    informational hydration subtotal, not a second flow) and
    ``evicted`` covers every byte that left the store, each with a
    recorded reason.
    """

    #: max fetch/put jobs queued before new demotions are evicted
    #: instead (backpressure: a dead backend must not grow host memory)
    MAX_PENDING_JOBS = 256

    def __init__(
        self,
        spec: PrefixStoreSpec,
        *,
        fingerprint: dict[str, Any],
        block_bytes: int,
        rows_per_block: int,
        clock: Callable[[], float] = time.monotonic,
        fault_injector=None,
    ):
        self.spec = spec
        # network fault seam (serving/faults.py `t2-get` site): the
        # engine hands its armed injector down so a chaos test can drop
        # or delay the hydrator's object-storage fetch deterministically
        # (a failed fetch takes the existing hydrate-timeout → cold-
        # compute fallback, so the shapes compose). None in production.
        self._fault_injector = fault_injector
        self.fingerprint = dict(fingerprint)
        self.block_bytes = int(block_bytes)
        self.rows_per_block = int(rows_per_block)
        self._clock = clock
        # T1: digest hex -> {"parent": hex, "arrays": {name: np}, "nbytes"}
        # (insertion order = LRU; move_to_end on hit)
        self._t1: "OrderedDict[str, dict[str, Any]]" = OrderedDict()
        self.t1_bytes = 0
        # demotions being serialized/PUT on the hydrator (bytes stay
        # accounted until the put confirms — never in two tiers at once)
        self._t2_inflight: dict[str, dict[str, Any]] = {}
        self.in_transit_bytes = 0
        # T2 index: digest hex -> payload bytes (0 = discovered via scan,
        # size unknown until hydrated); insertion order = age for budget
        # trims
        self._t2_index: "OrderedDict[str, int]" = OrderedDict()
        self.t2_bytes = 0
        self.t2_blob_bytes = 0
        # digests with an in-flight T2 fetch (dedup + completion check)
        self._hydrating: dict[str, float] = {}
        # loop-side event feed for the engine's flight recorder
        self._events: deque = deque()
        # monotone counters (the conservation-equation terms + hit/miss)
        self.inserted_bytes = 0
        self.taken_bytes = 0
        self.hydrated_bytes = 0
        self.discovered_bytes = 0
        self.evicted_bytes = 0
        self.t1_hits = 0
        self.t1_misses = 0
        self.t2_hits = 0
        self.demotions_t0_t1 = 0
        self.demotions_t1_t2 = 0
        self.promotions = 0
        self.hydrations = 0
        self.hydrate_failures = 0
        self.fingerprint_refusals = 0
        self.evictions = 0
        self.scans = 0
        # hydrator plumbing: handoff deques + a kick event; the thread
        # starts only when a T2 backend is configured
        self._jobs: deque = deque()
        self._results: deque = deque()
        self._kick = threading.Event()
        self._storage = make_prefix_storage(spec.t2_config())
        self._thread: threading.Thread | None = None
        if self._storage is not None:
            self._jobs.append(("scan",))
            self._thread = threading.Thread(
                target=self._io_loop, name="prefix-hydrator", daemon=True
            )
            self._thread.start()

    # -- wait-free decision paths (PFX801) ------------------------------

    def t1_has(self, digest_hex: str) -> bool:
        return digest_hex in self._t1

    def t2_has(self, digest_hex: str) -> bool:
        """Wait-free T2 membership: the in-memory index maintained by
        put confirmations and hydrator rescans — never storage I/O."""
        return (
            digest_hex in self._t2_index
            or digest_hex in self._t2_inflight
        )

    def hydrating(self, digest_hex: str) -> bool:
        return digest_hex in self._hydrating

    def take_t1(self, digest_hex: str) -> dict[str, Any] | None:
        """Remove-and-return a T1 entry for promotion into T0 (the
        caller scatters its rows into freshly allocated pool blocks).
        Counts a hit or a miss; a miss returns None."""
        entry = self._t1.pop(digest_hex, None)
        if entry is None:
            self.t1_misses += 1
            return None
        self.t1_bytes -= entry["nbytes"]
        self.taken_bytes += entry["nbytes"]
        self.t1_hits += 1
        return entry

    def insert_t1(
        self,
        digest_hex: str,
        parent_hex: str,
        arrays: dict[str, np.ndarray],
        *,
        source: str = "t0",
    ) -> None:
        """Insert one demoted/hydrated block into T1 (loop-side). Past
        the byte budget the LRU tail demotes to T2 (when configured) or
        evicts — counted and evented either way."""
        if digest_hex in self._t1:
            return  # already resident (idempotent re-demote)
        nbytes = int(sum(a.nbytes for a in arrays.values()))
        self._t1[digest_hex] = {
            "parent": parent_hex,
            "arrays": arrays,
            "nbytes": nbytes,
            # hydrated entries are PINNED against the budget shrink for
            # one hydrate-timeout window: the admission that asked for
            # them promotes (takes) them within it, and without the pin
            # a tight T1 budget would evict the hydration before the
            # requeued request ever saw it (hydrate → evict → re-hydrate
            # livelock). Expired pins shrink normally — a shed request
            # can never pin host memory for good.
            "pinned_m": self._clock() if source == "t2" else None,
        }
        self.t1_bytes += nbytes
        self.inserted_bytes += nbytes
        if source == "t0":
            self.demotions_t0_t1 += 1
            self._events.append(
                (
                    "prefix-demote",
                    {
                        "tier": "t0->t1",
                        "digest": digest_hex[:16],
                        "bytes": nbytes,
                    },
                )
            )
        self._shrink_t1()

    def _shrink_t1(self) -> None:
        """Eviction decision for the T1 byte budget (wait-free: the LRU
        walk is dict arithmetic; the I/O of a demotion happens later on
        the hydrator)."""
        while self.t1_bytes > self.spec.t1_bytes and self._t1:
            victim = None
            now = self._clock()
            for digest_hex, entry in self._t1.items():  # LRU order
                pinned = entry.get("pinned_m")
                if (
                    pinned is not None
                    and now - pinned < self.spec.hydrate_timeout_s
                ):
                    continue
                victim = digest_hex
                break
            if victim is None:
                # everything live-pinned by in-flight hydrations: allow
                # the bounded overshoot (stash size × block bytes) and
                # let the pins expire
                return
            digest_hex = victim
            entry = self._t1.pop(victim)
            self.t1_bytes -= entry["nbytes"]
            if (
                self._storage is not None
                and digest_hex not in self._t2_index
                and digest_hex not in self._t2_inflight
                and len(self._jobs) < self.MAX_PENDING_JOBS
            ):
                self._t2_inflight[digest_hex] = entry
                self.in_transit_bytes += entry["nbytes"]
                self.demotions_t1_t2 += 1
                self._jobs.append(("put", digest_hex, entry))
                self._kick.set()
                self._events.append(
                    (
                        "prefix-demote",
                        {
                            "tier": "t1->t2",
                            "digest": digest_hex[:16],
                            "bytes": entry["nbytes"],
                        },
                    )
                )
            else:
                reason = (
                    "already-in-t2"
                    if digest_hex in self._t2_index
                    or digest_hex in self._t2_inflight
                    else ("t1-budget" if self._storage is None
                          else "hydrator-backlog")
                )
                # a copy already durable in T2 is dropped, not lost
                self.evictions += 1
                self.evicted_bytes += entry["nbytes"]
                self._events.append(
                    (
                        "prefix-evict",
                        {
                            "tier": "t1",
                            "digest": digest_hex[:16],
                            "bytes": entry["nbytes"],
                            "reason": reason,
                        },
                    )
                )

    def note_promoted(
        self, blocks: int, nbytes: int, device_ms: float = 0.0
    ) -> None:
        """Bookkeeping for a completed T1→T0 promotion (the engine owns
        the scatter; the store only counts it)."""
        self.promotions += 1
        self._events.append(
            ("prefix-promote", {"tier": "t1->t0", "blocks": blocks,
                                "bytes": nbytes,
                                "device_ms": round(device_ms, 3)})
        )

    def request_hydration(self, digest_hexes: list[str]) -> int:
        """Enqueue T2→T1 fetches for the given chain digests (dedup'd,
        backpressured). Returns how many fetches are now pending for
        them — 0 means nothing to wait for."""
        pending = 0
        for digest_hex in digest_hexes:
            if digest_hex in self._t1:
                continue
            if digest_hex in self._hydrating:
                pending += 1
                continue
            if digest_hex not in self._t2_index:
                continue
            if len(self._jobs) >= self.MAX_PENDING_JOBS:
                break
            self._hydrating[digest_hex] = self._clock()
            self._jobs.append(("fetch", digest_hex))
            pending += 1
        if pending:
            self._kick.set()
        return pending

    def apply_results(self) -> None:
        """Drain the hydrator's result deque and apply ledger moves +
        T1 inserts on the loop side (the single writer). Wait-free:
        container ops and arithmetic over already-fetched payloads."""
        while self._results:
            result = self._results.popleft()
            kind = result[0]
            if kind == "put-done":
                _, digest_hex, blob_bytes = result
                entry = self._t2_inflight.pop(digest_hex, None)
                if entry is None:
                    continue
                self.in_transit_bytes -= entry["nbytes"]
                self._t2_index[digest_hex] = entry["nbytes"]
                self.t2_bytes += entry["nbytes"]
                self.t2_blob_bytes += blob_bytes
                self._trim_t2()
            elif kind == "put-failed":
                _, digest_hex, error = result
                entry = self._t2_inflight.pop(digest_hex, None)
                if entry is None:
                    continue
                self.in_transit_bytes -= entry["nbytes"]
                self.evictions += 1
                self.evicted_bytes += entry["nbytes"]
                self._events.append(
                    (
                        "prefix-evict",
                        {
                            "tier": "t1->t2",
                            "digest": digest_hex[:16],
                            "bytes": entry["nbytes"],
                            "reason": f"put-failed: {error}"[:120],
                        },
                    )
                )
            elif kind == "fetch-done":
                _, digest_hex, parent_hex, arrays, nbytes = result
                self._hydrating.pop(digest_hex, None)
                known = self._t2_index.get(digest_hex)
                if known == 0:
                    # discovered via scan: size learned at first fetch
                    self._t2_index[digest_hex] = nbytes
                    self.t2_bytes += nbytes
                    self.discovered_bytes += nbytes
                self.t2_hits += 1
                self.hydrations += 1
                if digest_hex not in self._t1:
                    # (a racing re-demote may have re-inserted the digest
                    # while the fetch was in flight — the rows are already
                    # resident, so no bytes move)
                    self.hydrated_bytes += nbytes
                    self._events.append(
                        (
                            "prefix-hydrate",
                            {
                                "stage": "fetched",
                                "digest": digest_hex[:16],
                                "bytes": nbytes,
                            },
                        )
                    )
                    self.insert_t1(
                        digest_hex, parent_hex, arrays, source="t2"
                    )
            elif kind == "fetch-refused":
                _, digest_hex, error = result
                self._hydrating.pop(digest_hex, None)
                dropped = self._t2_index.pop(digest_hex, None)
                if dropped:
                    self.t2_bytes -= dropped
                    self.evicted_bytes += dropped
                self.fingerprint_refusals += 1
                self.hydrate_failures += 1
                self.evictions += 1
                self._events.append(
                    (
                        "prefix-evict",
                        {
                            "tier": "t2",
                            "digest": digest_hex[:16],
                            "bytes": dropped or 0,
                            "reason": f"fingerprint-refused: {error}"[:160],
                        },
                    )
                )
            elif kind == "fetch-missing":
                _, digest_hex = result
                self._hydrating.pop(digest_hex, None)
                dropped = self._t2_index.pop(digest_hex, None)
                if dropped:
                    self.t2_bytes -= dropped
                    self.evicted_bytes += dropped
                self.hydrate_failures += 1
            elif kind == "scan-done":
                _, keys = result
                self.scans += 1
                for key in keys:
                    if (
                        key not in self._t2_index
                        and key not in self._t2_inflight
                    ):
                        # size unknown until first hydration (0-byte
                        # placeholder keeps the conservation equation
                        # exact: discovered bytes count when learned)
                        self._t2_index[key] = 0
                dead = [
                    k for k, n in self._t2_index.items()
                    if k not in keys and k not in self._hydrating
                ]
                for k in dead:
                    n = self._t2_index.pop(k)
                    if n:
                        self.t2_bytes -= n
                        self.evicted_bytes += n
                        self.evictions += 1

    def _trim_t2(self) -> None:
        """T2 byte-budget decision (wait-free; deletions are hydrator
        jobs). Oldest-first, never an entry being hydrated."""
        if self.spec.t2_bytes is None:
            return
        for digest_hex in list(self._t2_index):
            if self.t2_bytes <= self.spec.t2_bytes:
                break
            if digest_hex in self._hydrating:
                continue
            nbytes = self._t2_index.pop(digest_hex)
            self.t2_bytes -= nbytes
            self.evictions += 1
            self.evicted_bytes += nbytes
            self._jobs.append(("delete", digest_hex))
            self._kick.set()
            self._events.append(
                (
                    "prefix-evict",
                    {
                        "tier": "t2",
                        "digest": digest_hex[:16],
                        "bytes": nbytes,
                        "reason": "t2-budget",
                    },
                )
            )

    def drain_events(self) -> list[tuple[str, dict[str, Any]]]:
        """Pop the pending flight-event feed (loop-side emitter)."""
        out = []
        while self._events:
            out.append(self._events.popleft())
        return out

    def ledger(self) -> dict[str, Any]:
        """The exact byte ledger + conservation terms (wait-free)."""
        return {
            "t1_bytes": self.t1_bytes,
            "in_transit_bytes": self.in_transit_bytes,
            "t2_bytes": self.t2_bytes,
            "t2_blob_bytes": self.t2_blob_bytes,
            "inserted_bytes": self.inserted_bytes,
            "taken_bytes": self.taken_bytes,
            "hydrated_bytes": self.hydrated_bytes,
            "discovered_bytes": self.discovered_bytes,
            "evicted_bytes": self.evicted_bytes,
        }

    def stats(self) -> dict[str, Any]:
        return {
            "t1": {
                "entries": len(self._t1),
                "bytes": self.t1_bytes,
                "budget_bytes": self.spec.t1_bytes,
                "hits": self.t1_hits,
                "misses": self.t1_misses,
            },
            "t2": {
                "enabled": self._storage is not None,
                "entries": len(self._t2_index),
                "bytes": self.t2_bytes,
                "blob_bytes": self.t2_blob_bytes,
                "budget_bytes": self.spec.t2_bytes,
                "hits": self.t2_hits,
                "in_transit_bytes": self.in_transit_bytes,
                "pending_jobs": len(self._jobs),
                "scans": self.scans,
            },
            "demotions_t0_t1": self.demotions_t0_t1,
            "demotions_t1_t2": self.demotions_t1_t2,
            "promotions": self.promotions,
            "hydrations": self.hydrations,
            "hydrating": len(self._hydrating),
            "hydrate_failures": self.hydrate_failures,
            "fingerprint_refusals": self.fingerprint_refusals,
            "evictions": self.evictions,
            "ledger": self.ledger(),
        }

    # -- hydrator thread (T2 I/O — exempt from PFX801 by design) --------

    def _io_loop(self) -> None:
        storage = self._storage
        assert storage is not None
        while True:
            if not self._jobs:
                kicked = self._kick.wait(timeout=self.spec.t2_rescan_s)
                self._kick.clear()
                if not kicked:
                    # periodic rescan: notice blobs OTHER replicas wrote
                    self._io_scan(storage)
                    continue
            try:
                job = self._jobs.popleft()
            except IndexError:
                continue
            kind = job[0]
            if kind == "stop":
                return
            if kind == "sync":
                job[1].set()
            elif kind == "scan":
                self._io_scan(storage)
            elif kind == "put":
                self._io_put(storage, job[1], job[2])
            elif kind == "fetch":
                self._io_fetch(storage, job[1])
            elif kind == "delete":
                try:
                    storage.delete(job[1])
                except Exception as e:
                    # budget trims are best-effort: the ledger already
                    # dropped the entry and counted the bytes
                    log.debug("prefix T2 delete failed: %s", e)

    def _io_scan(self, storage: PrefixStorage) -> None:
        try:
            keys = storage.list_keys()
        except Exception as e:
            log.debug("prefix T2 scan failed: %s", e)
            return
        self._results.append(("scan-done", keys))

    def _io_put(
        self, storage: PrefixStorage, digest_hex: str, entry: dict[str, Any]
    ) -> None:
        header = {
            "kind": BLOB_KIND,
            "fingerprint": self.fingerprint,
            "digest": digest_hex,
            "parent": entry["parent"],
            "rows": self.rows_per_block,
            "payload-bytes": entry["nbytes"],
        }
        try:
            blob = serialize_handoff(header, entry["arrays"])
            storage.put(digest_hex, blob)
        except Exception as e:
            self._results.append(("put-failed", digest_hex, str(e)))
            return
        self._results.append(("put-done", digest_hex, len(blob)))

    def _io_fetch(self, storage: PrefixStorage, digest_hex: str) -> None:
        if self._fault_injector is not None:
            action = self._fault_injector.fire("t2-get")
            if action is not None:
                # hydrator thread: stalls/drops here never touch the
                # engine loop — a drop reports fetch-missing (the blob
                # "vanished"), the timeout machinery does the rest
                self._events.append(
                    ("fault-injected",
                     {"site": "t2-get", "shape": action.shape,
                      "fire": action.seq})
                )
                if action.shape == "delay-ms":
                    time.sleep(action.hang_ms / 1000.0)
                elif action.shape in ("drop", "error", "oom", "hang"):
                    self._results.append(("fetch-missing", digest_hex))
                    return
        try:
            blob = storage.get(digest_hex)
        except Exception:
            blob = None
        if blob is None:
            self._results.append(("fetch-missing", digest_hex))
            return
        try:
            header, arrays = deserialize_handoff(blob)
            if header.get("kind") != BLOB_KIND:
                raise LayoutMismatch(
                    f"not a prefix-block blob (kind={header.get('kind')!r})"
                )
            if header.get("digest") != digest_hex:
                raise LayoutMismatch(
                    f"blob digest {header.get('digest')!r} does not match "
                    f"its key {digest_hex!r}"
                )
            check_fingerprint(self.fingerprint, header.get("fingerprint") or {})
            # contiguous host copies: frombuffer views over the blob
            # would pin the whole payload per array
            arrays = {
                name: np.ascontiguousarray(a) for name, a in arrays.items()
            }
            nbytes = int(sum(a.nbytes for a in arrays.values()))
        except LayoutMismatch as e:
            # refused AND deleted — a mismatched blob must never be
            # half-hydrated, and leaving it would refuse forever
            try:
                storage.delete(digest_hex)
            except Exception as delete_error:
                log.debug(
                    "prefix T2 refused-blob delete failed: %s", delete_error
                )
            self._results.append(("fetch-refused", digest_hex, str(e)))
            return
        except Exception as e:
            self._results.append(("fetch-refused", digest_hex, str(e)))
            return
        self._results.append(
            ("fetch-done", digest_hex, str(header.get("parent") or ""),
             arrays, nbytes)
        )

    # -- lifecycle -------------------------------------------------------

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued hydrator job has been processed
        (tests/bench only — never called on the engine loop). Returns
        False on timeout or when no hydrator runs."""
        if self._thread is None:
            return False
        done = threading.Event()
        self._jobs.append(("sync", done))
        self._kick.set()
        return done.wait(timeout_s)

    def close(self) -> None:
        if self._thread is not None:
            self._jobs.append(("stop",))
            self._kick.set()
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._storage is not None:
            self._storage.close()


# ---------------------------------------------------------------------------
# gateway-side prompt-prefix digest (stamped as a routing header)
# ---------------------------------------------------------------------------

#: record header carrying the chained prompt-prefix digest the gateway
#: stamps; the router pins prefix→replica affinity on it
PREFIX_HEADER = "langstream-prefix-digest"
#: chained-digest chunking over the prompt TEXT (the gateway never
#: tokenizes): two 256-char links ≈ one shared system preamble
PREFIX_STAMP_CHUNK = 256
PREFIX_STAMP_DEPTH = 2


def prefix_digest_for_text(value: Any) -> str | None:
    """Chained blake2b digest of the first ``DEPTH × CHUNK`` characters
    of a prompt value — the same chained construction the T0 cache and
    kvtransfer use over token blocks, applied to text so the gateway
    can stamp it without a tokenizer. Prompts sharing that head (the
    shared-system-prompt shape) stamp the SAME digest; shorter prompts
    stamp nothing (``None``) and route exactly as before."""
    if value is None:
        return None
    text = value if isinstance(value, str) else str(value)
    if len(text) < PREFIX_STAMP_CHUNK * PREFIX_STAMP_DEPTH:
        return None
    prev = b""
    for i in range(PREFIX_STAMP_DEPTH):
        chunk = text[i * PREFIX_STAMP_CHUNK: (i + 1) * PREFIX_STAMP_CHUNK]
        h = hashlib.blake2b(digest_size=16)
        h.update(prev)
        h.update(chunk.encode("utf-8", errors="replace"))
        prev = h.digest()
    return prev.hex()
