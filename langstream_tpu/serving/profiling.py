"""Profiling and tracing hooks for the serving engine.

The TPU-native analogue of the reference's per-agent observability servlet
(``AgentInfoServlet.java`` / ``AgentRunner.java:604-624``): instead of JVM
stats, we capture device truth — ``jax.profiler`` traces (op-level timeline
viewable in TensorBoard/Perfetto) and the compiled HLO of the hot programs.

Activation (all off by default, zero overhead when unset):

- ``LS_TPU_PROFILE_DIR=/path``: the engine captures a trace of the first
  ``LS_TPU_PROFILE_CHUNKS`` (default 4) decode chunks after startup into
  ``/path``. Inspect with TensorBoard's profile plugin or Perfetto.
- ``LS_TPU_HLO_DUMP_DIR=/path``: each jitted serving program (prefill
  buckets, decode chunk variants) writes its optimized HLO text next to its
  first execution — the ground truth for "what did XLA fuse".
- Engine methods :meth:`ProfilerHooks.start_trace` / ``stop_trace`` expose
  the same capture programmatically (the pod's ``/profile`` debug endpoint
  drives these).

Also here: the decode roofline model. Decode is HBM-bandwidth bound: each
step must stream every live weight byte plus the attention-window slice of
the KV cache. ``decode_step_bytes`` computes that floor so benches can
report achieved-vs-roofline utilization instead of a bare tok/s.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Any

log = logging.getLogger(__name__)


class ProfilerHooks:
    """Owns trace capture state for one engine instance.

    Capture state is touched from two threads: the pod's ``/profile``
    debug endpoint drives :meth:`start_trace`/:meth:`stop_trace` from the
    event loop while :meth:`on_decode_chunk` runs on the engine dispatch
    thread — so the start/stop/auto-countdown read-modify-writes sit
    behind a lock (graftcheck RACE801 polices the shape). The lock guards
    only the state transitions: the ``_tracing`` flag is flipped as a
    *reservation* and the filesystem / ``jax.profiler`` calls run outside
    it, so the event-loop thread can never stall on a lock held across
    I/O (the OBS502/OBS503 failure mode). A concurrent start+stop can
    therefore observe the reservation before the profiler actually
    started — the losing call's jax error is caught and logged, never
    raised into serving, which is this class's contract anyway."""

    def __init__(self) -> None:
        self.profile_dir = os.environ.get("LS_TPU_PROFILE_DIR")
        self.auto_chunks = int(os.environ.get("LS_TPU_PROFILE_CHUNKS", "4"))
        self.hlo_dir = os.environ.get("LS_TPU_HLO_DUMP_DIR")
        self._state_lock = threading.Lock()
        self._tracing = False
        self._auto_remaining = self.auto_chunks if self.profile_dir else 0
        self._dumped: set[str] = set()

    # -- trace capture --------------------------------------------------

    def start_trace(self, trace_dir: str | None = None) -> bool:
        """Begin a jax.profiler capture (idempotent). Returns True if a
        capture started. The profiler is process-global while hooks are
        per-engine, so a capture already running elsewhere (another engine)
        is tolerated, never raised into the serving path."""
        target = trace_dir or self.profile_dir
        if not target:
            return False
        with self._state_lock:
            if self._tracing:
                return False
            self._tracing = True  # reserve: concurrent callers back off
        import jax

        try:
            os.makedirs(target, exist_ok=True)
            jax.profiler.start_trace(target)
        except Exception as e:  # profiling must never break serving
            log.warning(
                "profiler trace start failed (already active?): %s", e
            )
            with self._state_lock:
                self._tracing = False
                self._auto_remaining = 0
            return False
        log.info("jax profiler trace started -> %s", target)
        return True

    def stop_trace(self) -> bool:
        with self._state_lock:
            if not self._tracing:
                return False
            self._tracing = False
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as e:
            log.warning("profiler trace stop failed: %s", e)
            return False
        log.info("jax profiler trace stopped")
        return True

    def on_decode_chunk(self) -> None:
        """Called once per dispatched decode chunk: drives the env-var
        auto-capture of the first N chunks."""
        with self._state_lock:
            if self._auto_remaining <= 0:
                return
            need_start = not self._tracing
        if need_start and not self.start_trace():
            return  # start failed/disabled; _auto_remaining already zeroed
        with self._state_lock:
            if self._auto_remaining <= 0:
                return
            self._auto_remaining -= 1
            should_stop = self._auto_remaining == 0
        if should_stop:
            self.stop_trace()

    # -- HLO dumps ------------------------------------------------------

    def dump_hlo(self, name: str, jitted: Any, *args: Any, **kwargs: Any) -> str | None:
        """Write ``jitted``'s HLO for the given example args to
        ``<hlo_dir>/<name>.hlo.txt`` (once per name).

        Default dump is the (cheap) pre-optimization lowering — AOT
        ``compile()`` results don't populate the jit dispatch cache, so
        compiling here would double every program's warm-up. Set
        ``LS_TPU_HLO_OPTIMIZED=1`` to pay one extra compile per program and
        dump the post-fusion optimized HLO instead."""
        if not self.hlo_dir or name in self._dumped:
            return None
        self._dumped.add(name)
        try:
            lowered = jitted.lower(*args, **kwargs)
            if os.environ.get("LS_TPU_HLO_OPTIMIZED") == "1":
                text = lowered.compile().as_text()
            else:
                text = lowered.as_text()
        except Exception as e:  # profiling must never break serving
            log.warning("HLO dump %s failed: %s", name, e)
            return None
        os.makedirs(self.hlo_dir, exist_ok=True)
        path = os.path.join(self.hlo_dir, f"{name}.hlo.txt")
        with open(path, "w") as f:
            f.write(text)
        log.info("HLO dump: %s", path)
        return path


# ---------------------------------------------------------------------------
# roofline model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DecodeRoofline:
    weight_bytes: int          # streamed once per step (all slots share it)
    cache_bytes_per_step: int  # KV window read across all slots
    total_bytes_per_step: int
    hbm_gbps: float            # assumed device bandwidth
    # detected device identity, recorded so a bench JSON says WHICH roof it
    # was measured against instead of implying v5e everywhere
    generation: str | None = None   # "v5e"/"v5p"/"v4"/"v6e"; None off-TPU
    hbm_bytes: int | None = None    # allocator bytes_limit when exposed

    def min_step_ms(self) -> float:
        return self.total_bytes_per_step / (self.hbm_gbps * 1e9) * 1e3

    def utilization(self, achieved_step_ms: float) -> float:
        return self.min_step_ms() / max(achieved_step_ms, 1e-9)


# published HBM bandwidth by TPU generation (GB/s); used for reporting only
_HBM_GBPS = {"v5e": 819.0, "v5p": 2765.0, "v4": 1228.0, "v6e": 1640.0}

# published per-chip HBM capacity by generation — the fallback when the
# platform's allocator hides memory stats (several TPU plugins return None
# from memory_stats(), which is how BENCH_r05 recorded "hbm": null on a
# real chip). Used for reporting and the attribution memory ledger.
_HBM_CAPACITY_BYTES = {
    "v5e": 16 * 2**30,
    "v5p": 95 * 2**30,
    "v4": 32 * 2**30,
    "v6e": 32 * 2**30,
}

# jax device_kind substrings → generation key (plugins spell these several
# ways: "TPU v5 lite", "TPU v5e", "TPU v6 lite", ...). Checked in order so
# the lite variants match before the bare version numbers.
_DEVICE_KIND_GEN = (
    ("v5 lite", "v5e"),
    ("v5lite", "v5e"),
    ("v5e", "v5e"),
    ("v5p", "v5p"),
    ("v6 lite", "v6e"),
    ("v6lite", "v6e"),
    ("v6e", "v6e"),
    ("v4", "v4"),
)


def detect_generation() -> str | None:
    """TPU generation key from ``TPU_ACCELERATOR_TYPE``, falling back to
    the live backend's ``device_kind`` (the env var is unset under some
    plugins — the reason ``device.hbm``/generation used to come out null).
    None on CPU/GPU or when nothing matches."""
    accel = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    for key in _HBM_GBPS:
        if accel.startswith(key):
            return key
    try:
        import jax

        devices = jax.local_devices()
        if not devices or devices[0].platform != "tpu":
            return None
        kind = getattr(devices[0], "device_kind", "").lower()
        for pattern, key in _DEVICE_KIND_GEN:
            if pattern in kind:
                return key
    except Exception:  # backend not initialized / no devices: just unknown
        return None
    return None


def detect_hbm_capacity() -> tuple[int | None, str]:
    """(per-chip HBM bytes, source) — allocator truth when the platform
    exposes memory stats (``source: "memory_stats"``), else the published
    per-generation capacity table (``source: "table:<gen>"`` — the fix
    for BENCH_r05 recording ``"hbm": null`` on a real chip whose plugin
    hides allocator stats), else ``(None, "unknown")`` (CPU/GPU)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"]), "memory_stats"
    except Exception as e:
        log.debug("memory_stats unavailable: %s", e)
    generation = detect_generation()
    if generation in _HBM_CAPACITY_BYTES:
        return _HBM_CAPACITY_BYTES[generation], f"table:{generation}"
    return None, "unknown"


def detect_hbm_bytes() -> int | None:
    """Physical HBM per chip: the allocator's ``bytes_limit`` when
    exposed, falling back to the per-generation capacity table (see
    :func:`detect_hbm_capacity` for the source annotation)."""
    return detect_hbm_capacity()[0]


def detect_hbm_gbps(default: float = 819.0) -> float:
    """Bandwidth of the detected generation; ``default`` (v5e, the fleet
    baseline) only when no generation can be detected at all."""
    generation = detect_generation()
    return _HBM_GBPS.get(generation, default)


def decode_step_bytes(
    model_config: Any,
    slots: int,
    window: int,
    quantize: str | None = None,
    kv_dtype_bytes: int = 2,
    kv_quantize: str | None = None,
) -> DecodeRoofline:
    """Bytes that MUST cross HBM for one decode step of ``slots`` slots with
    an attention window of ``window`` cache rows per slot.

    Weight traffic: every parameter once (int8 → 1 byte + per-channel f32
    scales, negligible). Cache traffic: K and V windows for every slot and
    layer. Activations are negligible at decode batch sizes.
    """
    c = model_config
    from langstream_tpu.models.llama import param_count

    n_params = param_count(c)
    wbytes = n_params * (1 if quantize == "int8" else 2)
    if kv_quantize == "int8":
        # int8 row + one f32 scale per (position, head) row
        row_bytes = c.head_dim + 4
    else:
        row_bytes = c.head_dim * kv_dtype_bytes
    cache = c.layers * slots * window * c.kv_heads * row_bytes * 2
    return DecodeRoofline(
        weight_bytes=wbytes,
        cache_bytes_per_step=cache,
        total_bytes_per_step=wbytes + cache,
        hbm_gbps=detect_hbm_gbps(),
        generation=detect_generation(),
        hbm_bytes=detect_hbm_bytes(),
    )
