"""QoS policy types for the multi-tenant serving scheduler.

The vocabulary the scheduler (``serving/scheduler.py``), the gateway
(admission throttling), and the control plane (config validation, the
``/qos`` status route) all share:

- **Priority classes** — ``interactive`` / ``default`` / ``batch``, each
  with a WDRR weight (its guaranteed dequeue share under contention), a
  bounded engine-side queue (backpressure instead of unbounded growth),
  and a soft deadline that feeds the preemption cost model.
- **Token buckets** — per-tenant ``requests/s`` and ``generated
  tokens/s`` limits. Request admission is pre-debited (one token per
  request); generated tokens are post-debited on completion, so a tenant
  that just burned a large completion budget is throttled until the
  bucket refills — the only honest accounting when the engine cannot
  know a request's true cost up front.
- :class:`QosSpec` — the frozen, hashable config object that rides
  inside :class:`~langstream_tpu.serving.engine.ServingConfig` (engines
  are keyed by their config, so every field bottoms out in tuples) and
  round-trips through the app's ``tpu-serving-configuration`` resource.

Everything here is stdlib-only and never imports jax — the control plane
and gateway import it without touching a device. Clocks are
``time.monotonic()`` (graftcheck OBS501: these durations feed throttle
decisions and retry-after arithmetic).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

#: priority classes, highest first — the WDRR visit order and the rank
#: order the preemption policy compares (lower index = more urgent)
PRIORITY_CLASSES = ("interactive", "default", "batch")

_RANK = {name: i for i, name in enumerate(PRIORITY_CLASSES)}

#: per-class defaults: (weight, queue_limit, deadline_s). Weights are the
#: guaranteed WDRR shares (8:4:1 → batch keeps ~8% of admissions under
#: full contention but can never push interactive out); deadlines feed
#: the preemption cost model, not a hard timeout.
_CLASS_DEFAULTS = {
    "interactive": (8, 256, 2.0),
    "default": (4, 256, 10.0),
    "batch": (1, 1024, 120.0),
}

#: the catch-all tenant policy name
DEFAULT_TENANT = "*"


def normalize_priority(value: Any) -> str:
    """Clamp an arbitrary client-supplied priority to a known class —
    unknown names degrade to ``default``, never to an error (a malformed
    header must not fail the request, only its special treatment)."""
    name = str(value or "").strip().lower()
    return name if name in _RANK else "default"


def priority_rank(name: str) -> int:
    """Lower rank = more urgent; unknown names rank as ``default``."""
    return _RANK.get(name, _RANK["default"])


class RateLimited(Exception):
    """Admission refused by QoS policy. ``reason`` is ``throttled`` (a
    tenant bucket is empty) or ``queue-full`` (the class queue hit its
    bound — load shedding); ``retry_after`` is the seconds until the
    refusal is expected to clear (the gateway's ``Retry-After``)."""

    def __init__(self, reason: str, retry_after: float, detail: str = ""):
        self.reason = reason
        self.retry_after = max(0.0, round(retry_after, 3))
        super().__init__(
            detail or f"{reason} (retry after {self.retry_after:.3f}s)"
        )


class TokenBucket:
    """Classic token bucket on the monotonic clock.

    ``debit`` may drive the level negative (post-debited generated
    tokens); ``available`` refills lazily at ``rate``/s up to ``burst``.
    A deterministic ``clock`` injects in tests.
    """

    def __init__(
        self,
        rate: float,
        burst: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._level = self.burst
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._level = min(
            self.burst, self._level + (now - self._last) * self.rate
        )
        self._last = now

    def available(self) -> float:
        self._refill()
        return self._level

    def try_acquire(self, n: float = 1.0) -> bool:
        self._refill()
        if self._level >= n:
            self._level -= n
            return True
        return False

    def debit(self, n: float) -> None:
        """Unconditional withdrawal (may go negative): the post-debit for
        costs only known after the fact (generated tokens)."""
        self._refill()
        self._level -= n

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available (0 when they
        already are; infinity-free: a zero rate reports one burst
        period's worth of seconds as a bounded backoff hint)."""
        self._refill()
        deficit = n - self._level
        if deficit <= 0:
            return 0.0
        if self.rate <= 0:
            return 60.0
        return deficit / self.rate


@dataclasses.dataclass(frozen=True)
class ClassPolicy:
    name: str
    weight: int
    queue_limit: int
    deadline_s: float
    # streaming time-between-tokens target (docs/OBSERVABILITY.md
    # Streaming & TBT): the p99 inter-chunk interval this class
    # promises. Opt-in like deadline_headers — None (the default) means
    # no TBT SLO for the class: no per-class burn tracker, the engine's
    # stream-stall-s default draws the stall line instead. A
    # streaming-configured engine builds one "tbt" burn-rate tracker
    # per declaring class and health() degrades on a fast burn
    # (tbt_burn).
    tbt_p99_s: float | None = None

    def to_dict(self) -> dict[str, Any]:
        out = {
            "weight": self.weight,
            "queue-limit": self.queue_limit,
            "deadline-s": self.deadline_s,
        }
        if self.tbt_p99_s is not None:
            out["tbt-p99-s"] = self.tbt_p99_s
        return out


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """Rate limits for one tenant (or the ``*`` catch-all). ``None``
    means unlimited on that axis. ``adapter`` names the LoRA adapter
    this tenant's traffic decodes with (``serving/adapters.py``): the
    gateway stamps it as the ``langstream-adapter`` record header and
    the AI agents forward it into engine options — empty means base
    weights, byte-identical to a pre-adapter deploy."""

    name: str
    requests_per_s: float | None = None
    request_burst: float | None = None
    tokens_per_s: float | None = None
    token_burst: float | None = None
    adapter: str = ""

    def to_dict(self) -> dict[str, Any]:
        out = {
            "requests-per-s": self.requests_per_s,
            "request-burst": self.request_burst,
            "tokens-per-s": self.tokens_per_s,
            "token-burst": self.token_burst,
        }
        if self.adapter:
            out["adapter"] = self.adapter
        return out


@dataclasses.dataclass(frozen=True)
class QosSpec:
    """The engine/gateway QoS policy. Frozen and tuple-valued so a
    :class:`ServingConfig` carrying it stays hashable (engines are
    singleton-cached by config)."""

    enabled: bool = True
    classes: tuple[ClassPolicy, ...] = ()
    tenants: tuple[TenantPolicy, ...] = ()
    preempt: bool = True
    max_preemptions: int = 2
    # end-to-end deadline stamping (serving/handoff.py, docs/
    # RESILIENCE.md): when True the gateway stamps langstream-deadline
    # = now + the class's deadline-s on every produced record that did
    # not bring its own, and the engine's admission gate enforces it
    # 504-shaped. Opt-in: existing QoS deployments treat deadline-s as
    # the preemption cost model only, bit for bit.
    deadline_headers: bool = False

    def class_policy(self, name: str) -> ClassPolicy:
        for policy in self.classes:
            if policy.name == name:
                return policy
        w, q, d = _CLASS_DEFAULTS[normalize_priority(name)]
        return ClassPolicy(normalize_priority(name), w, q, d)

    def tenant_policy(self, tenant: str) -> TenantPolicy | None:
        fallback = None
        for policy in self.tenants:
            if policy.name == tenant:
                return policy
            if policy.name == DEFAULT_TENANT:
                fallback = policy
        return fallback

    def to_dict(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "classes": {p.name: p.to_dict() for p in self.classes},
            "tenants": {p.name: p.to_dict() for p in self.tenants},
            "preempt": self.preempt,
            "max-preemptions": self.max_preemptions,
            "deadline-headers": self.deadline_headers,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any] | None) -> "QosSpec | None":
        """Parse (and validate) the ``qos:`` section of a
        ``tpu-serving-configuration`` resource. ``None``/missing → no QoS
        (the engine keeps its FIFO scheduler). Raises :class:`ValueError`
        on malformed config — the control plane calls this at deploy
        validation so a bad policy fails the deploy, not the first
        request."""
        if d is None:
            return None
        if isinstance(d, QosSpec):
            return d
        if not isinstance(d, dict):
            raise ValueError(f"qos section must be a mapping, got {type(d).__name__}")
        enabled = _parse_bool(d.get("enabled", True))
        classes: list[ClassPolicy] = []
        raw_classes = d.get("classes") or {}
        if not isinstance(raw_classes, dict):
            raise ValueError("qos.classes must be a mapping of class name → policy")
        for name in raw_classes:
            if name not in _RANK:
                raise ValueError(
                    f"qos.classes: unknown priority class {name!r}; "
                    f"known: {list(PRIORITY_CLASSES)}"
                )
        for name in PRIORITY_CLASSES:
            w_def, q_def, d_def = _CLASS_DEFAULTS[name]
            raw = raw_classes.get(name) or {}
            if not isinstance(raw, dict):
                raise ValueError(f"qos.classes.{name} must be a mapping")
            weight = int(raw.get("weight", w_def))
            queue_limit = int(raw.get("queue-limit", raw.get("queue_limit", q_def)))
            deadline = float(raw.get("deadline-s", raw.get("deadline_s", d_def)))
            tbt = _opt_float(raw, "tbt-p99-s", "tbt_p99_s")
            if weight < 1:
                raise ValueError(
                    f"qos.classes.{name}.weight must be >= 1 (a zero weight "
                    f"starves the class — drop its traffic at the gateway "
                    f"instead)"
                )
            if queue_limit < 1:
                raise ValueError(f"qos.classes.{name}.queue-limit must be >= 1")
            if deadline <= 0:
                raise ValueError(f"qos.classes.{name}.deadline-s must be > 0")
            if tbt is not None and tbt <= 0:
                raise ValueError(
                    f"qos.classes.{name}.tbt-p99-s must be > 0 (omit it "
                    f"for no streaming TBT target)"
                )
            classes.append(
                ClassPolicy(name, weight, queue_limit, deadline, tbt)
            )
        tenants: list[TenantPolicy] = []
        raw_tenants = d.get("tenants") or {}
        if not isinstance(raw_tenants, dict):
            raise ValueError("qos.tenants must be a mapping of tenant → limits")
        for tenant in sorted(raw_tenants):
            raw = raw_tenants[tenant] or {}
            if not isinstance(raw, dict):
                raise ValueError(f"qos.tenants.{tenant} must be a mapping")
            rps = _opt_float(raw, "requests-per-s", "requests_per_s")
            tps = _opt_float(raw, "tokens-per-s", "tokens_per_s")
            rburst = _opt_float(raw, "request-burst", "request_burst", "burst")
            tburst = _opt_float(raw, "token-burst", "token_burst")
            for label, value in (("requests-per-s", rps), ("tokens-per-s", tps)):
                if value is not None and value <= 0:
                    raise ValueError(
                        f"qos.tenants.{tenant}.{label} must be > 0 (omit it "
                        f"for unlimited)"
                    )
            adapter = str(raw.get("adapter") or "")
            if adapter:
                # mirror of serving/adapters.py check_adapter_name, kept
                # inline so this module stays stdlib-only (no jax in the
                # gateway/control-plane import graph via this path)
                if len(adapter) > 120 or not set(adapter) <= _ADAPTER_NAME_OK:
                    raise ValueError(
                        f"qos.tenants.{tenant}.adapter {adapter!r} may only "
                        f"contain [A-Za-z0-9_-] (max 120 chars)"
                    )
            tenants.append(
                TenantPolicy(
                    name=str(tenant),
                    requests_per_s=rps,
                    request_burst=rburst,
                    tokens_per_s=tps,
                    token_burst=tburst,
                    adapter=adapter,
                )
            )
        max_preemptions = int(d.get("max-preemptions", d.get("max_preemptions", 2)))
        if max_preemptions < 0:
            raise ValueError("qos.max-preemptions must be >= 0")
        return cls(
            enabled=enabled,
            classes=tuple(classes),
            tenants=tuple(tenants),
            preempt=_parse_bool(d.get("preempt", True)),
            max_preemptions=max_preemptions,
            deadline_headers=_parse_bool(
                d.get("deadline-headers", d.get("deadline_headers", False))
            ),
        )


#: legal characters in a tenant's adapter name (serving/adapters.py
#: check_adapter_name — adapter names are storage keys + metric labels)
_ADAPTER_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-"
)


def _parse_bool(v: Any) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def _opt_float(raw: dict, *keys: str) -> float | None:
    for key in keys:
        if raw.get(key) is not None:
            return float(raw[key])
    return None


class TenantLimiter:
    """Per-tenant token buckets built from a :class:`QosSpec`, shared by
    the gateway (pre-admission 429s) and the engine scheduler (the same
    policy enforced where the tokens are actually generated).

    Request admission pre-debits one request token and requires the
    tenant's *token* bucket to be non-negative (generated tokens are
    post-debited by :meth:`debit_tokens`, so a tenant that overdrew is
    refused until the refill catches up).

    Tenant names can be client-influenced on unauthenticated gateways
    (``param:tenant``), so every per-tenant map here is LRU-bounded: a
    client rotating random names cannot grow memory without bound. An
    evicted ``'*'``-fallback bucket resets that name's budget — the
    limit a hostile client dodges by rotating identities anyway; real
    per-tenant enforcement needs authenticated subjects (see
    ``docs/SCHEDULING.md``).
    """

    #: max distinct tenants tracked (buckets + counters) before LRU
    #: eviction — bounds client-chosen-identity cardinality
    MAX_TENANTS = 1024

    def __init__(
        self,
        spec: QosSpec,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.spec = spec
        self._clock = clock
        from collections import OrderedDict

        self._requests: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._tokens: "OrderedDict[str, TokenBucket]" = OrderedDict()
        # counters for /qos + engine_top: tenant → {submitted, throttled,
        # tokens-debited}
        self.counters: "OrderedDict[str, dict[str, int]]" = OrderedDict()

    @staticmethod
    def _touch(lru, key, factory):
        value = lru.get(key)
        if value is None:
            value = lru[key] = factory()
        lru.move_to_end(key)
        while len(lru) > TenantLimiter.MAX_TENANTS:
            lru.popitem(last=False)
        return value

    def _counter(self, tenant: str) -> dict[str, int]:
        return self._touch(
            self.counters, tenant,
            lambda: {"submitted": 0, "throttled": 0, "tokens_debited": 0},
        )

    def _buckets(
        self, tenant: str
    ) -> tuple[TokenBucket | None, TokenBucket | None]:
        policy = self.spec.tenant_policy(tenant)
        if policy is None:
            return None, None
        req = tok = None
        if policy.requests_per_s is not None:
            req = self._touch(
                self._requests, tenant,
                lambda: TokenBucket(
                    policy.requests_per_s,
                    policy.request_burst or max(1.0, policy.requests_per_s),
                    clock=self._clock,
                ),
            )
        if policy.tokens_per_s is not None:
            tok = self._touch(
                self._tokens, tenant,
                lambda: TokenBucket(
                    policy.tokens_per_s,
                    policy.token_burst or policy.tokens_per_s,
                    clock=self._clock,
                ),
            )
        return req, tok

    def retry_after(self, tenant: str) -> float | None:
        """Seconds until ``tenant`` could admit a request, or ``None``
        when it can right now. Read-only — debits nothing (the gateway's
        WS-upgrade gate peeks without consuming)."""
        req, tok = self._buckets(tenant)
        waits = []
        if req is not None and req.available() < 1.0:
            waits.append(req.retry_after(1.0))
        if tok is not None and tok.available() < 0.0:
            waits.append(tok.retry_after(0.0))
        return max(waits) if waits else None

    def admit_request(self, tenant: str) -> float | None:
        """Debit one request from ``tenant``'s bucket. ``None`` =
        admitted; a float = refused, retry after that many seconds."""
        self._counter(tenant)["submitted"] += 1
        req, tok = self._buckets(tenant)
        if tok is not None and tok.available() < 0.0:
            self._counter(tenant)["throttled"] += 1
            return tok.retry_after(0.0)
        if req is not None and not req.try_acquire(1.0):
            self._counter(tenant)["throttled"] += 1
            return req.retry_after(1.0)
        return None

    def debit_tokens(self, tenant: str, n: int) -> None:
        """Post-debit ``n`` generated tokens against the tenant's
        tokens/s bucket (no-op for unlimited tenants)."""
        if n <= 0:
            return
        _req, tok = self._buckets(tenant)
        if tok is not None:
            tok.debit(float(n))
            self._counter(tenant)["tokens_debited"] += n

    def stats(self) -> dict[str, dict[str, int]]:
        return {t: dict(c) for t, c in self.counters.items()}


def validate_application_qos(application) -> None:
    """Deploy-time validation: parse every ``tpu-serving-configuration``
    resource's ``qos`` section so a malformed policy fails the deploy
    (HTTP 400) instead of the first request. Duck-typed on the parsed
    :class:`~langstream_tpu.api.application.Application`."""
    for name, res in (getattr(application, "resources", None) or {}).items():
        if getattr(res, "type", None) != "tpu-serving-configuration":
            continue
        try:
            QosSpec.from_dict((res.configuration or {}).get("qos"))
        except ValueError as e:
            raise ValueError(f"resource {name!r}: invalid qos section: {e}") from e
