"""In-jit token sampling.

Sampling runs inside the same jit as the decode step so only the sampled
token ids (B int32) and their logprobs cross the host boundary per step —
never the (B, vocab) logits (HBM→host bandwidth is the TTFT killer at high
slot counts).

Supports greedy (temperature 0), temperature, and top-k. The expensive
machinery is compiled in only when a request in the batch actually asks
for it (static flags the engine derives per decode burst):

- ``use_top_p``: the sorted-cumulative-mass pass costs a vocab sort/step;
- ``use_top_k``: ``lax.top_k`` over the vocab is a k-deep selection sweep
  per step — pure waste for the (common) greedy/temperature-only batch;
- ``all_greedy``: temperature 0 everywhere → only the argmax and the
  sampled token's logprob are computed; no categorical draw at all.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(
    logits: jax.Array,        # (B, V) float32
    key: jax.Array,
    temperatures: jax.Array,  # (B,) 0 = greedy
    top_ks: jax.Array,        # (B,) 0 = off
    use_top_p: bool = False,
    top_ps: jax.Array | None = None,  # (B,) 1.0 = off
    use_top_k: bool = True,
    all_greedy: bool = False,
    use_penalties: bool = False,
    presences: jax.Array | None = None,     # (B,) presence penalty
    frequencies: jax.Array | None = None,   # (B,) frequency penalty
    counts: jax.Array | None = None,        # (B, V) output-token counts
) -> tuple[jax.Array, jax.Array]:
    """→ (tokens (B,) int32, logprobs (B,) float32 of the sampled token)."""
    B, V = logits.shape
    if use_penalties:
        # OpenAI-style presence/frequency penalties over OUTPUT tokens
        # (reference: ChatCompletionsConfig presence-penalty /
        # frequency-penalty, forwarded to the provider — here the engine IS
        # the provider). Applied before everything: greedy argmax and
        # logprobs see the penalised distribution.
        cf = counts.astype(logits.dtype)
        logits = logits - (
            presences[:, None] * (cf > 0).astype(logits.dtype)
            + frequencies[:, None] * cf
        )
    greedy_tokens = jnp.argmax(logits, axis=-1)

    def token_logprob(tokens: jax.Array) -> jax.Array:
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(logprobs, tokens[:, None], axis=1).squeeze(1)

    if all_greedy:
        return greedy_tokens.astype(jnp.int32), token_logprob(greedy_tokens)

    scaled = filtered_logits(
        logits, temperatures, top_ks,
        use_top_p=use_top_p, top_ps=top_ps, use_top_k=use_top_k,
    )
    sampled = jax.random.categorical(key, scaled, axis=-1)
    tokens = jnp.where(temperatures <= 0, greedy_tokens, sampled)
    return tokens.astype(jnp.int32), token_logprob(tokens)


def filtered_logits(
    logits: jax.Array,        # (B, V) float32
    temperatures: jax.Array,  # (B,)
    top_ks: jax.Array,        # (B,) 0 = off
    use_top_p: bool = False,
    top_ps: jax.Array | None = None,
    use_top_k: bool = True,
) -> jax.Array:
    """Temperature-scaled, top-k/top-p-masked logits — the exact
    categorical distribution :func:`sample_tokens` draws from. Shared by
    the decode sampler and the speculative verify's rejection sampler so
    acceptance probabilities match what plain decode would sample."""
    B, V = logits.shape
    temps = jnp.maximum(temperatures, 1e-6)[:, None]
    scaled = logits / temps
    neg = jnp.finfo(scaled.dtype).min

    if use_top_k:
        # top-k: mask everything below the k-th largest (k dynamic per row
        # via a fixed K_MAX window — vocab-sized sort avoided)
        K_MAX = 64
        top_vals, _ = jax.lax.top_k(scaled, K_MAX)  # (B, K_MAX) descending
        k_idx = jnp.clip(top_ks - 1, 0, K_MAX - 1)
        kth_val = jnp.take_along_axis(top_vals, k_idx[:, None], axis=1)
        apply_topk = (top_ks > 0)[:, None]
        scaled = jnp.where(apply_topk & (scaled < kth_val), neg, scaled)

    if use_top_p:
        assert top_ps is not None
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_ps[:, None]  # always keep the top one
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(B)[:, None], sort_idx
        ].set(keep_sorted)
        scaled = jnp.where(keep, scaled, neg)

    return scaled


def speculative_accept(
    logits: jax.Array,        # (B, D1, V) float32 — verify forward outputs
    drafts: jax.Array,        # (B, D1-1) int32 — deterministic draft tokens
    key: jax.Array,
    temperatures: jax.Array,  # (B,)
    top_ks: jax.Array,        # (B,)
    top_ps: jax.Array,        # (B,)
    use_top_p: bool = False,
    use_top_k: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Rejection sampling for a DETERMINISTIC drafter (prompt-lookup).

    The draft distribution is a point mass at the drafted token, so the
    standard speculative-sampling rule reduces to: accept draft ``d_j``
    with probability ``p_j(d_j)`` (the target's filtered probability); on
    the first rejection emit a sample from the residual ``p_j`` with
    ``d_j`` removed; if all drafts survive, emit a bonus sample from the
    last position. The emitted stream is distributed EXACTLY as plain
    autoregressive sampling from ``filtered_logits`` — speculation changes
    latency, never the distribution.

    Greedy rows (temperature <= 0) degenerate cleanly: the target becomes
    a point mass at the argmax, so acceptance is ``draft == argmax`` and
    every fallback is the argmax — identical to the pure-greedy verify.

    Returns ``(accepted (B,) int32 — count of accepted drafts,
    fallback (B, D1) int32 — the token to emit at each position if the
    burst stops there: residual samples for draft positions, the bonus
    sample at the last)``.
    """
    B, D1, V = logits.shape
    flat = logits.reshape(B * D1, V)
    rep = lambda a: jnp.repeat(a, D1, axis=0)
    scaled = filtered_logits(
        flat, rep(temperatures), rep(top_ks),
        use_top_p=use_top_p, top_ps=rep(top_ps), use_top_k=use_top_k,
    )
    p = jax.nn.softmax(scaled, axis=-1)
    # greedy rows: point mass at the (unfiltered) argmax
    greedy_mask = (rep(temperatures) <= 0)[:, None]
    onehot = jax.nn.one_hot(jnp.argmax(flat, axis=-1), V, dtype=p.dtype)
    p = jnp.where(greedy_mask, onehot, p).reshape(B, D1, V)

    key_u, key_fb = jax.random.split(key)
    p_draft = jnp.take_along_axis(
        p[:, :-1], drafts[..., None], axis=-1
    ).squeeze(-1)                                            # (B, D1-1)
    u = jax.random.uniform(key_u, (B, D1 - 1))
    accept = u < p_draft
    accepted = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)

    # fallback per position: categorical over log p with the draft masked
    # out (residual); the last position keeps full p (bonus sample). A
    # masked position is only ever used at the first rejection, where
    # p(draft) < 1 guarantees the residual has mass.
    fb_logits = jnp.log(p + 1e-30)
    neg = jnp.finfo(fb_logits.dtype).min
    draft_hot = jax.nn.one_hot(drafts, V, dtype=bool)        # (B, D1-1, V)
    fb_logits = fb_logits.at[:, :-1].set(
        jnp.where(draft_hot, neg, fb_logits[:, :-1])
    )
    fallback = jax.random.categorical(key_fb, fb_logits, axis=-1)
    return accepted.astype(jnp.int32), fallback.astype(jnp.int32)
