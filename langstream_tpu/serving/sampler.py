"""In-jit token sampling.

Sampling runs inside the same jit as the decode step so only the sampled
token ids (B int32) and their logprobs cross the host boundary per step —
never the (B, vocab) logits (HBM→host bandwidth is the TTFT killer at high
slot counts).

Supports greedy (temperature 0), temperature, and top-k. The expensive
machinery is compiled in only when a request in the batch actually asks
for it (static flags the engine derives per decode burst):

- ``use_top_p``: the sorted-cumulative-mass pass costs a vocab sort/step;
- ``use_top_k``: ``lax.top_k`` over the vocab is a k-deep selection sweep
  per step — pure waste for the (common) greedy/temperature-only batch;
- ``all_greedy``: temperature 0 everywhere → only the argmax and the
  sampled token's logprob are computed; no categorical draw at all.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(
    logits: jax.Array,        # (B, V) float32
    key: jax.Array,
    temperatures: jax.Array,  # (B,) 0 = greedy
    top_ks: jax.Array,        # (B,) 0 = off
    use_top_p: bool = False,
    top_ps: jax.Array | None = None,  # (B,) 1.0 = off
    use_top_k: bool = True,
    all_greedy: bool = False,
    use_penalties: bool = False,
    presences: jax.Array | None = None,     # (B,) presence penalty
    frequencies: jax.Array | None = None,   # (B,) frequency penalty
    counts: jax.Array | None = None,        # (B, V) output-token counts
) -> tuple[jax.Array, jax.Array]:
    """→ (tokens (B,) int32, logprobs (B,) float32 of the sampled token)."""
    B, V = logits.shape
    if use_penalties:
        # OpenAI-style presence/frequency penalties over OUTPUT tokens
        # (reference: ChatCompletionsConfig presence-penalty /
        # frequency-penalty, forwarded to the provider — here the engine IS
        # the provider). Applied before everything: greedy argmax and
        # logprobs see the penalised distribution.
        cf = counts.astype(logits.dtype)
        logits = logits - (
            presences[:, None] * (cf > 0).astype(logits.dtype)
            + frequencies[:, None] * cf
        )
    greedy_tokens = jnp.argmax(logits, axis=-1)

    def token_logprob(tokens: jax.Array) -> jax.Array:
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(logprobs, tokens[:, None], axis=1).squeeze(1)

    if all_greedy:
        return greedy_tokens.astype(jnp.int32), token_logprob(greedy_tokens)

    temps = jnp.maximum(temperatures, 1e-6)[:, None]
    scaled = logits / temps
    neg = jnp.finfo(scaled.dtype).min

    if use_top_k:
        # top-k: mask everything below the k-th largest (k dynamic per row
        # via a fixed K_MAX window — vocab-sized sort avoided)
        K_MAX = 64
        top_vals, _ = jax.lax.top_k(scaled, K_MAX)  # (B, K_MAX) descending
        k_idx = jnp.clip(top_ks - 1, 0, K_MAX - 1)
        kth_val = jnp.take_along_axis(top_vals, k_idx[:, None], axis=1)
        apply_topk = (top_ks > 0)[:, None]
        scaled = jnp.where(apply_topk & (scaled < kth_val), neg, scaled)

    if use_top_p:
        assert top_ps is not None
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_ps[:, None]  # always keep the top one
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(B)[:, None], sort_idx
        ].set(keep_sorted)
        scaled = jnp.where(keep, scaled, neg)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    tokens = jnp.where(temperatures <= 0, greedy_tokens, sampled)
    return tokens.astype(jnp.int32), token_logprob(tokens)
