"""Pluggable admission scheduling for the serving engine.

The engine used to admit strictly FIFO from one unbounded
``asyncio.Queue`` inlined in its loop — one tenant's batch job could
starve every interactive client, with no rate limiting and no bounded-
queue backpressure anywhere between gateway and engine. This module
factors that queue behind a :class:`Scheduler` interface:

- :class:`FifoScheduler` — the default. Bit-for-bit the old behavior
  (one unbounded FIFO, head-of-line admission), so existing deployments,
  tests, and bench numbers are untouched when QoS is off.
- :class:`QosScheduler` — priority classes with **weighted deficit
  round-robin** dequeue (each class's weight is its guaranteed share of
  admissions under contention; batch can never starve interactive, and
  interactive can never starve batch below its share), **bounded
  per-class queues** (a full queue sheds load with a retry hint instead
  of growing without bound — graftcheck QOS601 polices the unbounded
  spelling), **per-tenant token buckets** (requests/s pre-debited,
  generated tokens/s post-debited), and the **preemption policy**: when
  admission stalls on KV pressure, pick the running victim whose class
  ranks strictly below the stalled head's and whose deadline has the
  most slack (cheapest progress to redo breaks ties).

The engine owns the *mechanics* (slot/block bookkeeping, resume via
context re-prefill — see ``engine.py``); the scheduler owns the
*policy* (who waits, who sheds, who gets preempted). Everything here
runs on the engine's event-loop thread — plain deques, no locks, no I/O
(OBS503 discipline) — and never imports jax.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Iterable

from langstream_tpu.serving.qos import (
    PRIORITY_CLASSES,
    QosSpec,
    RateLimited,
    TenantLimiter,
    normalize_priority,
    priority_rank,
)


def _pct(sorted_values: list, q: float):
    if not sorted_values:
        return None
    return sorted_values[min(len(sorted_values) - 1, int(q * len(sorted_values)))]


class Scheduler:
    """Admission-queue policy the engine loop drives.

    The contract mirrors how the engine consumed its old queue: ``peek``
    returns the next admission candidate without removing it (admission
    checks KV headroom against the head before committing), ``pop``
    removes exactly the peeked request, ``requeue_front`` re-enqueues a
    preempted request ahead of its class so resume latency is bounded.
    All methods run on the engine's event-loop thread.
    """

    def submit(self, request) -> None:
        """Enqueue a new request. Raises
        :class:`~langstream_tpu.serving.qos.RateLimited` when policy
        refuses it (tenant bucket empty / class queue full)."""
        raise NotImplementedError

    def peek(self):
        raise NotImplementedError

    def pop(self):
        raise NotImplementedError

    def requeue_front(self, request) -> None:
        raise NotImplementedError

    def drain(self) -> list:
        """Remove and return everything queued (engine failure path)."""
        raise NotImplementedError

    def empty(self) -> bool:
        return self.qsize() == 0

    def qsize(self) -> int:
        raise NotImplementedError

    def depths(self) -> dict[str, int] | None:
        """Per-class queue depths (None for policies without classes —
        keeps the flight-sample schema unchanged for FIFO engines)."""
        return None

    def on_finished(self, request) -> None:
        """A request completed: account its generated tokens."""

    def preempt_candidate(self, head, running: Iterable[tuple[int, Any]]):
        """Given the stalled head-of-queue request and ``(slot_id,
        request)`` pairs currently decoding, return the slot to preempt,
        or None. FIFO never preempts."""
        return None

    def note_preempted(self, request) -> None:
        """Bookkeeping hook when the engine actually preempted."""

    def stats(self) -> dict[str, Any]:
        raise NotImplementedError


class FifoScheduler(Scheduler):
    """The pre-QoS default: one unbounded FIFO, head-of-line admission."""

    def __init__(self) -> None:
        self._queue: deque = deque()
        self.admitted = 0

    def submit(self, request) -> None:
        self._queue.append(request)

    def peek(self):
        return self._queue[0] if self._queue else None

    def pop(self):
        request = self._queue.popleft()
        self.admitted += 1
        return request

    def requeue_front(self, request) -> None:
        self._queue.appendleft(request)

    def drain(self) -> list:
        out = list(self._queue)
        self._queue.clear()
        return out

    def qsize(self) -> int:
        return len(self._queue)

    def stats(self) -> dict[str, Any]:
        return {
            "policy": "fifo",
            "queued": len(self._queue),
            "admitted": self.admitted,
        }


class QosScheduler(Scheduler):
    """Priority classes + WDRR dequeue + tenant buckets + preemption
    policy (see the module docstring for the policy model; the full
    write-up lives in ``docs/SCHEDULING.md``)."""

    def __init__(self, spec: QosSpec, clock=time.monotonic):
        self.spec = spec
        self._clock = clock
        self.limiter = TenantLimiter(spec, clock=clock)
        self._order = PRIORITY_CLASSES
        self._queues: dict[str, deque] = {c: deque() for c in self._order}
        self._policies = {c: spec.class_policy(c) for c in self._order}
        # WDRR state: a class with deficit >= 1 owns the next dequeue;
        # each visit of the round-robin pointer grants one quantum
        # (= the class weight), so shares converge to the weight ratio
        self._deficit: dict[str, float] = {c: 0.0 for c in self._order}
        self._ptr = 0
        self._selected: str | None = None
        # per-class counters + bounded queue-wait windows (seconds): the
        # deterministic saturation acceptance asserts on these, and the
        # /qos route serves them
        self.counters: dict[str, dict[str, int]] = {
            c: {"queued": 0, "admitted": 0, "shed": 0, "preempted": 0,
                "resumed": 0}
            for c in self._order
        }
        self._waits: dict[str, deque] = {
            c: deque(maxlen=512) for c in self._order
        }

    # -- enqueue ---------------------------------------------------------

    def submit(self, request) -> None:
        cls = normalize_priority(getattr(request, "priority", "default"))
        request.priority = cls
        queue = self._queues[cls]
        # engine-internal warmup probes bypass policy entirely: a '*'
        # catch-all tenant policy must not fail warmup (losing the
        # pre-compiles) or pre-drain the anonymous tenant's budget
        if getattr(request, "warmup", False):
            queue.append(request)
            self.counters[cls]["queued"] += 1
            return
        # queue bound BEFORE the bucket debit: a shed request must not
        # also burn rate budget (the client's retry would then be
        # throttled for work the engine never accepted)
        if len(queue) >= self._policies[cls].queue_limit:
            self.counters[cls]["shed"] += 1
            # the honest hint is one service interval: the queue drains at
            # an unknowable rate, so report the class deadline as backoff
            raise RateLimited(
                "queue-full", self._policies[cls].deadline_s,
                f"class {cls!r} queue is full "
                f"({self._policies[cls].queue_limit}); shedding",
            )
        tenant = getattr(request, "tenant", "") or ""
        retry = self.limiter.admit_request(tenant)
        if retry is not None:
            raise RateLimited(
                "throttled", retry,
                f"tenant {tenant or '<anonymous>'!r} over its rate limit; "
                f"retry after {retry:.3f}s",
            )
        queue.append(request)
        self.counters[cls]["queued"] += 1

    def requeue_front(self, request) -> None:
        # a preempted request re-enters ahead of its class (its wait was
        # already served once) and is exempt from the queue bound — shed
        # policy applies to NEW work, never to work already admitted
        cls = normalize_priority(getattr(request, "priority", "default"))
        self._queues[cls].appendleft(request)

    # -- WDRR dequeue ----------------------------------------------------

    def _select(self) -> str | None:
        if self._selected and self._queues[self._selected]:
            if self._deficit[self._selected] >= 1.0:
                return self._selected
        self._selected = None
        if not any(self._queues[c] for c in self._order):
            return None
        for _ in range(len(self._order) + 1):
            cls = self._order[self._ptr % len(self._order)]
            if self._queues[cls]:
                if self._deficit[cls] < 1.0:
                    # one quantum per visit; integer weights >= 1 mean one
                    # grant always reaches serving credit
                    self._deficit[cls] += self._policies[cls].weight
                self._selected = cls
                return cls
            self._deficit[cls] = 0.0
            self._ptr += 1
        return None

    def peek(self):
        cls = self._select()
        return self._queues[cls][0] if cls else None

    def pop(self):
        cls = self._select()
        if cls is None:
            raise IndexError("pop from empty scheduler")
        request = self._queues[cls].popleft()
        self._deficit[cls] -= 1.0
        if not self._queues[cls]:
            self._deficit[cls] = 0.0
        if self._deficit[cls] < 1.0:
            self._ptr += 1
            self._selected = None
        self.counters[cls]["admitted"] += 1
        if getattr(request, "preemptions", 0):
            self.counters[cls]["resumed"] += 1
        else:
            enqueued = getattr(request, "enqueue_time", None)
            if enqueued is not None:
                self._waits[cls].append(self._clock() - enqueued)
        return request

    def drain(self) -> list:
        out: list = []
        for cls in self._order:
            out.extend(self._queues[cls])
            self._queues[cls].clear()
            self._deficit[cls] = 0.0
        self._selected = None
        return out

    def qsize(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depths(self) -> dict[str, int]:
        return {c: len(self._queues[c]) for c in self._order}

    # -- completion + preemption policy ----------------------------------

    def on_finished(self, request) -> None:
        if getattr(request, "warmup", False):
            return  # warmup tokens are engine-internal, not tenant spend
        self.limiter.debit_tokens(
            getattr(request, "tenant", "") or "",
            len(getattr(request, "generated", ()) or ()),
        )

    def preempt_candidate(self, head, running):
        """Deadline-aware victim choice: eligible victims run in a class
        strictly below the stalled head's, have preemptions left, and
        are not closer to a still-achievable deadline than the head —
        preempting someone tighter-but-on-time than the waiter would
        just move the miss, but a victim already PAST its soft deadline
        stays eligible (its SLO is lost either way; long-running batch
        work going overdue must not become unpreemptable, or preemption
        silently disables exactly during sustained overload). Among
        eligible: lowest class first, then most slack, then least
        generated progress (cheapest resume)."""
        if not self.spec.preempt:
            return None
        now = self._clock()
        head_cls = normalize_priority(getattr(head, "priority", "default"))
        head_rank = priority_rank(head_cls)
        head_slack = (
            getattr(head, "enqueue_time", now)
            + self._policies[head_cls].deadline_s
            - now
        )
        best = None
        best_key = None
        for slot_id, request in running:
            cls = normalize_priority(getattr(request, "priority", "default"))
            if priority_rank(cls) <= head_rank:
                continue
            if getattr(request, "preemptions", 0) >= self.spec.max_preemptions:
                continue
            slack = (
                getattr(request, "enqueue_time", now)
                + self._policies[cls].deadline_s
                - now
            )
            if 0 <= slack <= head_slack:
                continue
            key = (
                -priority_rank(cls),  # lowest class first
                -slack,               # most slack first
                len(getattr(request, "generated", ()) or ()),  # cheapest redo
            )
            if best_key is None or key < best_key:
                best, best_key = slot_id, key
        return best

    def note_preempted(self, request) -> None:
        cls = normalize_priority(getattr(request, "priority", "default"))
        self.counters[cls]["preempted"] += 1

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict[str, Any]:
        classes: dict[str, Any] = {}
        for cls in self._order:
            waits = sorted(self._waits[cls])
            classes[cls] = {
                **self.counters[cls],
                "depth": len(self._queues[cls]),
                "weight": self._policies[cls].weight,
                "queue_limit": self._policies[cls].queue_limit,
                "queue_wait_p50_s": _pct(waits, 0.50),
                "queue_wait_p95_s": _pct(waits, 0.95),
            }
        totals = {
            key: sum(self.counters[c][key] for c in self._order)
            for key in ("queued", "admitted", "shed", "preempted", "resumed")
        }
        return {
            "policy": "qos",
            # live depth vs the cumulative ``queued`` counter below
            "depth": self.qsize(),
            **totals,
            "classes": classes,
            "tenants": self.limiter.stats(),
        }


def make_scheduler(spec: QosSpec | None) -> Scheduler:
    """The engine's factory: a QoS spec that exists and is enabled gets
    the QoS scheduler; everything else keeps the FIFO default."""
    if spec is not None and spec.enabled:
        return QosScheduler(spec)
    return FifoScheduler()
