"""Streaming token delivery: the TBT digest + disconnect-as-cancellation.

The engine's chunk-emission plane (``TpuServingEngine._flush_emits``)
delivers ``(new_token_ids, new_text, is_final)`` to a per-request
``on_chunk`` consumer at every decode-chunk boundary. This module holds
the two pieces that plane needs but that neither belong in the 6k-line
engine nor may import it:

- :class:`TbtDigest` — a **bounded** inter-emit interval digest
  (log-spaced buckets, p50/p99/max/count). The per-request record that
  lands in ``request_timings`` and the per-class aggregate behind
  ``stats()["streaming"]`` are both this shape — the raw interval list
  is never stored (a 4k-token stream at decode-chunk 4 is a thousand
  floats per request; the ring holds 4096 requests).
- :class:`StreamCancelRegistry` / :data:`STREAMS` — the bridge that
  turns a gateway-observed client disconnect into an engine-side
  cancellation. The engine registers each request's future under its
  ``stream-key`` (the ``langstream-stream-id`` header the gateway
  stamped); the gateway calls :meth:`~StreamCancelRegistry.cancel` from
  its socket teardown. Cancellation lands via
  ``loop.call_soon_threadsafe`` so the gateway may live on another
  thread/loop than the engine; the engine's decode loop observes
  ``future.cancelled()`` at the next chunk boundary and frees the slot
  (the PR 4 cancel path — this module adds only the wiring). Entries
  self-clean through a future done-callback, so an abandoned key never
  pins a request object.

Hot-path discipline (graftcheck **STRM1501**, the emit-path twin of
OBS503): :meth:`TbtDigest.add` is pure arithmetic — no locks, no I/O,
no device sync — because it runs inside ``_flush_emits`` between decode
dispatches. The registry's lock is acquired only at request
register/unregister and at gateway teardown, never per token.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any

__all__ = ["TbtDigest", "StreamCancelRegistry", "STREAMS"]


def _log_bounds() -> tuple:
    """Bucket upper bounds: 1 ms growing ~1.33x per bucket out to ~200 s
    (48 buckets). Built once at import; quantiles interpolate nothing —
    they answer with the bucket bound, which at 1.33x spacing is within
    ~15% of the true value, plenty for an alerting digest."""
    bounds = []
    v = 0.001
    for _ in range(48):
        bounds.append(v)
        v *= 4.0 / 3.0
    return tuple(bounds)


class TbtDigest:
    """Bounded time-between-emissions digest: log-spaced bucket counts
    plus exact count/max/sum. ~50 ints per instance regardless of stream
    length; ``add`` is two comparisons, a scan-free bucket index, and
    three attribute bumps — wait-free by construction (STRM1501)."""

    BOUNDS = _log_bounds()

    __slots__ = ("counts", "count", "max", "sum")

    def __init__(self) -> None:
        self.counts = [0] * (len(self.BOUNDS) + 1)
        self.count = 0
        self.max = 0.0
        self.sum = 0.0

    def add(self, interval_s: float) -> None:
        if interval_s < 0.0:
            interval_s = 0.0
        # inline binary search (≤6 probes over 48 bounds): no imports,
        # no allocation, nothing a hot emit path has to wait on
        lo, hi = 0, len(self.BOUNDS)
        while lo < hi:
            mid = (lo + hi) // 2
            if interval_s <= self.BOUNDS[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1
        self.count += 1
        self.sum += interval_s
        if interval_s > self.max:
            self.max = interval_s

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile observation
        (0 with no observations). The overflow bucket answers with the
        exact observed max — an off-scale stall must not be clipped to
        the last bound."""
        if self.count == 0:
            return 0.0
        rank = max(1, int(q * self.count + 0.999999))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                if i < len(self.BOUNDS):
                    return min(self.BOUNDS[i], self.max)
                return self.max
        return self.max

    def summary(self) -> dict[str, Any]:
        return {
            "count": self.count,
            "p50": round(self.quantile(0.50), 6),
            "p99": round(self.quantile(0.99), 6),
            "max": round(self.max, 6),
            "mean": round(self.sum / self.count, 6) if self.count else 0.0,
        }


class StreamCancelRegistry:
    """stream-key → in-flight request futures, with cross-loop cancel.

    One process-wide instance (:data:`STREAMS`). The engine registers at
    admission (``generate(options={"stream-key": ...})``) and entries
    remove themselves when the future resolves either way; the gateway
    cancels from its disconnect teardown. A key may map to several
    futures (a client can produce many records on one socket before any
    finishes) — cancel sweeps them all.
    """

    #: bound on the cancelled-key memory below — old keys fall off LRU
    CANCELLED_KEYS_MAX = 1024

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # key -> {future: loop}
        self._streams: dict[str, dict[Any, Any]] = {}
        # keys cancel() has seen, kept (bounded) so the agent layer can
        # tell a disconnect-driven CancelledError apart from a shutdown
        # cancel — and so a record that reaches the engine AFTER its
        # client disconnected is cancelled at registration instead of
        # decoding to a dead socket. Values are unused (ordered-set).
        self._cancelled: "OrderedDict[str, None]" = OrderedDict()

    def register(self, key: str, future, loop) -> None:
        with self._lock:
            late_cancel = key in self._cancelled
            self._streams.setdefault(key, {})[future] = loop
        # self-clean on resolution (result, cancel, exception): the
        # callback runs on the engine's loop, after which the key no
        # longer holds the request object
        future.add_done_callback(lambda f: self.unregister(key, f))
        if late_cancel:
            # the disconnect arrived before this record did (the produce
            # sat in the topic behind a queue): every token it would
            # decode is waste, so cancel it the same way cancel() would
            try:
                loop.call_soon_threadsafe(future.cancel)
            except RuntimeError:
                pass

    def unregister(self, key: str, future) -> None:
        with self._lock:
            entry = self._streams.get(key)
            if entry is not None:
                entry.pop(future, None)
                if not entry:
                    self._streams.pop(key, None)

    def cancel(self, key: str) -> int:
        """Cancel every in-flight future registered under ``key``;
        returns how many were signalled. Safe from any thread — the
        cancel itself is marshalled onto each future's own loop."""
        with self._lock:
            entry = dict(self._streams.get(key) or {})
            self._cancelled[key] = None
            self._cancelled.move_to_end(key)
            while len(self._cancelled) > self.CANCELLED_KEYS_MAX:
                self._cancelled.popitem(last=False)
        for future, loop in entry.items():
            try:
                loop.call_soon_threadsafe(future.cancel)
            except RuntimeError:
                # loop already closed: the engine is gone, nothing to free
                pass
        return len(entry)

    def consume_cancelled(self, key: str) -> bool:
        """True exactly once per cancelled key: the agent layer calls
        this when ``engine.generate`` raises ``CancelledError`` to decide
        whether the cancel was a client disconnect (terminal for the
        record — commit it, emit nothing) or a process shutdown (must
        keep propagating). Consuming removes the key."""
        with self._lock:
            if key in self._cancelled:
                del self._cancelled[key]
                return True
            return False

    def active(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._streams.values())


#: process-wide registry: the engine writes, the gateway cancels
STREAMS = StreamCancelRegistry()
