"""Test fixtures.

Multi-chip-without-TPUs strategy (SURVEY.md §4 implication): tests run JAX on
CPU with 8 virtual devices (`--xla_force_host_platform_device_count=8`), the
role KubeTestServer + testcontainers play in the reference — sharding and
collectives are exercised for real, just on host devices.
"""

import os

# Must be set before jax initialises a backend. The environment's TPU plugin
# prepends its own platform to JAX_PLATFORMS at interpreter start, so the
# config override below (not just the env var) is what actually forces CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (repo-local, gitignored): the suite is
# dominated by jit compiles of the same tiny-model programs, and a warm
# cache cuts a full run by minutes on a 2-vCPU box. Keyed by HLO hash +
# compile options + jax version, so correctness is jax's guarantee; set
# LS_TPU_TEST_JAX_CACHE=0 to measure cold-compile behavior.
if os.environ.get("LS_TPU_TEST_JAX_CACHE", "1") != "0":
    _cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".cache", "jax",
    )
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import asyncio  # noqa: E402

import pytest  # noqa: E402

from langstream_tpu.runtime.memory_broker import MemoryBroker  # noqa: E402
from langstream_tpu.agents.vector import InMemoryVectorStore  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (multi-process or subprocess)"
    )


@pytest.fixture(autouse=True)
def _fresh_brokers():
    """Isolate broker + vector-store state between tests."""
    MemoryBroker.reset()
    InMemoryVectorStore.reset()
    yield
    MemoryBroker.reset()
    InMemoryVectorStore.reset()


@pytest.fixture
def run_async():
    def _run(coro):
        return asyncio.run(coro)

    return _run
