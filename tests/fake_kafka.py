"""A fake Kafka broker speaking the server side of the wire protocol.

The role the reference's Kafka testcontainer plays
(``AbstractKafkaApplicationRunner.java:48-51``) — no broker binaries exist
in this image, so the client in ``runtime/kafka_wire.py`` is proven against
this server instead. The request parsing and the record-batch decoding are
written INDEPENDENTLY here (own field-by-field parsing, own CRC check over
the wire bytes), so a client-side encoding bug surfaces as a server-side
parse/CRC failure rather than a self-consistent round-trip.

Single-node cluster (node id 0 = this server); supports the same
non-flexible API versions the client speaks: ApiVersions(0) Metadata(1)
Produce(3) Fetch(4) ListOffsets(1) FindCoordinator(1) OffsetCommit(2)
OffsetFetch(1) CreateTopics(1) DeleteTopics(1).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import secrets
import struct
import threading
from dataclasses import dataclass, field

from langstream_tpu.runtime.kafka_wire import (
    API_API_VERSIONS,
    API_CREATE_TOPICS,
    API_DELETE_TOPICS,
    API_FETCH,
    API_FIND_COORDINATOR,
    API_HEARTBEAT,
    API_JOIN_GROUP,
    API_LEAVE_GROUP,
    API_LIST_OFFSETS,
    API_METADATA,
    API_OFFSET_COMMIT,
    API_OFFSET_FETCH,
    API_PRODUCE,
    API_SASL_AUTHENTICATE,
    API_SASL_HANDSHAKE,
    API_SYNC_GROUP,
    ERR_ILLEGAL_GENERATION,
    ERR_NONE,
    ERR_OFFSET_OUT_OF_RANGE,
    ERR_REBALANCE_IN_PROGRESS,
    ERR_TOPIC_ALREADY_EXISTS,
    ERR_UNKNOWN_MEMBER_ID,
    ERR_SASL_AUTHENTICATION_FAILED,
    ERR_UNKNOWN_TOPIC_OR_PARTITION,
    ERR_UNSUPPORTED_SASL_MECHANISM,
    Reader,
    Writer,
    crc32c,
)


class _ScramServerState:
    """Independent server side of SCRAM-SHA-256/-512 (own derivation — a
    client bug shows up as a proof-verification failure here, not a
    self-consistent round trip)."""

    def __init__(self, mechanism: str, username: str, password: str):
        self.hash = {
            "SCRAM-SHA-256": hashlib.sha256,
            "SCRAM-SHA-512": hashlib.sha512,
        }[mechanism]
        self.username = username
        self.password = password
        self.stage = "first"
        self.salt = secrets.token_bytes(16)
        self.iterations = 4096
        self.client_first_bare = ""
        self.server_first = ""

    def handle_first(self, token: bytes) -> bytes:
        text = token.decode("utf-8")
        assert text.startswith("n,,"), f"unexpected GS2 header in {text!r}"
        self.client_first_bare = text[3:]
        fields = dict(p.split("=", 1) for p in self.client_first_bare.split(","))
        user = fields["n"].replace("=2C", ",").replace("=3D", "=")
        if user != self.username:
            raise PermissionError(f"unknown user {user!r}")
        server_nonce = fields["r"] + secrets.token_urlsafe(18)
        self.server_first = (
            f"r={server_nonce},"
            f"s={base64.b64encode(self.salt).decode()},i={self.iterations}"
        )
        self.stage = "final"
        return self.server_first.encode("utf-8")

    def handle_final(self, token: bytes) -> bytes:
        text = token.decode("utf-8")
        without_proof, _, proof_b64 = text.rpartition(",p=")
        fields = dict(p.split("=", 1) for p in without_proof.split(","))
        server_nonce = dict(
            p.split("=", 1) for p in self.server_first.split(",")
        )["r"]
        if fields.get("r") != server_nonce:
            raise PermissionError("nonce mismatch")
        salted = hashlib.pbkdf2_hmac(
            self.hash().name, self.password.encode(), self.salt,
            self.iterations,
        )
        client_key = hmac.new(salted, b"Client Key", self.hash).digest()
        stored_key = self.hash(client_key).digest()
        auth_message = ",".join(
            [self.client_first_bare, self.server_first, without_proof]
        ).encode("utf-8")
        signature = hmac.new(stored_key, auth_message, self.hash).digest()
        recovered = bytes(
            a ^ b for a, b in zip(base64.b64decode(proof_b64), signature)
        )
        if self.hash(recovered).digest() != stored_key:
            raise PermissionError("SCRAM proof invalid (bad password)")
        server_key = hmac.new(salted, b"Server Key", self.hash).digest()
        server_sig = hmac.new(server_key, auth_message, self.hash).digest()
        self.stage = "done"
        return b"v=" + base64.b64encode(server_sig)


@dataclass
class _StoredRecord:
    offset: int
    timestamp: int
    key: bytes | None
    value: bytes | None
    headers: list[tuple[str, bytes | None]]


@dataclass
class _Partition:
    records: list[_StoredRecord] = field(default_factory=list)

    @property
    def log_end(self) -> int:
        return self.records[-1].offset + 1 if self.records else 0


@dataclass
class _Group:
    """Group-coordinator state machine: Empty → Joining ⇄ AwaitingSync →
    Stable, mirroring the real coordinator's generations. A join round
    completes when every member expected to rejoin has, or when
    ``join_window`` elapses after the first joiner (dropping laggards —
    the session-expiry analogue a test can rely on)."""

    generation: int = 0
    state: str = "Empty"
    protocol: str = ""
    leader: str = ""
    members: dict[str, bytes] = field(default_factory=dict)
    assignments: dict[str, bytes] = field(default_factory=dict)
    joiners: dict[str, bytes] = field(default_factory=dict)
    expected: set[str] = field(default_factory=set)
    join_event: asyncio.Event = field(default_factory=asyncio.Event)
    sync_event: asyncio.Event = field(default_factory=asyncio.Event)
    member_seq: int = 0
    round_id: int = 0


class FakeKafkaBroker:
    def __init__(self, join_window: float = 1.0,
                 sasl: dict[str, tuple[str, str]] | None = None,
                 ssl_context=None) -> None:
        """``sasl``: mechanism -> (username, password); when set, every
        connection must SaslHandshake+SaslAuthenticate before any other
        API (pre-auth requests close the connection, like a real broker).
        ``ssl_context``: server-side ``ssl.SSLContext`` for TLS listeners.
        """
        self.topics: dict[str, dict[int, _Partition]] = {}
        self.offsets: dict[tuple[str, str, int], int] = {}
        self.groups: dict[str, _Group] = {}
        self.join_window = join_window
        self.sasl = sasl
        self.ssl_context = ssl_context
        self.requests: list[tuple[int, int]] = []  # (api_key, version) seen
        self.auth_failures = 0
        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self.host = "127.0.0.1"
        self.port = 0

    # -- lifecycle (runs its own loop thread so tests can drive a client
    #    loop independently) ----------------------------------------------

    def start(self) -> "FakeKafkaBroker":
        started = threading.Event()

        def _run() -> None:
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _serve():
                self._server = await asyncio.start_server(
                    self._client, self.host, 0, ssl=self.ssl_context
                )
                self.port = self._server.sockets[0].getsockname()[1]
                started.set()

            self._loop.run_until_complete(_serve())
            self._loop.run_forever()

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        started.wait(10)
        return self

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(5)
            self._loop = None

    def __enter__(self) -> "FakeKafkaBroker":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- independent server-side record-batch codec ------------------------

    @staticmethod
    def _parse_batches(data: bytes) -> list[tuple[int, bytes | None, bytes | None, list]]:
        """Own parser: header field by field, CRC verified over the raw
        bytes following the crc field."""
        out = []
        pos = 0
        while pos + 61 <= len(data):
            (base_offset,) = struct.unpack_from(">q", data, pos)
            (batch_len,) = struct.unpack_from(">i", data, pos + 8)
            body = data[pos + 12 : pos + 12 + batch_len]
            pos += 12 + batch_len
            magic = body[4]
            assert magic == 2, f"client must send magic 2, got {magic}"
            (crc,) = struct.unpack_from(">I", body, 5)
            assert crc32c(body[9:]) == crc, "client batch CRC invalid"
            r = Reader(body, 9)
            attributes = r.i16()
            codec = attributes & 0x07
            assert codec in (0, 1), f"server only speaks gzip, got codec {codec}"
            r.i32()                       # lastOffsetDelta
            base_ts = r.i64()
            r.i64(); r.i64(); r.i16(); r.i32()
            count = r.i32()
            if codec == 1:
                # independent decompression: stdlib gzip (the client uses
                # zlib.compressobj — a framing bug would fail here)
                import gzip as _gzip

                r = Reader(_gzip.decompress(r.raw(r.remaining())))
            for _ in range(count):
                length = r.varint()
                rec = Reader(r.raw(length))
                rec.i8()
                ts_delta = rec.varint()
                offset_delta = rec.varint()
                klen = rec.varint()
                key = rec.raw(klen) if klen >= 0 else None
                vlen = rec.varint()
                value = rec.raw(vlen) if vlen >= 0 else None
                headers = []
                for _h in range(rec.varint()):
                    hklen = rec.varint()
                    hk = rec.raw(hklen).decode()
                    hvlen = rec.varint()
                    hv = rec.raw(hvlen) if hvlen >= 0 else None
                    headers.append((hk, hv))
                out.append((base_ts + ts_delta, key, value, headers))
        return out

    @staticmethod
    def _encode_batch(records: list[_StoredRecord]) -> bytes:
        """Own encoder for fetch responses (one batch per contiguous run)."""
        if not records:
            return b""
        base = records[0].offset
        base_ts = records[0].timestamp
        body = Writer()
        for rec in records:
            r = Writer()
            r.i8(0)
            r.varint(rec.timestamp - base_ts)
            r.varint(rec.offset - base)
            r.varint(-1 if rec.key is None else len(rec.key))
            if rec.key is not None:
                r.raw(rec.key)
            r.varint(-1 if rec.value is None else len(rec.value))
            if rec.value is not None:
                r.raw(rec.value)
            r.varint(len(rec.headers))
            for hk, hv in rec.headers:
                hkb = hk.encode()
                r.varint(len(hkb))
                r.raw(hkb)
                r.varint(-1 if hv is None else len(hv))
                if hv is not None:
                    r.raw(hv)
            encoded = r.done()
            body.varint(len(encoded)).raw(encoded)
        crc_part = (
            Writer()
            .i16(0)
            .i32(records[-1].offset - base)
            .i64(base_ts)
            .i64(records[-1].timestamp)
            .i64(-1).i16(-1).i32(-1)
            .i32(len(records))
            .raw(body.done())
            .done()
        )
        return (
            Writer()
            .i64(base)
            .i32(4 + 1 + 4 + len(crc_part))
            .i32(-1)
            .i8(2)
            .u32(crc32c(crc_part))
            .raw(crc_part)
            .done()
        )

    # -- group coordinator -------------------------------------------------

    @staticmethod
    def _begin_round(g: _Group, expected: set[str]) -> None:
        g.state = "Joining"
        g.round_id += 1
        g.expected = set(expected)
        g.joiners = {}
        g.join_event = asyncio.Event()
        g.sync_event = asyncio.Event()

    @staticmethod
    def _complete_join(g: _Group) -> None:
        g.generation += 1
        g.members = dict(g.joiners)
        g.leader = sorted(g.members)[0]
        g.state = "AwaitingSync"
        g.assignments = {}
        g.join_event.set()

    async def _join_group(
        self, group: str, member_id: str, protocols: list[tuple[str, bytes]]
    ) -> bytes:
        g = self.groups.setdefault(group, _Group())
        if member_id == "":
            g.member_seq += 1
            member_id = f"member-{g.member_seq}"
        if g.state != "Joining":
            self._begin_round(g, set(g.members) | {member_id})
        else:
            g.expected.add(member_id)
        g.protocol = protocols[0][0] if protocols else "range"
        g.joiners[member_id] = protocols[0][1] if protocols else b""
        if g.expected <= set(g.joiners):
            self._complete_join(g)
        else:
            # wait for the stragglers; on window expiry whoever is present
            # forms the generation (the session-expiry analogue). The round
            # id pins the timeout to THIS round — a stale waiter must never
            # cut a newer round short before its members assembled.
            round_id = g.round_id
            event = g.join_event
            try:
                await asyncio.wait_for(event.wait(), self.join_window)
            except asyncio.TimeoutError:
                if g.state == "Joining" and g.round_id == round_id:
                    self._complete_join(g)
        w = (
            Writer().i32(0).i16(ERR_NONE).i32(g.generation)
            .string(g.protocol).string(g.leader).string(member_id)
        )
        if member_id == g.leader:
            w.array(
                sorted(g.members.items()),
                lambda wr, p: (wr.string(p[0]), wr.bytes_(p[1])),
            )
        else:
            w.i32(0)
        return w.done()

    async def _sync_group(
        self, group: str, generation: int, member_id: str,
        assignments: dict[str, bytes],
    ) -> bytes:
        def _fail(err: int) -> bytes:
            return Writer().i32(0).i16(err).bytes_(b"").done()

        g = self.groups.get(group)
        if g is None or member_id not in g.members:
            return _fail(ERR_UNKNOWN_MEMBER_ID)
        if g.state == "Joining":
            return _fail(ERR_REBALANCE_IN_PROGRESS)
        if generation != g.generation:
            return _fail(ERR_ILLEGAL_GENERATION)
        if member_id == g.leader:
            g.assignments = dict(assignments)
            g.state = "Stable"
            g.sync_event.set()
        else:
            try:
                await asyncio.wait_for(
                    g.sync_event.wait(), self.join_window + 5.0
                )
            except asyncio.TimeoutError:
                return _fail(ERR_REBALANCE_IN_PROGRESS)
        # a new round may have started while this follower waited
        if g.state != "Stable" or generation != g.generation:
            return _fail(ERR_REBALANCE_IN_PROGRESS)
        return (
            Writer().i32(0).i16(ERR_NONE)
            .bytes_(g.assignments.get(member_id, b""))
            .done()
        )

    # -- request handling --------------------------------------------------

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # per-connection SASL session: mechanism chosen by handshake, then
        # token exchange, then (and only then) the normal APIs
        session = {"authenticated": self.sasl is None, "scram": None,
                   "mechanism": None}
        try:
            while True:
                size_raw = await reader.readexactly(4)
                (size,) = struct.unpack(">i", size_raw)
                frame = await reader.readexactly(size)
                r = Reader(frame)
                api_key = r.i16()
                version = r.i16()
                correlation = r.i32()
                r.string()  # client id
                self.requests.append((api_key, version))
                if api_key in (API_SASL_HANDSHAKE, API_SASL_AUTHENTICATE):
                    payload = self._dispatch_sasl(api_key, r, session)
                elif not session["authenticated"]:
                    # real brokers drop unauthenticated connections that
                    # send normal APIs — the client sees a reset
                    self.auth_failures += 1
                    return
                else:
                    payload = await self._dispatch(api_key, version, r)
                body = Writer().i32(correlation).raw(payload).done()
                writer.write(struct.pack(">i", len(body)) + body)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    def _dispatch_sasl(self, api_key: int, r: Reader, session: dict) -> bytes:
        if api_key == API_SASL_HANDSHAKE:
            mechanism = r.string()
            if self.sasl is None or mechanism not in self.sasl:
                supported = sorted(self.sasl or {})
                w = Writer().i16(ERR_UNSUPPORTED_SASL_MECHANISM)
                w.array(supported, lambda wr, m: wr.string(m))
                return w.done()
            session["mechanism"] = mechanism
            if mechanism.startswith("SCRAM"):
                user, pw = self.sasl[mechanism]
                session["scram"] = _ScramServerState(mechanism, user, pw)
            return (
                Writer().i16(ERR_NONE)
                .array([mechanism], lambda wr, m: wr.string(m)).done()
            )
        # SaslAuthenticate v0: auth_bytes in, (error, message, bytes) out
        token = r.bytes_() or b""

        def _fail(msg: str) -> bytes:
            self.auth_failures += 1
            return (
                Writer().i16(ERR_SASL_AUTHENTICATION_FAILED)
                .string(msg).bytes_(b"").done()
            )

        mechanism = session.get("mechanism")
        if mechanism is None:
            return _fail("SaslAuthenticate before SaslHandshake")
        if mechanism == "PLAIN":
            parts = token.split(b"\x00")
            user, pw = self.sasl["PLAIN"]
            if len(parts) != 3 or parts[1].decode() != user \
                    or parts[2].decode() != pw:
                return _fail("invalid PLAIN credentials")
            session["authenticated"] = True
            return Writer().i16(ERR_NONE).string(None).bytes_(b"").done()
        scram: _ScramServerState = session["scram"]
        try:
            if scram.stage == "first":
                out = scram.handle_first(token)
            else:
                out = scram.handle_final(token)
                session["authenticated"] = True
            return Writer().i16(ERR_NONE).string(None).bytes_(out).done()
        except (PermissionError, KeyError, ValueError, AssertionError) as e:
            return _fail(str(e))

    async def _dispatch(self, api_key: int, version: int, r: Reader) -> bytes:
        if api_key == API_API_VERSIONS:
            w = Writer().i16(ERR_NONE)
            keys = [
                (API_PRODUCE, 0, 3), (API_FETCH, 0, 4),
                (API_LIST_OFFSETS, 0, 1), (API_METADATA, 0, 1),
                (API_OFFSET_COMMIT, 0, 2), (API_OFFSET_FETCH, 0, 1),
                (API_FIND_COORDINATOR, 0, 1), (API_JOIN_GROUP, 0, 2),
                (API_HEARTBEAT, 0, 1), (API_LEAVE_GROUP, 0, 1),
                (API_SYNC_GROUP, 0, 1), (API_API_VERSIONS, 0, 0),
                (API_CREATE_TOPICS, 0, 1), (API_DELETE_TOPICS, 0, 1),
                (API_SASL_HANDSHAKE, 0, 1), (API_SASL_AUTHENTICATE, 0, 0),
            ]
            w.i32(len(keys))
            for k, lo, hi in keys:
                w.i16(k).i16(lo).i16(hi)
            return w.done()

        if api_key == API_METADATA:
            assert version == 1
            n = r.i32()
            wanted = [r.string() for _ in range(n)] if n >= 0 else None
            w = Writer()
            w.i32(1).i32(0).string(self.host).i32(self.port).string(None)
            w.i32(0)  # controller id
            names = sorted(self.topics) if wanted is None else wanted
            w.i32(len(names))
            for name in names:
                parts = self.topics.get(name)
                w.i16(ERR_NONE if parts is not None
                      else ERR_UNKNOWN_TOPIC_OR_PARTITION)
                w.string(name)
                w.raw(b"\x00")  # is_internal
                if parts is None:
                    w.i32(0)
                    continue
                w.i32(len(parts))
                for pid in sorted(parts):
                    w.i16(ERR_NONE).i32(pid).i32(0)
                    w.i32(1).i32(0)   # replicas [0]
                    w.i32(1).i32(0)   # isr [0]
            return w.done()

        if api_key == API_PRODUCE:
            assert version == 3
            r.string()               # transactional id
            r.i16()                  # acks
            r.i32()                  # timeout
            w_topics = Writer()
            topic_count = r.i32()
            w_topics.i32(topic_count)
            for _ in range(topic_count):
                topic = r.string()
                w_topics.string(topic)
                part_count = r.i32()
                w_topics.i32(part_count)
                for _p in range(part_count):
                    partition = r.i32()
                    record_set = r.bytes_() or b""
                    part = self.topics.get(topic, {}).get(partition)
                    if part is None:
                        w_topics.i32(partition).i16(
                            ERR_UNKNOWN_TOPIC_OR_PARTITION
                        ).i64(-1).i64(-1)
                        continue
                    base = part.log_end
                    for i, (ts, key, value, headers) in enumerate(
                        self._parse_batches(record_set)
                    ):
                        part.records.append(_StoredRecord(
                            offset=base + i, timestamp=ts, key=key,
                            value=value, headers=headers,
                        ))
                    w_topics.i32(partition).i16(ERR_NONE).i64(base).i64(-1)
            return w_topics.done()

        if api_key == API_FETCH:
            assert version == 4
            r.i32(); r.i32(); r.i32(); r.i32(); r.i8()
            topic_count = r.i32()
            w = Writer().i32(0)      # throttle
            w.i32(topic_count)
            for _ in range(topic_count):
                topic = r.string()
                w.string(topic)
                part_count = r.i32()
                w.i32(part_count)
                for _p in range(part_count):
                    partition = r.i32()
                    fetch_offset = r.i64()
                    r.i32()          # partition max bytes
                    part = self.topics.get(topic, {}).get(partition)
                    if part is None:
                        w.i32(partition).i16(ERR_UNKNOWN_TOPIC_OR_PARTITION)
                        w.i64(-1).i64(-1).i32(0).bytes_(b"")
                        continue
                    if fetch_offset > part.log_end:
                        w.i32(partition).i16(ERR_OFFSET_OUT_OF_RANGE)
                        w.i64(part.log_end).i64(part.log_end).i32(0).bytes_(b"")
                        continue
                    pending = [
                        rec for rec in part.records if rec.offset >= fetch_offset
                    ]
                    w.i32(partition).i16(ERR_NONE)
                    w.i64(part.log_end).i64(part.log_end)
                    w.i32(0)         # aborted transactions
                    w.bytes_(self._encode_batch(pending))
            return w.done()

        if api_key == API_LIST_OFFSETS:
            assert version == 1
            r.i32()
            topic_count = r.i32()
            w = Writer().i32(topic_count)
            for _ in range(topic_count):
                topic = r.string()
                w.string(topic)
                part_count = r.i32()
                w.i32(part_count)
                for _p in range(part_count):
                    partition = r.i32()
                    ts = r.i64()
                    part = self.topics.get(topic, {}).get(partition)
                    if part is None:
                        w.i32(partition).i16(ERR_UNKNOWN_TOPIC_OR_PARTITION)
                        w.i64(-1).i64(-1)
                        continue
                    first = part.records[0].offset if part.records else 0
                    offset = first if ts == -2 else part.log_end
                    w.i32(partition).i16(ERR_NONE).i64(-1).i64(offset)
            return w.done()

        if api_key == API_FIND_COORDINATOR:
            assert version == 1
            r.string()               # group
            r.i8()                   # type
            return (
                Writer().i32(0).i16(ERR_NONE).string(None)
                .i32(0).string(self.host).i32(self.port).done()
            )

        if api_key == API_OFFSET_COMMIT:
            assert version == 2
            group = r.string()
            generation = r.i32()
            member = r.string()
            r.i64()                  # retention
            # simple-consumer commits (generation -1, empty member) are
            # always accepted; dynamic-member commits are FENCED against
            # the coordinator's generation so a zombie that missed a
            # rebalance cannot clobber the new owner's progress
            group_err = ERR_NONE
            if generation != -1 or member != "":
                g = self.groups.get(group)
                if g is None or member not in g.members:
                    group_err = ERR_UNKNOWN_MEMBER_ID
                elif generation != g.generation:
                    group_err = ERR_ILLEGAL_GENERATION
            topic_count = r.i32()
            w = Writer().i32(topic_count)
            for _ in range(topic_count):
                topic = r.string()
                w.string(topic)
                part_count = r.i32()
                w.i32(part_count)
                for _p in range(part_count):
                    partition = r.i32()
                    offset = r.i64()
                    r.string()       # metadata
                    if group_err == ERR_NONE:
                        self.offsets[(group, topic, partition)] = offset
                    w.i32(partition).i16(group_err)
            return w.done()

        if api_key == API_JOIN_GROUP:
            assert version == 2
            group = r.string()
            r.i32()                  # session timeout
            r.i32()                  # rebalance timeout
            member_id = r.string()
            r.string()               # protocol type ("consumer")
            protocols = []
            for _ in range(r.i32()):
                protocols.append((r.string(), r.bytes_() or b""))
            return await self._join_group(group, member_id, protocols)

        if api_key == API_SYNC_GROUP:
            assert version == 1
            group = r.string()
            generation = r.i32()
            member_id = r.string()
            assignments = {}
            for _ in range(r.i32()):
                mid = r.string()
                assignments[mid] = r.bytes_() or b""
            return await self._sync_group(group, generation, member_id, assignments)

        if api_key == API_HEARTBEAT:
            assert version == 1
            group = r.string()
            generation = r.i32()
            member_id = r.string()
            g = self.groups.get(group)
            if g is None or member_id not in (set(g.members) | set(g.joiners)):
                err = ERR_UNKNOWN_MEMBER_ID
            elif g.state == "Joining":
                err = ERR_REBALANCE_IN_PROGRESS
            elif generation != g.generation:
                err = ERR_ILLEGAL_GENERATION
            else:
                err = ERR_NONE
            return Writer().i32(0).i16(err).done()

        if api_key == API_LEAVE_GROUP:
            assert version == 1
            group = r.string()
            member_id = r.string()
            g = self.groups.get(group)
            if g is None or member_id not in (set(g.members) | set(g.joiners)):
                return Writer().i32(0).i16(ERR_UNKNOWN_MEMBER_ID).done()
            g.members.pop(member_id, None)
            g.joiners.pop(member_id, None)
            g.expected.discard(member_id)
            if not g.members and not g.joiners:
                g.state = "Empty"
                g.leader = ""
                g.join_event.set()
                g.sync_event.set()
            elif g.state == "Joining":
                if g.expected and g.expected <= set(g.joiners):
                    self._complete_join(g)
            else:
                # survivors discover the rebalance via heartbeat errors
                self._begin_round(g, set(g.members))
            return Writer().i32(0).i16(ERR_NONE).done()

        if api_key == API_OFFSET_FETCH:
            assert version == 1
            group = r.string()
            topic_count = r.i32()
            w = Writer().i32(topic_count)
            for _ in range(topic_count):
                topic = r.string()
                w.string(topic)
                part_count = r.i32()
                w.i32(part_count)
                for _p in range(part_count):
                    partition = r.i32()
                    offset = self.offsets.get((group, topic, partition), -1)
                    w.i32(partition).i64(offset).string(None).i16(ERR_NONE)
            return w.done()

        if api_key == API_CREATE_TOPICS:
            assert version == 1
            topic_count = r.i32()
            results = []
            for _ in range(topic_count):
                topic = r.string()
                partitions = r.i32()
                r.i16()              # replication
                for _a in range(r.i32()):
                    r.i32()
                    r.array(lambda rr: rr.i32())
                for _c in range(r.i32()):
                    r.string(); r.string()
                if topic in self.topics:
                    results.append((topic, ERR_TOPIC_ALREADY_EXISTS))
                else:
                    self.topics[topic] = {
                        p: _Partition() for p in range(max(partitions, 1))
                    }
                    results.append((topic, ERR_NONE))
            r.i32()                  # timeout
            r.i8()                   # validate_only
            w = Writer().i32(len(results))
            for topic, err in results:
                w.string(topic).i16(err).string(None)
            return w.done()

        if api_key == API_DELETE_TOPICS:
            assert version == 1
            names = r.array(lambda rr: rr.string())
            r.i32()                  # timeout
            w = Writer().i32(0).i32(len(names))
            for name in names:
                err = ERR_NONE if self.topics.pop(name, None) is not None \
                    else ERR_UNKNOWN_TOPIC_OR_PARTITION
                w.string(name).i16(err)
            return w.done()

        raise AssertionError(f"unsupported api key {api_key} v{version}")
