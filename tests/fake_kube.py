"""Back-compat shim: the conformance-grade fake API server was promoted to
``langstream_tpu.k8s.apiserver`` (the mini-cluster's embedded API server —
the process-kubelet's pods reach it over real HTTP)."""

from langstream_tpu.k8s.apiserver import *  # noqa: F401,F403
from langstream_tpu.k8s.apiserver import FakeKubeApiServer  # noqa: F401
