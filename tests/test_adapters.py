"""Multi-LoRA adapter serving e2e (serving/adapters.py, docs/ADAPTERS.md).

Layers covered: the spec (kebab round trip + deploy-time validation
rejects), the wire format (LSKV adapter blobs: kind/name/fingerprint/
factor-set checks), the store's tier mechanics (T0 row LRU + pin
refusal, T1 budget demote-vs-evict, T2 scan discovery + hydration +
the hydrate-pin window, fingerprint refusal-and-delete), the exact-
ledger property test (byte conservation across any install/demote/
hydrate/evict sequence), the engine integration (single-adapter greedy
f32 generation identical to offline-merged ``W + A @ B`` weights;
adapter-less and default-config surfaces byte-identical to the seed;
unknown-adapter and hydrate-timeout cold refusals; the journey's
``adapter-hydrate`` segment), the chaos leg (more adapters than T0
rows under concurrent mixed-adapter traffic — the evict/re-hydrate
storm completes every request with zero silent loss and exactly-
summing ledgers, and a fresh replica cold-starts from T2 byte-
identically to a locally-loaded run), the router's adapter affinity,
the gateway's tenant-config adapter stamp, the incident plane's
``adapter-storm`` thrash predicate, the engine_top adapters panel +
thrash flag, and the ``multi_lora`` bench phase.
"""

import asyncio
import importlib.util
import random
from pathlib import Path

import numpy as np
import pytest

from langstream_tpu.serving.adapters import (
    ADAPTER_HEADER,
    FACTOR_KEYS,
    AdapterStore,
    AdapterStoreSpec,
    AdapterUnavailable,
    check_adapter_name,
    deserialize_adapter,
    make_lora_arrays,
    merge_adapter_into_params,
    publish_adapter,
    serialize_adapter,
    validate_application_adapter_store,
)
from langstream_tpu.serving.kvtransfer import LayoutMismatch

FINGERPRINT = {
    "model": "tiny",
    "dtype": "float32",
    "rank": 2,
    "layers": 1,
    "hidden": 8,
    "heads": 2,
    "kv-heads": 1,
    "head-dim": 4,
}


def _spec(tmp_path=None, **overrides) -> AdapterStoreSpec:
    d = {
        "rank": 2,
        "t0-entries": 2,
        "t1-bytes": 1 << 20,
        "hydrate-timeout-s": 5.0,
        "t2-rescan-s": 0.1,
    }
    if tmp_path is not None:
        d["t2"] = {"type": "local", "path": str(tmp_path)}
    d.update(overrides)
    return AdapterStoreSpec.from_dict(d)


def _store(tmp_path=None, clock=None, **overrides) -> AdapterStore:
    kwargs = {} if clock is None else {"clock": clock}
    return AdapterStore(
        _spec(tmp_path, **overrides),
        fingerprint=dict(FINGERPRINT),
        entry_bytes=4096,
        **kwargs,
    )


def _arrays(seed: int) -> dict[str, np.ndarray]:
    """Tiny factor set matching FINGERPRINT (one layer, rank 2)."""
    return make_lora_arrays(
        layers=1, hidden=8, heads=2, kv_heads=1, head_dim=4,
        rank=2, seed=seed,
    )


def _nbytes(arrays: dict[str, np.ndarray]) -> int:
    return int(sum(a.nbytes for a in arrays.values()))


def _assert_conserved(store: AdapterStore) -> None:
    ledger = store.ledger()
    resident = (
        ledger["t1_bytes"]
        + ledger["in_transit_bytes"]
        + ledger["t2_bytes"]
    )
    flows = (
        ledger["inserted_bytes"]
        + ledger["discovered_bytes"]
        - ledger["evicted_bytes"]
    )
    assert resident == flows, ledger


def _settle(store: AdapterStore, timeout_s: float = 10.0) -> None:
    """Flush the hydrator and apply its results (tests only)."""
    assert store.flush(timeout_s)
    store.apply_results()


# --------------------------------------------------------------------------
# spec + validation + names
# --------------------------------------------------------------------------


def test_spec_roundtrip_and_defaults():
    spec = _spec()
    back = AdapterStoreSpec.from_dict(spec.to_dict())
    assert back == spec
    assert AdapterStoreSpec.from_dict(None) is None
    full = AdapterStoreSpec.from_dict(
        {
            "enabled": True,
            "rank": 16,
            "t0-entries": 8,
            "t1-bytes": 4096,
            "t2-bytes": 1 << 30,
            "t2": {"type": "local", "path": "/tmp/x"},
            "hydrate-timeout-s": 2.5,
            "t2-rescan-s": 1.0,
        }
    )
    assert AdapterStoreSpec.from_dict(full.to_dict()) == full
    assert full.t2_config() == {"type": "local", "path": "/tmp/x"}
    # defaults
    bare = AdapterStoreSpec.from_dict({})
    assert bare.rank == 8 and bare.t0_entries == 4
    assert bare.hydrate_timeout_s == 5.0 and bare.t2_config() is None


@pytest.mark.parametrize(
    "bad",
    [
        {"rank": 0},
        {"t0-entries": 0},
        {"t1-bytes": 0},
        {"t2-bytes": -5},
        {"hydrate-timeout-s": 0},
        {"t2-rescan-s": -1},
        {"t2": {"type": "ftp"}},
        {"t2": "not-a-mapping"},
        {"unknown-key": 1},
    ],
)
def test_spec_validation_rejects(bad):
    with pytest.raises(ValueError):
        AdapterStoreSpec.from_dict(bad)


def test_validate_application_adapter_store():
    class Res:
        type = "tpu-serving-configuration"

        def __init__(self, conf):
            self.configuration = conf

    class App:
        def __init__(self, conf):
            self.resources = {"tpu": Res(conf)}

    validate_application_adapter_store(App({"adapter-store": None}))
    validate_application_adapter_store(
        App({"adapter-store": {"rank": 4, "t0-entries": 2}})
    )
    with pytest.raises(ValueError, match="adapter-store"):
        validate_application_adapter_store(
            App({"adapter-store": {"rank": -1}})
        )


def test_check_adapter_name():
    assert check_adapter_name("tenant-a-v3") == "tenant-a-v3"
    for bad in ("", "a/b", "a b", "a\nb", "x" * 121, None):
        with pytest.raises(ValueError):
            check_adapter_name(bad)


def test_engine_config_requires_paged_layout():
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    with pytest.raises(ValueError, match="kv-layout=paged"):
        TpuServingEngine(
            ServingConfig(
                model="tiny", slots=1, max_seq_len=64,
                adapter_store=_spec(),
            )
        )


# --------------------------------------------------------------------------
# wire format
# --------------------------------------------------------------------------


def test_wire_roundtrip_and_checks():
    arrays = _arrays(1)
    blob = serialize_adapter("a1", arrays, FINGERPRINT)
    back = deserialize_adapter(blob, "a1", FINGERPRINT)
    assert set(back) == set(FACTOR_KEYS)
    for k in FACTOR_KEYS:
        np.testing.assert_array_equal(back[k], arrays[k])
    # name-vs-key mismatch
    with pytest.raises(LayoutMismatch, match="does not match"):
        deserialize_adapter(blob, "a2", FINGERPRINT)
    # fingerprint mismatch names the disagreeing key
    with pytest.raises(LayoutMismatch, match="rank"):
        deserialize_adapter(blob, "a1", {**FINGERPRINT, "rank": 4})
    # missing factor
    partial = {k: v for k, v in arrays.items() if k != "wo_b"}
    bad = serialize_adapter("a1", partial, FINGERPRINT)
    with pytest.raises(LayoutMismatch, match="missing factors"):
        deserialize_adapter(bad, "a1", FINGERPRINT)


# --------------------------------------------------------------------------
# store tier mechanics
# --------------------------------------------------------------------------


def test_t0_row_lru_pin_and_refusal():
    store = _store()  # 2 device rows, no T2
    for name, seed in (("a", 1), ("b", 2), ("c", 3)):
        store.install(name, _arrays(seed))
    ra = store.t0_assign("a")
    rb = store.t0_assign("b")
    assert {ra, rb} == {1, 2}  # row 0 is the reserved zeros row
    # LRU bump: touching "a" makes "b" the eviction victim
    assert store.t0_row("a") == ra
    rc = store.t0_assign("c")
    assert rc == rb
    assert store.t0_evictions == 1
    assert sorted(store.t0_resident()) == ["a", "c"]
    # pins refuse eviction: with both rows pinned a new assign fails
    store.pin("a")
    store.pin("c")
    assert store.t0_assign("b") is None
    assert store.eviction_refusals == 1
    # releasing one pin unblocks the assignment
    store.unpin("c")
    assert store.t0_assign("b") == rc
    assert store.pinned("a") == 1 and store.pinned("c") == 0
    kinds = [k for k, _ in store.drain_events()]
    assert kinds.count("adapter-evict") == 2
    _assert_conserved(store)


def test_t1_budget_evicts_without_t2_demotes_with(tmp_path):
    arrays = _arrays(1)
    per = _nbytes(arrays)
    # no T2: the second install pushes the first out — counted eviction
    store = _store(**{"t1-bytes": per + per // 2})
    store.install("a", _arrays(1))
    store.install("b", _arrays(2))
    assert store.t1_has("b") and not store.t1_has("a")
    assert store.evictions == 1 and store.evicted_bytes == per
    events = store.drain_events()
    assert ("adapter-evict", {
        "tier": "t1", "adapter": "a", "bytes": per, "reason": "t1-budget",
    }) in events
    _assert_conserved(store)

    # with T2: the overflow demotes instead — bytes move through
    # in_transit into the T2 index, nothing is lost
    store2 = _store(tmp_path, **{"t1-bytes": per + per // 2})
    store2.install("a", _arrays(1))
    store2.install("b", _arrays(2))
    _settle(store2)
    assert store2.demotions_t1_t2 == 1
    assert store2.t2_has("a") and store2.t2_bytes == per
    assert store2.in_transit_bytes == 0
    assert store2.evictions == 0
    _assert_conserved(store2)
    store2.close()


def test_t2_scan_discovery_and_hydration(tmp_path):
    publish_adapter(
        {"type": "local", "path": str(tmp_path)},
        "pub", _arrays(9), FINGERPRINT,
    )
    store = _store(tmp_path)
    _settle(store)  # initial scan job
    assert store.known("pub") and store.t2_has("pub")
    ledger = store.ledger()
    # discovered via scan: size unknown until first fetch
    assert ledger["t2_bytes"] == 0 and ledger["discovered_bytes"] == 0
    assert store.request_hydration(["pub"]) == 1
    _settle(store)
    assert store.t1_has("pub")
    per = _nbytes(_arrays(9))
    ledger = store.ledger()
    assert ledger["discovered_bytes"] == per
    assert ledger["t2_bytes"] == per  # still durable in T2
    assert store.hydrations == 1 and store.t2_hits == 1
    kinds = [k for k, _ in store.drain_events()]
    assert "adapter-hydrate" in kinds
    _assert_conserved(store)
    # unknown names are nothing to wait for
    assert store.request_hydration(["nope"]) == 0
    store.close()


def test_hydrated_entries_pinned_against_shrink(tmp_path):
    """A freshly hydrated T1 entry survives the budget shrink for one
    hydrate-timeout window (no hydrate->evict->re-hydrate livelock);
    the pin expires with the fake clock and the shrink proceeds."""
    now = [1000.0]
    per = _nbytes(_arrays(1))
    store = _store(
        tmp_path, clock=lambda: now[0],
        **{"t1-bytes": per + per // 2, "hydrate-timeout-s": 5.0},
    )
    publish_adapter(
        {"type": "local", "path": str(tmp_path)},
        "hyd", _arrays(3), FINGERPRINT,
    )
    store._jobs.append(("scan",))
    store._kick.set()
    _settle(store)
    store.request_hydration(["hyd"])
    _settle(store)
    assert store.t1_has("hyd")
    # a local install overflows the budget — but the hydrated entry is
    # pin-protected, so the INSTALL itself is the eviction victim...
    store.install("loc", _arrays(4))
    assert store.t1_has("hyd")
    # ...until the window passes: then the hydrated entry shrinks away
    now[0] += 6.0
    store.install("loc2", _arrays(5))
    store._shrink_t1()
    assert not store.t1_has("hyd")
    _settle(store)
    _assert_conserved(store)
    store.close()


def test_fingerprint_mismatch_refused_and_deleted(tmp_path):
    publish_adapter(
        {"type": "local", "path": str(tmp_path)},
        "bad", _arrays(2), {**FINGERPRINT, "rank": 64},
    )
    store = _store(tmp_path)
    _settle(store)
    assert store.t2_has("bad")
    store.request_hydration(["bad"])
    _settle(store)
    assert not store.t1_has("bad")
    assert store.fingerprint_refusals == 1
    assert store.hydrate_failures == 1
    assert not store.t2_has("bad")  # dropped from the index
    events = store.drain_events()
    refusal = [
        d for k, d in events
        if k == "adapter-evict" and "fingerprint" in d.get("reason", "")
    ]
    assert refusal and refusal[0]["adapter"] == "bad"
    # the blob was DELETED from the origin: the next scan cannot
    # resurrect a blob that would refuse forever
    store._jobs.append(("scan",))
    store._kick.set()
    _settle(store)
    assert not store.known("bad")
    _assert_conserved(store)
    store.close()


def test_t2_byte_budget_trims_oldest(tmp_path):
    per = _nbytes(_arrays(1))
    store = _store(
        tmp_path,
        **{"t1-bytes": per + per // 2, "t2-bytes": per + per // 2},
    )
    for name, seed in (("a", 1), ("b", 2), ("c", 3)):
        store.install(name, _arrays(seed))
        _settle(store)
    # two demotions landed; the T2 budget holds one — oldest trimmed
    assert store.demotions_t1_t2 == 2
    assert store.t2_bytes <= per + per // 2
    assert store.evictions >= 1
    trims = [
        d for k, d in store.drain_events()
        if k == "adapter-evict" and d.get("reason") == "t2-budget"
    ]
    assert trims
    _assert_conserved(store)
    store.close()


# --------------------------------------------------------------------------
# ledger conservation property
# --------------------------------------------------------------------------


def test_ledger_conservation_property(tmp_path):
    """Random install/assign/hydrate/shrink/trim sequences keep
    ``t1 + in_transit + t2 == inserted + discovered - evicted`` exact
    at every settle point."""
    rng = random.Random(7)
    per = _nbytes(_arrays(0))
    store = _store(
        tmp_path,
        **{"t1-bytes": int(per * 2.5), "t2-bytes": per * 3},
    )
    names = [f"ad-{i}" for i in range(8)]
    # seed a couple of T2-only blobs for scan discovery
    for i in (6, 7):
        publish_adapter(
            {"type": "local", "path": str(tmp_path)},
            names[i], _arrays(100 + i), FINGERPRINT,
        )
    store._jobs.append(("scan",))
    store._kick.set()
    for step in range(60):
        op = rng.randrange(5)
        name = rng.choice(names)
        if op == 0:
            store.install(name, _arrays(hash(name) % 997))
        elif op == 1:
            store.t0_assign(name)
        elif op == 2:
            store.request_hydration([name])
        elif op == 3:
            store.pin(name) if rng.random() < 0.5 else store.unpin(name)
        else:
            _settle(store)
            _assert_conserved(store)
    _settle(store)
    _assert_conserved(store)
    # T0's copy-tier ledger is exact arithmetic over the row map
    assert store.ledger()["t0_bytes"] == len(store.t0_resident()) * 4096
    store.drain_events()
    store.close()


# --------------------------------------------------------------------------
# engine integration: merge pin, byte-identity, refusals, journey
# --------------------------------------------------------------------------

TINY = dict(
    model="tiny", slots=2, max_seq_len=256, decode_chunk=4,
    model_dtype="float32", kv_layout="paged", kv_block_size=16,
    kv_pool_blocks=48,
)


def _engine_config(tmp_path=None, **overrides):
    from langstream_tpu.serving.engine import ServingConfig

    spec = {
        "rank": 4,
        "t0-entries": 2,
        "t1-bytes": 8 << 20,
        "hydrate-timeout-s": 10.0,
        "t2-rescan-s": 0.1,
    }
    if tmp_path is not None:
        spec["t2"] = {"type": "local", "path": str(tmp_path)}
    spec.update(overrides)
    return ServingConfig(
        **TINY, adapter_store=AdapterStoreSpec.from_dict(spec)
    )


def _engine_arrays(seed: int) -> dict[str, np.ndarray]:
    """Factors matching the tiny model at the engine specs' rank 4."""
    return make_lora_arrays(
        layers=2, hidden=64, heads=4, kv_heads=2, head_dim=16,
        rank=4, seed=seed,
    )


def test_single_adapter_matches_offline_merge():
    """The correctness pin: greedy f32 generation through the ragged
    batched adapter path equals the base model with the same deltas
    merged offline (``W + A @ B``)."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    prompt = list(range(1, 80))
    opts = {"max-tokens": 8, "temperature": 0}
    arrays = _engine_arrays(11)

    async def main():
        a = TpuServingEngine(_engine_config())
        a.install_adapter("tenant-a-v1", arrays)
        adapted = await a.generate(
            prompt, {**opts, "adapter": "tenant-a-v1"}
        )
        base = await a.generate(prompt, dict(opts))
        st = a.stats()["adapters"]
        assert st["t0"]["loads"] == 1
        assert sorted(st["t0"]["resident"]) == ["tenant-a-v1"]
        await a.close()
        TpuServingEngine.reset_instances()

        # offline-merged reference: a store-less engine whose attention
        # weights carry the deltas
        ref = TpuServingEngine(ServingConfig(**TINY))
        ref.params = merge_adapter_into_params(ref.params, arrays)
        merged = await ref.generate(prompt, dict(opts))
        plain = await ref.generate(prompt, dict(opts))  # merged != base
        await ref.close()
        TpuServingEngine.reset_instances()

        assert adapted["tokens"] == merged["tokens"]
        assert adapted["text"] == merged["text"]
        assert merged["tokens"] == plain["tokens"]  # determinism sanity
        # the adapter genuinely steered the output
        assert adapted["tokens"] != base["tokens"]

    asyncio.run(main())


def test_adapterless_surfaces_byte_identical_to_seed():
    """Adapter-less traffic on an adapter-enabled engine produces the
    seed's exact tokens, and a default-config engine exposes no adapter
    surface anywhere (stats, scrape)."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    prompt = list(range(1, 80))
    opts = {"max-tokens": 8, "temperature": 0}

    async def main():
        seed = TpuServingEngine(ServingConfig(**TINY))
        want = await seed.generate(prompt, dict(opts))
        stats = seed.stats()
        assert "adapters" not in stats
        assert seed.adapter_store is None and seed._ad_layers is None
        assert not any(
            str(e.get("kind", "")).startswith("adapter")
            for e in seed.flight.recent_events()
        )
        await seed.close()
        TpuServingEngine.reset_instances()

        with_store = TpuServingEngine(_engine_config())
        with_store.install_adapter("unused", _engine_arrays(5))
        got = await with_store.generate(prompt, dict(opts))
        assert got["tokens"] == want["tokens"]
        assert got["text"] == want["text"]
        events = [
            e["kind"] for e in with_store.flight.recent_events()
        ]
        assert "adapter-load" not in events  # nothing resolved a row
        await with_store.close()
        TpuServingEngine.reset_instances()

    asyncio.run(main())


def test_unknown_adapter_refused_cold():
    from langstream_tpu.serving.engine import TpuServingEngine

    async def main():
        a = TpuServingEngine(_engine_config())
        with pytest.raises(AdapterUnavailable, match="not resident"):
            await a.generate(
                list(range(1, 40)),
                {"max-tokens": 4, "temperature": 0, "adapter": "ghost"},
            )
        st = a.stats()["adapters"]
        assert st["refusals"] == 1
        events = [e["kind"] for e in a.flight.recent_events()]
        assert "adapter-refused" in events
        await a.close()
        TpuServingEngine.reset_instances()

    asyncio.run(main())


def test_install_adapter_shape_checked():
    from langstream_tpu.serving.engine import TpuServingEngine

    a = TpuServingEngine(_engine_config())
    wrong_rank = make_lora_arrays(
        layers=2, hidden=64, heads=4, kv_heads=2, head_dim=16,
        rank=2, seed=1,
    )
    with pytest.raises(ValueError, match="shape"):
        a.install_adapter("bad", wrong_rank)

    async def main():
        await a.close()
        TpuServingEngine.reset_instances()

    asyncio.run(main())


def test_hydrate_timeout_refuses_cold(tmp_path):
    """A hydration whose blob never arrives refuses the request loudly
    inside the deadline — never a silent strand, never a silent base-
    weights answer."""
    from langstream_tpu.serving.engine import TpuServingEngine

    async def main():
        publish_adapter(
            {"type": "local", "path": str(tmp_path)},
            "slow", _engine_arrays(3), FINGERPRINT,  # wrong fp is fine:
        )  # the fetch never happens — the hydrator dies first
        b = TpuServingEngine(
            _engine_config(tmp_path, **{"hydrate-timeout-s": 0.3})
        )
        store = b.adapter_store
        assert store.flush(10)
        store.apply_results()
        assert store.t2_has("slow")
        # sabotage: the hydrator thread exits — fetches never complete
        store._jobs.append(("stop",))
        store._kick.set()
        with pytest.raises(AdapterUnavailable, match="timed out"):
            await asyncio.wait_for(
                b.generate(
                    list(range(1, 40)),
                    {"max-tokens": 4, "temperature": 0, "adapter": "slow"},
                ),
                30,
            )
        events = [
            e for e in b.flight.recent_events()
            if e.get("kind") == "adapter-hydrate"
        ]
        assert any(e.get("stage") == "timeout" for e in events)
        assert not b._adapter_hydrating
        await b.close()
        TpuServingEngine.reset_instances()

    asyncio.run(main())


def test_hydration_journey_segment(tmp_path):
    """A T2 cold-start admission records adapter-hydrate journey edges
    that segment into ``adapter-hydrate``."""
    from langstream_tpu.serving.engine import TpuServingEngine
    from langstream_tpu.serving.journey import JOURNEYS, segments

    async def main():
        eng = TpuServingEngine(_engine_config(tmp_path))
        publish_adapter(
            {"type": "local", "path": str(tmp_path)},
            "pub", _engine_arrays(2), eng.adapter_fingerprint(),
        )
        store = eng.adapter_store
        for _ in range(200):
            store.apply_results()
            if store.t2_has("pub"):
                break
            await asyncio.sleep(0.02)
        assert store.t2_has("pub")
        JOURNEYS.clear()
        out = await eng.generate(
            list(range(1, 80)),
            {"max-tokens": 4, "temperature": 0, "adapter": "pub"},
        )
        assert out["tokens"]
        segs = set()
        for jid in JOURNEYS.ids():
            for s in segments(JOURNEYS.events(jid)):
                segs.add(s["segment"])
        assert "adapter-hydrate" in segs
        st = eng.stats()["adapters"]
        assert st["hydrations"] >= 1
        await eng.close()
        TpuServingEngine.reset_instances()

    asyncio.run(main())


# --------------------------------------------------------------------------
# chaos: mixed-adapter eviction storm + cross-replica T2 cold start
# --------------------------------------------------------------------------


def test_chaos_eviction_storm_zero_silent_loss(tmp_path):
    """More adapters than T0 rows under concurrent mixed-adapter
    traffic: the evict/re-hydrate storm completes every request, the
    per-tier ledgers sum exactly, and a fresh replica serving from the
    shared T2 origin answers byte-identically to a locally-loaded
    run."""
    from langstream_tpu.serving.engine import TpuServingEngine

    per = _nbytes(_engine_arrays(0))
    names = [f"ad-{i}" for i in range(4)]
    prompt = list(range(1, 60))
    opts = {"max-tokens": 4, "temperature": 0}

    async def main():
        # T0 holds 2 rows, T1 holds ~2 adapters: 4 adapters churn both
        a = TpuServingEngine(
            _engine_config(
                tmp_path,
                **{"t0-entries": 2, "t1-bytes": int(per * 2.5)},
            )
        )
        for i in (0, 1):
            a.install_adapter(names[i], _engine_arrays(i))
        for i in (2, 3):
            publish_adapter(
                {"type": "local", "path": str(tmp_path)},
                names[i], _engine_arrays(i), a.adapter_fingerprint(),
            )
        store = a.adapter_store
        for _ in range(400):
            store.apply_results()
            if all(store.known(n) for n in names):
                break
            await asyncio.sleep(0.02)
        assert all(store.known(n) for n in names)

        submitted, results = 0, []
        for wave in range(3):
            batch = []
            for i, name in enumerate(names):
                o = dict(opts)
                if i % 2 == 0 or wave == 0:
                    o["adapter"] = name
                # odd slots in later waves ride base weights: the mixed
                # batch is the point of the ragged gather
                batch.append(a.generate(list(prompt), o))
                submitted += 1
            results.extend(
                await asyncio.gather(*batch, return_exceptions=True)
            )
        failures = [r for r in results if isinstance(r, BaseException)]
        completions = [r for r in results if not isinstance(r, BaseException)]
        # zero silent loss: every submission either completed or raised
        assert len(completions) + len(failures) == submitted
        assert not failures, failures
        assert all(r["tokens"] for r in completions)

        st = a.stats()["adapters"]
        # the storm genuinely churned the tiers
        assert st["t0"]["evictions"] + st["evictions"] > 0
        assert st["hydrations"] >= 1
        _assert_conserved(store)
        assert st["t0"]["bytes"] == st["t0"]["entries"] * st["entry_bytes"]
        kinds = [e["kind"] for e in a.flight.recent_events()]
        assert "adapter-load" in kinds and "adapter-evict" in kinds

        # the locally-loaded reference answer for the cold-start pin
        ref = await a.generate(
            list(prompt), {**opts, "adapter": names[2]}
        )
        await a.close()
        TpuServingEngine.reset_instances()

        # replica B: fresh engine, shared T2 only — discovers, hydrates,
        # and serves the SAME adapter byte-identically
        b = TpuServingEngine(_engine_config(tmp_path))
        store_b = b.adapter_store
        for _ in range(400):
            store_b.apply_results()
            if store_b.t2_has(names[2]):
                break
            await asyncio.sleep(0.02)
        cold = await b.generate(
            list(prompt), {**opts, "adapter": names[2]}
        )
        assert cold["tokens"] == ref["tokens"]
        assert cold["text"] == ref["text"]
        st_b = b.stats()["adapters"]
        assert st_b["hydrations"] >= 1
        _assert_conserved(store_b)
        await b.close()
        TpuServingEngine.reset_instances()

    asyncio.run(main())


# --------------------------------------------------------------------------
# router affinity + gateway stamp
# --------------------------------------------------------------------------


def test_router_adapter_affinity():
    from langstream_tpu.gateway.router import ReplicaRouter

    r = ReplicaRouter()
    r.observe([
        {"replica": "app-ai-0", "queued": 0, "occupancy": 0, "slots": 4},
        {"replica": "app-ai-1", "queued": 5, "occupancy": 4, "slots": 4},
    ])
    assert r.pick("t1", adapter="tenant-a-v1") == "app-ai-0"
    # load inverts: the adapter pin holds — even for a different tenant
    r.observe([
        {"replica": "app-ai-0", "queued": 9, "occupancy": 4, "slots": 4},
        {"replica": "app-ai-1", "queued": 0, "occupancy": 0, "slots": 4},
    ])
    assert r.pick("t2", adapter="tenant-a-v1") == "app-ai-0"
    stats = r.stats()
    assert stats["adapter_hits"] == 1
    assert stats["pinned_adapters"] == 1
    # adapter-less traffic keeps the least-loaded choice
    assert r.pick("t3") == "app-ai-1"
    # the pinned replica drains: the pin breaks, traffic re-pins
    r.observe([
        {
            "replica": "app-ai-0", "queued": 0, "occupancy": 0,
            "slots": 4, "draining": True,
        },
        {"replica": "app-ai-1", "queued": 0, "occupancy": 0, "slots": 4},
    ])
    assert r.pick("t2", adapter="tenant-a-v1") == "app-ai-1"
    assert r.stats()["adapter_rerouted"] == 1
    assert r.pick("t9", adapter="tenant-a-v1") == "app-ai-1"
    assert r.stats()["adapter_hits"] == 2


def test_gateway_stamps_adapter_from_tenant_config():
    from langstream_tpu.gateway.server import GatewayServer
    from langstream_tpu.serving.qos import QosSpec, TenantLimiter

    server = GatewayServer(port=0)
    spec = QosSpec.from_dict({
        "tenants": {
            "acme": {"adapter": "acme-summarizer-v2"},
            "plain": {},
        },
    })
    limiter = TenantLimiter(spec)
    out = server._qos_headers(limiter, {"tenant": "acme"}, {})
    assert out[ADAPTER_HEADER] == "acme-summarizer-v2"
    # a tenant with no adapter configured stamps nothing extra
    out2 = server._qos_headers(limiter, {"tenant": "plain"}, {})
    assert ADAPTER_HEADER not in out2
    # no QoS at all: headers stay byte-identical to the seed
    assert server._qos_headers(None, {}, {}) == {}


def test_tenant_policy_adapter_roundtrip():
    from langstream_tpu.serving.qos import QosSpec

    spec = QosSpec.from_dict({
        "tenants": {"acme": {"adapter": "a-v1"}},
    })
    assert spec.tenant_policy("acme").adapter == "a-v1"
    d = spec.to_dict()
    assert d["tenants"]["acme"]["adapter"] == "a-v1"
    # empty adapter is omitted from the wire — pre-adapter configs
    # round-trip byte-identically
    bare = QosSpec.from_dict({"tenants": {"x": {}}})
    assert "adapter" not in bare.to_dict()["tenants"]["x"]


# --------------------------------------------------------------------------
# incident plane: the adapter-storm thrash predicate
# --------------------------------------------------------------------------


def test_adapter_eviction_storm_predicate():
    from langstream_tpu.serving.incident import (
        OFFENDING_SEGMENT,
        TRIGGER_KINDS,
        adapter_eviction_storm,
    )

    assert "adapter-storm" in TRIGGER_KINDS
    assert OFFENDING_SEGMENT["adapter-storm"] == "adapter-hydrate"

    def ev(adapter, m_s):
        return {"kind": "adapter-evict", "adapter": adapter, "m_s": m_s}

    now = 100.0
    # same adapter bouncing: thrash
    events = [ev("hot", now - 9), ev("hot", now - 5), ev("hot", now - 1)]
    hit = adapter_eviction_storm(events, now, k=3, window_s=30.0)
    assert hit == {
        "adapter": "hot", "count": 3, "window_s": 30.0,
        "evictions": events,
    }
    # distinct adapters cycling is healthy LRU turnover, not thrash
    churn = [ev("a", now - 9), ev("b", now - 5), ev("c", now - 1)]
    assert adapter_eviction_storm(churn, now, k=3, window_s=30.0) is None
    # old evictions age out of the window
    stale = [ev("hot", now - 90), ev("hot", now - 80), ev("hot", now - 1)]
    assert adapter_eviction_storm(stale, now, k=3, window_s=30.0) is None


# --------------------------------------------------------------------------
# engine_top: adapters panel + thrash flag
# --------------------------------------------------------------------------


def _load_engine_top():
    path = Path(__file__).resolve().parents[1] / "tools" / "engine_top.py"
    spec = importlib.util.spec_from_file_location("engine_top", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _adapters_entry(evict_t_ms):
    # the summary.totals block makes the entry flight-shaped so
    # analyze()'s dump walker collects it (anomaly flags ride there)
    return {
        "engine": "e0",
        "summary": {
            "totals": {"device_ms": 10.0, "host_ms": 1.0, "stall_ms": 0.0},
        },
        "adapters": {
            "t0": {
                "entries": 2, "budget_entries": 2,
                "bytes": 8192, "budget_bytes": 8192,
                "resident": ["ad-0", "ad-1"], "pinned": {"ad-0": 1},
                "hits": 6, "loads": 4, "evictions": len(evict_t_ms),
                "eviction_refusals": 1,
            },
            "t1": {
                "entries": 3, "bytes": 12288, "budget_bytes": 1 << 20,
                "hits": 5, "misses": 2,
            },
            "t2": {
                "enabled": True, "entries": 4, "bytes": 16384,
                "blob_bytes": 17000, "budget_bytes": None, "hits": 3,
                "in_transit_bytes": 0, "pending_jobs": 0, "scans": 9,
            },
            "rank": 4, "entry_bytes": 4096, "hydrate_timeout_s": 10.0,
            "installs": 2, "demotions_t1_t2": 1, "hydrations": 3,
            "hydrating": 0, "hydrate_failures": 0,
            "fingerprint_refusals": 0, "evictions": 2, "refusals": 1,
        },
        "events": [
            {
                "kind": "adapter-evict", "tier": "t0", "adapter": "ad-0",
                "bytes": 4096, "t_ms": t, "reason": "t0-capacity",
            }
            for t in evict_t_ms
        ],
    }


def test_engine_top_renders_adapters_panel():
    engine_top = _load_engine_top()
    frame = engine_top.render([_adapters_entry([1000.0])])
    assert "adapter" in frame
    assert "rows 2/2" in frame
    assert "ad-0(1)" in frame  # pin count in parens
    assert "refused cold 1" in frame
    # adapter-less payloads render with no adapter lines at all
    quiet = engine_top.render([{"engine": "e0"}])
    assert "adapter" not in quiet
    # --json mirrors the rendered panel
    payload = engine_top.render_json([_adapters_entry([1000.0])])[0]
    panel = payload["panels"]["adapters"]
    assert panel["section"]["rank"] == 4
    assert any("adapter" in ln for ln in panel["lines"])


def test_engine_top_analyze_flags_adapter_thrash():
    engine_top = _load_engine_top()
    # 3 evictions of ONE adapter inside the 10s hydrate window
    out = engine_top.analyze(
        [_adapters_entry([1000.0, 4000.0, 9000.0])]
    )
    assert "adapter thrash" in out and "'ad-0'" in out
    # spread past the window: quiet
    quiet = engine_top.analyze(
        [_adapters_entry([1000.0, 15000.0, 30000.0])]
    )
    assert "adapter thrash" not in quiet


# --------------------------------------------------------------------------
# acceptance e2e: the multi-LoRA bench phase
# --------------------------------------------------------------------------


def test_multi_lora_bench_phase(tmp_path):
    """The bench leg end to end: mixed-adapter traffic over an
    undersized T0 with half the adapters published T2-only — every
    request completes, the ledger balances, and the perf_diff metrics
    are all present."""
    import sys

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    from gateway_bench import run_multi_lora_phase

    out = asyncio.run(
        run_multi_lora_phase(
            tenants=4, adapters=4, repeats=2, max_tokens=4,
            t2_dir=str(tmp_path),
        )
    )
    assert out["zero_silent_loss"] is True
    assert out["failures"] == []
    assert out["ledger_balanced"] is True
    assert out["multi_lora_evictions"] > 0  # the churn genuinely ran
    assert out["hydrations"] > 0  # the T2-published half hydrated
    assert 0.0 <= out["multi_lora_t0_hit_ratio"] <= 1.0
    assert out["multi_lora_ttft_p99_s"] > 0
    assert "adapter-hydrate" in (out.get("journey_segments") or {})
    assert out["router"]["adapter_hits"] > 0
    assert out["flight_events"].get("adapter-load", 0) > 0
