"""Agent-level tests: transform steps, text processing, flow control,
AI agents against the mock provider, vector store round trip."""

import asyncio
import json

import pytest

from langstream_tpu.api.agent import AgentContext
from langstream_tpu.api.record import make_record
from langstream_tpu.runtime.composite import process_await
from langstream_tpu.runtime.local_runner import LocalApplicationRunner

INSTANCE = """
instance:
  streamingCluster:
    type: "memory"
"""

MOCK_CONFIG = """
configuration:
  resources:
    - type: "mock-serving-configuration"
      name: "mock"
      configuration:
        reply: "the answer is 42"
"""


async def run_single(agent_factory, configuration, record):
    agent = agent_factory()
    await agent.init({**configuration, "__resources__": {}, "__globals__": {}})
    await agent.setup(AgentContext())
    await agent.start()
    results = await process_await(agent, [record])
    await agent.close()
    assert len(results) == 1
    if results[0].error:
        raise results[0].error
    return results[0].results


# ---------------------------------------------------------------------------
# transform steps
# ---------------------------------------------------------------------------


def test_compute_and_drop_fields(run_async):
    from langstream_tpu.agents.transform import ComputeStep, DropFieldsStep

    async def main():
        record = make_record(value={"a": 2, "secret": "x"})
        out = await run_single(
            ComputeStep,
            {"fields": [{"name": "value.b", "expression": "value.a * 3"}]},
            record,
        )
        assert out[0].value == {"a": 2, "secret": "x", "b": 6}
        out2 = await run_single(DropFieldsStep, {"fields": ["secret"]}, out[0])
        assert out2[0].value == {"a": 2, "b": 6}

    run_async(main())


def test_when_guard_skips_step(run_async):
    from langstream_tpu.agents.transform import ComputeStep

    async def main():
        record = make_record(value={"a": 1})
        out = await run_single(
            ComputeStep,
            {
                "when": "value.a > 10",
                "fields": [{"name": "value.b", "expression": "99"}],
            },
            record,
        )
        assert out[0].value == {"a": 1}

    run_async(main())


def test_drop_flatten_merge_unwrap(run_async):
    from langstream_tpu.agents.transform import (
        DropStep,
        FlattenStep,
        MergeKeyValueStep,
        UnwrapKeyValueStep,
    )

    async def main():
        dropped = await run_single(
            DropStep, {"when": "value.x == 1"}, make_record(value={"x": 1})
        )
        assert dropped == []
        kept = await run_single(
            DropStep, {"when": "value.x == 1"}, make_record(value={"x": 2})
        )
        assert len(kept) == 1

        flat = await run_single(
            FlattenStep, {}, make_record(value={"a": {"b": {"c": 1}}})
        )
        assert flat[0].value == {"a_b_c": 1}

        merged = await run_single(
            MergeKeyValueStep, {}, make_record(value={"v": 1}, key={"k": 2})
        )
        assert merged[0].value == {"k": 2, "v": 1}

        unwrapped = await run_single(
            UnwrapKeyValueStep, {}, make_record(value={"v": 1}, key={"k": 2})
        )
        assert unwrapped[0].value == {"v": 1} and unwrapped[0].key is None

    run_async(main())


def test_cast(run_async):
    from langstream_tpu.agents.transform import CastStep

    async def main():
        out = await run_single(
            CastStep, {"schema-type": "string"}, make_record(value={"a": 1})
        )
        assert out[0].value == '{"a": 1}'
        out2 = await run_single(
            CastStep, {"schema-type": "int32"}, make_record(value="42")
        )
        assert out2[0].value == 42

    run_async(main())


# ---------------------------------------------------------------------------
# text processing
# ---------------------------------------------------------------------------


def test_text_splitter_chunks(run_async):
    from langstream_tpu.agents.text import TextSplitterAgent

    async def main():
        text = "\n\n".join(f"paragraph {i} " + "word " * 30 for i in range(5))
        out = await run_single(
            TextSplitterAgent,
            {"chunk-size": 100, "chunk-overlap": 10},
            make_record(value=text),
        )
        assert len(out) > 1
        assert all(len(r.value) <= 120 for r in out)
        assert out[0].header("chunk_id") == "0"
        # every chunk advertises the total
        assert {r.header("text_num_chunks") for r in out} == {str(len(out))}

    run_async(main())


def test_splitter_reassembly_covers_text(run_async):
    from langstream_tpu.agents.text import RecursiveCharacterTextSplitter

    async def main():
        text = "the quick brown fox. " * 50
        splitter = RecursiveCharacterTextSplitter(chunk_size=80, chunk_overlap=0)
        chunks = splitter.split_text(text)
        assert all(len(c) <= 80 for c in chunks)
        assert "".join(c.replace(" ", "") for c in chunks).startswith(
            "thequickbrownfox"
        )

    run_async(main())


def test_html_extraction_and_language(run_async):
    from langstream_tpu.agents.text import LanguageDetectorAgent, TextExtractorAgent

    async def main():
        html = "<html><head><script>bad()</script></head><body><p>The cat is on the mat and it is happy</p></body></html>"
        out = await run_single(TextExtractorAgent, {}, make_record(value=html))
        assert "cat is on the mat" in out[0].value
        assert "bad()" not in out[0].value
        lang = await run_single(LanguageDetectorAgent, {}, out[0])
        assert lang[0].header("language") == "en"

    run_async(main())


def test_document_to_json(run_async):
    from langstream_tpu.agents.text import DocumentToJsonAgent

    async def main():
        out = await run_single(
            DocumentToJsonAgent, {"text-field": "question"}, make_record(value="hi")
        )
        assert out[0].value == {"question": "hi"}

    run_async(main())


# ---------------------------------------------------------------------------
# AI agents with the mock provider (WireMock analogue)
# ---------------------------------------------------------------------------

CHAT_PIPELINE = """
topics:
  - name: "input-topic"
    creation-mode: create-if-not-exists
  - name: "output-topic"
    creation-mode: create-if-not-exists
  - name: "stream-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "convert"
    type: "document-to-json"
    input: "input-topic"
    configuration:
      text-field: "question"
  - name: "chat"
    type: "ai-chat-completions"
    output: "output-topic"
    configuration:
      model: "mock-model"
      completion-field: "value.answer"
      log-field: "value.prompt"
      stream-to-topic: "stream-topic"
      stream-response-completion-field: "value"
      min-chunks-per-message: 2
      messages:
        - role: user
          content: "Q: {{ value.question }}"
"""


def test_chat_completions_with_streaming(tmp_path, run_async):
    async def main():
        (tmp_path / "pipeline.yaml").write_text(CHAT_PIPELINE)
        (tmp_path / "configuration.yaml").write_text(MOCK_CONFIG)
        runner = LocalApplicationRunner.from_directory(tmp_path, instance=INSTANCE)
        async with runner:
            await runner.produce(
                "input-topic", "what is it?", headers={"session": "s1"}
            )
            final = await runner.wait_for_messages("output-topic", 1)
            assert final[0].value["answer"] == "the answer is 42"
            assert "Q: what is it?" in final[0].value["prompt"]
            # streamed chunks reassemble to the full answer, preserve headers
            await asyncio.sleep(0.1)
            chunks = await runner.wait_for_messages("stream-topic", 1)
            text = "".join(c.value for c in chunks)
            # eventually all chunks arrive
            for _ in range(50):
                if text == "the answer is 42":
                    break
                await asyncio.sleep(0.05)
                chunks = await runner.wait_for_messages("stream-topic", len(chunks))
                text = "".join(c.value for c in chunks)
            assert text == "the answer is 42"
            assert chunks[0].header("session") == "s1"
            assert chunks[-1].header("stream-last-message") == "true"
            indexes = [int(c.header("stream-index")) for c in chunks]
            assert indexes == sorted(indexes)

    run_async(main())


EMBED_PIPELINE = """
topics:
  - name: "input-topic"
    creation-mode: create-if-not-exists
  - name: "output-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "embed"
    type: "compute-ai-embeddings"
    input: "input-topic"
    output: "output-topic"
    configuration:
      model: "mock-embed"
      embeddings-field: "value.embeddings"
      text: "{{ value.text }}"
      batch-size: 4
      flush-interval: 50
"""


def test_embeddings_batched(tmp_path, run_async):
    async def main():
        (tmp_path / "pipeline.yaml").write_text(EMBED_PIPELINE)
        (tmp_path / "configuration.yaml").write_text(MOCK_CONFIG)
        runner = LocalApplicationRunner.from_directory(tmp_path, instance=INSTANCE)
        async with runner:
            for i in range(6):
                await runner.produce("input-topic", {"text": f"doc {i}"})
            msgs = await runner.wait_for_messages("output-topic", 6)
            for m in msgs:
                assert len(m.value["embeddings"]) == 8
                assert abs(sum(x * x for x in m.value["embeddings"]) - 1.0) < 1e-5

    run_async(main())


RAG_PIPELINE = """
topics:
  - name: "docs-topic"
    creation-mode: create-if-not-exists
  - name: "questions-topic"
    creation-mode: create-if-not-exists
  - name: "answers-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "embed-docs"
    id: "ingest"
    type: "compute-ai-embeddings"
    input: "docs-topic"
    configuration:
      embeddings-field: "value.embeddings"
      text: "{{ value.text }}"
      flush-interval: 0
  - name: "write-docs"
    type: "vector-db-sink"
    configuration:
      datasource: "vdb"
      collection-name: "docs"
      fields:
        - name: "id"
          expression: "value.doc_id"
        - name: "vector"
          expression: "value.embeddings"
        - name: "text"
          expression: "value.text"
"""

QUERY_PIPELINE = """
topics:
  - name: "questions-topic"
    creation-mode: create-if-not-exists
  - name: "answers-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "embed-q"
    id: "query"
    type: "compute-ai-embeddings"
    input: "questions-topic"
    configuration:
      embeddings-field: "value.q_embeddings"
      text: "{{ value.q }}"
      flush-interval: 0
  - name: "lookup"
    type: "query-vector-db"
    output: "answers-topic"
    configuration:
      datasource: "vdb"
      query: '{"collection": "docs", "vector": ?, "top-k": 2}'
      fields:
        - "value.q_embeddings"
      output-field: "value.related"
"""

VDB_CONFIG = """
configuration:
  resources:
    - type: "mock-serving-configuration"
      name: "mock"
      configuration: {}
    - type: "datasource"
      name: "vdb"
      configuration:
        service: "in-memory"
"""


def test_rag_vector_roundtrip(tmp_path, run_async):
    async def main():
        ingest = tmp_path / "ingest"
        ingest.mkdir()
        (ingest / "pipeline.yaml").write_text(RAG_PIPELINE)
        (ingest / "configuration.yaml").write_text(VDB_CONFIG)
        query = tmp_path / "query"
        query.mkdir()
        (query / "pipeline.yaml").write_text(QUERY_PIPELINE)
        (query / "configuration.yaml").write_text(VDB_CONFIG)

        ingest_runner = LocalApplicationRunner.from_directory(
            ingest, instance=INSTANCE, application_id="ingest"
        )
        async with ingest_runner:
            for i, text in enumerate(
                ["cats purr softly", "dogs bark loudly", "fish swim in water"]
            ):
                await ingest_runner.produce(
                    "docs-topic", {"doc_id": f"d{i}", "text": text}
                )
            # wait for the sink to land all three
            from langstream_tpu.agents.vector import InMemoryVectorStore

            # generous deadline: the embedding encoder compiles on first
            # use, and a loaded full-suite run can make that slow on CPU
            for _ in range(600):
                store = InMemoryVectorStore.get("vdb")
                if len(store.collection("docs").ids) == 3:
                    break
                await asyncio.sleep(0.05)
            assert len(store.collection("docs").ids) == 3

        query_runner = LocalApplicationRunner.from_directory(
            query, instance=INSTANCE, application_id="query"
        )
        async with query_runner:
            await query_runner.produce("questions-topic", {"q": "cats purr"})
            msgs = await query_runner.wait_for_messages("answers-topic", 1)
            related = msgs[0].value["related"]
            assert len(related) == 2
            assert related[0]["text"] == "cats purr softly"

    run_async(main())


# ---------------------------------------------------------------------------
# re-rank
# ---------------------------------------------------------------------------


def test_rerank_mmr(run_async):
    from langstream_tpu.agents.ai import ReRankAgent

    async def main():
        docs = [
            {"text": "cats purr", "emb": [1.0, 0.0]},
            {"text": "cats purr again", "emb": [0.99, 0.1]},
            {"text": "dogs bark", "emb": [0.0, 1.0]},
        ]
        record = make_record(
            value={"docs": docs, "q": "cats", "q_emb": [1.0, 0.0]}
        )
        out = await run_single(
            ReRankAgent,
            {
                "field": "value.docs",
                "query-text": "value.q",
                "query-embeddings": "value.q_emb",
                "text-field": "record.text",
                "embeddings-field": "record.emb",
                "output-field": "value.docs",
                "max": 2,
                "lambda": 0.3,  # diversity-heavy: penalise the near-duplicate
            },
            record,
        )
        reranked = out[0].value["docs"]
        assert len(reranked) == 2
        assert reranked[0]["text"] == "cats purr"
        # MMR should diversify: second pick is the dog doc, not the near-dup
        assert reranked[1]["text"] == "dogs bark"

    run_async(main())


# ---------------------------------------------------------------------------
# custom python agents
# ---------------------------------------------------------------------------

PY_PIPELINE = """
topics:
  - name: "input-topic"
    creation-mode: create-if-not-exists
  - name: "output-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "custom"
    type: "python-processor"
    input: "input-topic"
    output: "output-topic"
    configuration:
      className: "my_agent.Exclaimer"
"""

PY_AGENT = """
class Exclaimer:
    def init(self, config):
        self.mark = config.get("mark", "!")

    def process(self, record):
        return [(str(record.value) + self.mark, record.key, {})]
"""


def test_custom_python_processor(tmp_path, run_async):
    async def main():
        (tmp_path / "pipeline.yaml").write_text(PY_PIPELINE)
        pydir = tmp_path / "python"
        pydir.mkdir()
        (pydir / "my_agent.py").write_text(PY_AGENT)
        import sys

        sys.path.insert(0, str(pydir))
        try:
            runner = LocalApplicationRunner.from_directory(tmp_path, instance=INSTANCE)
            async with runner:
                await runner.produce("input-topic", "hello")
                msgs = await runner.wait_for_messages("output-topic", 1)
                assert msgs[0].value == "hello!"
        finally:
            sys.path.remove(str(pydir))

    run_async(main())


# ---------------------------------------------------------------------------
# batching executor
# ---------------------------------------------------------------------------


def test_ordered_batch_executor(run_async):
    from langstream_tpu.api.batching import OrderedAsyncBatchExecutor

    async def main():
        batches = []

        async def proc(batch):
            batches.append(list(batch))
            await asyncio.sleep(0.01)

        ex = OrderedAsyncBatchExecutor(
            batch_size=3, processor=proc, flush_interval=10.0, num_buckets=2,
            key_fn=lambda item: item[0],
        )
        for i in range(6):
            await ex.add(("k1", i))
        await ex.close()
        # same key → same bucket → order preserved across batches
        flat = [item for b in batches for item in b]
        assert [x[1] for x in flat] == list(range(6))
        assert all(len(b) <= 3 for b in batches)

    run_async(main())
