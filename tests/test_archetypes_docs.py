"""Archetypes, diagram, docs generation + the examples tree itself.

Every example application must parse and plan (the role the reference's 36
sample apps play as living documentation — here they are also golden tests).
"""

from __future__ import annotations

from pathlib import Path

import pytest

from langstream_tpu.core.archetypes import (
    ArchetypeError,
    instantiate,
    list_archetypes,
    load_archetype,
)
from langstream_tpu.core.deployer import ApplicationDeployer
from langstream_tpu.core.diagram import mermaid_diagram
from langstream_tpu.core.docsgen import agent_docs, render_json, render_markdown
from langstream_tpu.core.parser import (
    build_application_from_directory,
    build_application_from_files,
)

REPO = Path(__file__).resolve().parents[1]
EXAMPLES = REPO / "examples"


# ---------------------------------------------------------------------------
# examples are golden tests
# ---------------------------------------------------------------------------

EXAMPLE_APPS = sorted(
    p for p in (EXAMPLES / "applications").iterdir() if p.is_dir()
)


@pytest.mark.parametrize("app_dir", EXAMPLE_APPS, ids=lambda p: p.name)
def test_example_application_plans(app_dir):
    app = build_application_from_directory(
        app_dir,
        instance=EXAMPLES / "instances" / "memory.yaml",
        secrets=EXAMPLES / "secrets" / "secrets.yaml",
    )
    plan = ApplicationDeployer().create_implementation("example", app)
    assert plan.agents, f"{app_dir.name}: no agents planned"
    # every agent type is known to the registry
    from langstream_tpu.api.registry import AgentCodeRegistry

    known = AgentCodeRegistry.known_types()
    for node in plan.agents.values():
        for agent in node.agents:
            assert agent.type in known, f"unknown agent type {agent.type!r}"


@pytest.mark.parametrize(
    "instance_file",
    sorted((EXAMPLES / "instances").glob("*.yaml")),
    ids=lambda p: p.name,
)
def test_example_instances_parse(instance_file):
    app = build_application_from_files(
        {"pipeline.yaml": "topics:\n  - name: t\n"},
        instance=instance_file.read_text(),
    )
    assert app.instance.streaming_cluster.type


# ---------------------------------------------------------------------------
# archetypes
# ---------------------------------------------------------------------------


def test_archetype_load_and_instantiate():
    archetypes = list_archetypes(EXAMPLES / "archetypes")
    assert [a.id for a in archetypes] == ["chatbot"]
    chatbot = load_archetype(EXAMPLES / "archetypes" / "chatbot")
    assert chatbot.parameters[0].name == "model"

    files = instantiate(chatbot, {"model": "tiny", "slots": 4})
    assert 'model: "tiny"' in files["pipeline.yaml"]
    assert "slots: 4" in files["configuration.yaml"]
    # defaults apply
    assert "helpful assistant" in files["pipeline.yaml"]
    # the rendered app actually plans
    app = build_application_from_files(
        files, instance="instance:\n  streamingCluster:\n    type: memory\n"
    )
    plan = ApplicationDeployer().create_implementation("chatbot", app)
    assert plan.agents


def test_archetype_parameter_validation():
    chatbot = load_archetype(EXAMPLES / "archetypes" / "chatbot")
    with pytest.raises(ArchetypeError, match="missing required"):
        instantiate(chatbot, {})
    with pytest.raises(ArchetypeError, match="unknown parameters"):
        instantiate(chatbot, {"model": "tiny", "nope": 1})


def test_archetype_endpoints(run_async):
    import aiohttp

    from langstream_tpu.controlplane.server import ControlPlaneServer

    async def main():
        server = ControlPlaneServer(
            port=18990, archetypes_path=str(EXAMPLES / "archetypes")
        )
        server.store.put_tenant("default")
        await server.start()
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(
                    "http://127.0.0.1:18990/api/archetypes/default"
                ) as r:
                    assert (await r.json()) == [
                        {"id": "chatbot", "title": "TPU chatbot"}
                    ]
                async with session.get(
                    "http://127.0.0.1:18990/api/archetypes/default/chatbot"
                ) as r:
                    detail = await r.json()
                    assert detail["parameters"][0]["name"] == "model"
                async with session.post(
                    "http://127.0.0.1:18990/api/archetypes/default/chatbot"
                    "/applications/mybot",
                    json={
                        "parameters": {"model": "tiny", "slots": 2},
                        "instance": (
                            "instance:\n  streamingCluster:\n    type: memory\n"
                        ),
                    },
                ) as r:
                    body = await r.json()
                    assert r.status == 200, body
                    assert body["status"]["status"] == "DEPLOYED"
                async with session.get(
                    "http://127.0.0.1:18990/api/docs/agents"
                ) as r:
                    docs = await r.json()
                    assert "ai-chat-completions" in docs
        finally:
            await server.stop()

    run_async(main())


# ---------------------------------------------------------------------------
# diagram + docs
# ---------------------------------------------------------------------------


def test_mermaid_diagram():
    app = build_application_from_directory(
        EXAMPLES / "applications" / "chat-completions",
        instance=EXAMPLES / "instances" / "memory.yaml",
    )
    plan = ApplicationDeployer().create_implementation("app", app)
    diagram = mermaid_diagram(plan)
    assert diagram.startswith("flowchart LR")
    assert 'T_questions_topic[("questions-topic")]' in diagram
    assert "gateway: user-input (produce)" in diagram
    assert "-->" in diagram


def test_docs_generation():
    docs = agent_docs()
    assert docs["ai-chat-completions"]["component-type"] == "processor"
    assert "model" in docs["ai-chat-completions"]["configuration"]
    assert docs["webcrawler"]["component-type"] == "source"
    md = render_markdown()
    assert "## `compute-ai-embeddings`" in md
    assert "| `batch-size` |" in md
    assert render_json().startswith("{")


def test_committed_agent_reference_is_fresh():
    """docs/AGENTS.md is a committed artifact of `cli docs agents` — it
    must match the generator, or the reference drifts from the code."""
    from pathlib import Path

    committed = (
        Path(__file__).resolve().parent.parent / "docs" / "AGENTS.md"
    ).read_text()
    assert committed == render_markdown() + "\n" or committed == render_markdown()
