"""Device attribution plane tests (serving/attribution.py).

Layers covered: the analytical cost model pinned against hand-computed
bytes/FLOPs at the llama3-8b shape, the memory ledger's
sums-to-detected-limit invariant (unit and on a live CPU engine), the
``/attribution``/``/memory`` pod endpoints and their acceptance shape
(≥ 3 registered programs with expected bytes, measured p50, and
achieved-vs-expected), the control-plane scoping, the
``tools/trace_attrib.py`` golden fixture, the ``tools/perf_diff.py``
regression sentry (an injected 30% step-time regression flags exactly
that metric; identical rollups stay quiet), and the ``engine_top``
attribution panels + degraded-program flag."""

import asyncio
import importlib.util
import json
import socket
from pathlib import Path

import aiohttp
import pytest

from langstream_tpu.serving.attribution import (
    ModelShape,
    ProgramLedger,
    decode_cost,
    memory_ledger,
    prefill_cost,
    verify_cost,
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _load_tool(name: str):
    path = Path(__file__).resolve().parents[1] / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


# --------------------------------------------------------------------------
# cost model: pinned against hand-computed bytes/FLOPs (llama3-8b shape)
# --------------------------------------------------------------------------

# Llama-3-8B: 32L / 4096H / 32 heads / GQA-8 / 128 head-dim / 14336 FFN /
# 128256 vocab. Parameter count by hand:
#   per layer: wq 4096*4096 + wk,wv 2*4096*1024 + wo 4096*4096
#              + 3*4096*14336 (gate/up/down) + 2*4096 (norms)
#            = 16777216 + 8388608 + 16777216 + 176160768 + 8192
#            = 218111, *wait — spelled out below in numbers.
_P_LAYER = (
    4096 * 4096 + 2 * 4096 * 1024 + 4096 * 4096 + 3 * 4096 * 14336 + 2 * 4096
)
_N_PARAMS = 32 * _P_LAYER + 2 * 128256 * 4096 + 4096  # embed + head + norm

_SHAPE_8B_INT8 = ModelShape(
    layers=32,
    hidden=4096,
    heads=32,
    kv_heads=8,
    head_dim=128,
    intermediate=14336,
    vocab=128256,
    weight_bytes=_N_PARAMS,       # int8: 1 byte/param
    param_count=_N_PARAMS,
    kv_row_bytes=128 + 4,          # int8 KV row + f32 scale
    act_bytes=2,                   # bf16 activations
)


def test_param_count_hand_check_matches_model_helper():
    from langstream_tpu.models.llama import LlamaConfig, param_count

    assert param_count(LlamaConfig.llama3_8b()) == _N_PARAMS
    assert _N_PARAMS == 8_030_261_248  # ~8.03B, the published shape


def test_decode_cost_pinned_to_hand_computed_bytes():
    slots, window, k = 64, 512, 32
    cost = decode_cost(
        _SHAPE_8B_INT8, slots=slots, window_rows=window, k_steps=k,
        hbm_gbps=819.0,
    )
    # weights stream once per fused step
    assert cost.weight_bytes == k * _N_PARAMS
    # KV window read: K and V, every layer, every slot, int8 rows
    kv_row = 8 * (128 + 4) * 2
    assert cost.kv_read_bytes == k * 32 * slots * window * kv_row
    # one new row per slot per step
    assert cost.kv_write_bytes == k * 32 * slots * kv_row
    # activations: residual+norm (2H) + FFN intermediate per layer, plus
    # the logits row, bf16
    assert cost.act_bytes == (
        k * slots * 2 * (32 * (2 * 4096 + 14336) + 128256)
    )
    # FLOPs: 2*params per token plus the attention window sweep
    assert cost.flops == k * slots * (
        2 * _N_PARAMS + 4 * 32 * 128 * window
    )
    assert cost.total_bytes == (
        cost.weight_bytes + cost.kv_read_bytes + cost.kv_write_bytes
        + cost.act_bytes
    )
    # expected time is the HBM floor at the assumed bandwidth
    assert cost.expected_ms() == pytest.approx(
        cost.total_bytes / (819.0 * 1e9) * 1e3
    )
    # sanity: the dominant term at this shape is weight streaming — the
    # per-step floor must sit in the ~10ms/step regime BENCH_NOTES pins
    assert 8.0 < cost.expected_ms() / k < 16.0


def test_prefill_and_verify_costs_hand_computed():
    kv_row = 8 * (128 + 4) * 2
    cost = prefill_cost(
        _SHAPE_8B_INT8, rows=4, tokens_per_row=256, prefix_rows=0,
        hbm_gbps=819.0,
    )
    assert cost.kind == "prefill"
    assert cost.weight_bytes == _N_PARAMS  # once per dispatch, not per token
    assert cost.kv_read_bytes == 0
    assert cost.kv_write_bytes == 32 * 4 * 256 * kv_row
    cont = prefill_cost(
        _SHAPE_8B_INT8, rows=4, tokens_per_row=64, prefix_rows=512,
        hbm_gbps=819.0,
    )
    assert cont.kind == "prefill-continue"
    assert cont.kv_read_bytes == 32 * 4 * 512 * kv_row
    ver = verify_cost(
        _SHAPE_8B_INT8, slots=64, window_rows=512, drafts=4, hbm_gbps=819.0,
    )
    assert ver.kind == "verify"
    assert ver.kv_write_bytes == 32 * 64 * 5 * kv_row
    assert ver.tokens == 64 * 5


# --------------------------------------------------------------------------
# ledger units
# --------------------------------------------------------------------------


def test_program_ledger_report_and_census():
    ledger = ProgramLedger(window=4)
    cost = decode_cost(
        _SHAPE_8B_INT8, slots=4, window_rows=128, k_steps=8, hbm_gbps=819.0
    )
    ledger.register("decode:w128:k8:greedy", cost)
    ledger.register("decode:w128:k8:greedy", cost)  # idempotent
    for ms in (10.0, 20.0, 30.0):
        ledger.observe("decode:w128:k8:greedy", ms / 1000.0)
    ledger.observe("never-registered", 1.0)  # dropped, never raises
    report = ledger.report()
    assert len(report) == 1
    entry = report[0]
    assert entry["dispatches"] == 3
    assert entry["measured_ms_p50"] == pytest.approx(20.0)
    assert entry["expected"]["total_bytes"] == cost.total_bytes
    assert entry["achieved_vs_expected"] == pytest.approx(
        cost.expected_ms() / 20.0, rel=1e-3
    )
    assert ledger.census() == {"decode:w128:k8:greedy": 3}


def test_memory_ledger_slack_identity_and_sub_owner():
    out = memory_ledger(
        weights_bytes=1000,
        kv_pool_bytes=500,
        prefix_blocks=3,
        bytes_per_block=50,
        sampler_bytes=20,
        tables_bytes=30,
        limit_bytes=2000,
        limit_source="table:v5e",
    )
    owners = out["hbm_bytes_by_owner"]
    assert out["accounted_bytes"] == 1550
    assert owners["slack"] == 450
    # owner sum (slack included) equals the detected limit EXACTLY
    assert sum(owners.values()) == 2000
    # prefix blocks are a sub-owner of the pool, never added to the sum
    assert out["kv_pool_prefix_bytes"] == 150
    # unknown capacity: slack is honest-None, not zero
    unknown = memory_ledger(
        weights_bytes=1, kv_pool_bytes=1, prefix_blocks=0,
        bytes_per_block=0, sampler_bytes=0, tables_bytes=0,
        limit_bytes=None, limit_source="unknown",
    )
    assert unknown["slack_bytes"] is None
    assert "slack" not in unknown["hbm_bytes_by_owner"]


# --------------------------------------------------------------------------
# live CPU engine: the /attribution acceptance shape
# --------------------------------------------------------------------------


def test_live_engine_attribution_and_memory_invariant(run_async, monkeypatch):
    """≥ 3 distinct registered programs, each with expected bytes, a
    measured p50, and an achieved-vs-expected ratio; the memory ledger's
    owner sum equals the (table-fallback) capacity within the reported
    slack; flight samples carry the program key."""
    import langstream_tpu.serving.engine as engine_mod
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    # a synthetic capacity table hit: CPU exposes no allocator limit,
    # and the invariant needs a known denominator (the engine resolves
    # capacity once at construction)
    limit = 1 << 30
    monkeypatch.setattr(
        engine_mod, "detect_hbm_capacity", lambda: (limit, "table:test")
    )

    async def main():
        engine = TpuServingEngine.get_or_create(
            ServingConfig(
                model="tiny", slots=4, max_seq_len=128, kv_layout="paged",
                kv_block_size=16, decode_chunk=4, decode_chunk_light=0,
            )
        )
        try:
            prompts = ["attribution probe " * n for n in (1, 2, 6, 10)]
            await asyncio.gather(
                *(engine.generate(p, {"max-tokens": 12}) for p in prompts)
            )
            section = engine.stats()["attribution"]
            programs = section["programs"]
            assert len(programs) >= 3, [p["program"] for p in programs]
            kinds = {p["kind"] for p in programs}
            assert "decode" in kinds and (
                "prefill" in kinds or "prefill-continue" in kinds
            )
            for program in programs:
                assert program["expected"]["total_bytes"] > 0
                assert program["dispatches"] >= 1
                assert program["measured_ms_p50"] is not None
                assert program["achieved_vs_expected"] is not None
            # memory invariant: owner sum + slack == capacity, exactly
            memory = section["memory"]
            owners = memory["hbm_bytes_by_owner"]
            assert memory["limit_source"] == "table:test"
            assert sum(owners.values()) == limit
            assert owners["slack"] == memory["slack_bytes"]
            assert memory["slack_bytes"] >= 0  # tiny model fits easily
            assert owners["weights"] > 0 and owners["kv-pool"] > 0
            assert memory["kv_pool_prefix_bytes"] <= owners["kv-pool"]
            # flight samples are keyed by program id
            keyed = [
                s for s in engine.flight.recent(0)
                if s["phase"] != "stall" and s.get("program")
            ]
            assert keyed, "dispatch samples carry the program key"
            assert any(
                s["program"].startswith("decode:") for s in keyed
            )
        finally:
            await engine.close()

    run_async(main())


def test_pod_serves_attribution_and_memory(run_async, monkeypatch):
    from langstream_tpu.runtime.pod import _serve_info
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def main():
        engine = TpuServingEngine.get_or_create(
            ServingConfig(model="tiny", slots=2, max_seq_len=64, decode_chunk=4)
        )
        port = free_port()
        monkeypatch.setenv("LS_HTTP_PORT", str(port))
        server = await _serve_info(None)
        try:
            await engine.generate("pod attribution probe", {"max-tokens": 4})
            async with aiohttp.ClientSession() as session:
                base = f"http://127.0.0.1:{port}"
                async with session.get(f"{base}/attribution") as resp:
                    assert resp.status == 200
                    assert resp.headers["Content-Type"] == "application/json"
                    report = await resp.json()
                entry = next(e for e in report if e["model"] == "tiny")
                assert entry["programs"]
                assert entry["memory"]["hbm_bytes_by_owner"]["weights"] > 0
                async with session.get(f"{base}/memory") as resp:
                    assert resp.status == 200
                    memory = await resp.json()
                entry = next(e for e in memory if e["model"] == "tiny")
                assert "programs" not in entry  # ledger-only view
                assert entry["memory"]["accounted_bytes"] > 0
        finally:
            server.close()
            await engine.close()

    run_async(main())


def test_dev_attribution_scoped_to_declared_models(monkeypatch):
    """Mirror of the /flight scoping: one tenant's attribution route
    must not read another's device economics off the process-global
    engine map."""
    import langstream_tpu.serving.engine as engine_mod
    from langstream_tpu.controlplane.server import LocalComputeRuntime

    monkeypatch.setattr(
        engine_mod,
        "attribution_report",
        lambda: [
            {"model": "tiny", "programs": [], "memory": {}},
            {"model": "llama-1b", "programs": [], "memory": {}},
        ],
    )

    class _Resource:
        def __init__(self, rtype, configuration):
            self.type = rtype
            self.configuration = configuration

    def runner_with(resources):
        class _App:
            pass

        class _Runner:
            pass

        _Runner.application = _App()
        _Runner.application.resources = resources
        return _Runner()

    compute = LocalComputeRuntime()
    compute.runners[("t", "app")] = runner_with(
        {"tpu": _Resource("tpu-serving-configuration", {"model": "tiny"})}
    )
    compute.runners[("t", "plain")] = runner_with({})
    assert [e["model"] for e in compute.attribution("t", "app")] == ["tiny"]
    assert compute.attribution("t", "plain") == []
    assert compute.attribution("t", "ghost") == []


# --------------------------------------------------------------------------
# tools/trace_attrib.py: golden fixture
# --------------------------------------------------------------------------

_FIXTURE = (
    Path(__file__).resolve().parent / "fixtures"
    / "mini_trace.trace.json.gz"
)


def test_trace_attrib_golden_fixture():
    trace_attrib = _load_tool("trace_attrib")
    agg = trace_attrib.bucket_events(
        trace_attrib._load_trace(str(_FIXTURE))
    )
    rep = trace_attrib.report(agg)
    buckets = rep["buckets"]
    # hand-pinned against the checked-in fixture's event durations (µs)
    assert rep["total_device_ms"] == pytest.approx(8.6)
    assert buckets["attention"]["device_ms"] == pytest.approx(3.0)
    assert buckets["mlp"]["device_ms"] == pytest.approx(4.0)
    assert buckets["collectives"]["device_ms"] == pytest.approx(0.5)
    assert buckets["sampling"]["device_ms"] == pytest.approx(0.75)
    assert buckets["copies"]["device_ms"] == pytest.approx(0.25)
    assert buckets["other"]["device_ms"] == pytest.approx(0.1)
    # the host lane (pid 2, a 100s python_sleep) is excluded by the
    # device-pid filter — its inclusion would swamp every bucket
    assert buckets["attention"]["events"] == 2
    top = buckets["mlp"]["top_ops"]
    assert top[0]["name"] == "dot_general.7"
    # text renderer smoke
    assert "attention" in trace_attrib.render(rep)


def test_trace_attrib_cli_on_fixture(capsys):
    trace_attrib = _load_tool("trace_attrib")
    assert trace_attrib.main([str(_FIXTURE), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["total_device_ms"] == pytest.approx(8.6)
    assert trace_attrib.main(["/nonexistent/dir"]) == 2


# --------------------------------------------------------------------------
# tools/perf_diff.py: the regression sentry
# --------------------------------------------------------------------------


def _bench_record(step_ms: float) -> dict:
    return {
        "schema": 2,
        "metric": "tok/s/chip llama3-8b int8-weights decode",
        "value": 1500.0,
        "unit": "tok/s/chip",
        "vs_baseline": 0.75,
        "detail": {
            "paged": {
                "tok_s": 1500.0,
                "mean_step_ms": 40.0,
                "overlap_ratio": 0.5,
                "roofline": {"hbm_utilization": 0.291},
                "flight": {
                    "step_ms_p50": step_ms,
                    "recompile_count": 4,
                    "totals": {
                        "wall_ms": 1000.0,
                        "device_ms": 800.0,
                        "host_ms": 150.0,
                        "stall_ms": 50.0,
                        "steps_by_phase": {"decode": 20},
                    },
                },
                "programs": {"decode:w512:k32:greedy": 100},
            },
            "speculative": {"uplift": 1.2, "accepted_per_step": 3.0},
            "gateway_ttft_p50_s": 0.6,
        },
    }


def test_perf_diff_flags_exactly_the_injected_step_regression(tmp_path):
    perf_diff = _load_tool("perf_diff")
    base = tmp_path / "r05.json"
    new = tmp_path / "r06.json"
    base.write_text(json.dumps(_bench_record(40.0)))
    new.write_text(json.dumps(_bench_record(52.0)))  # +30% step time
    results, any_regression = perf_diff.diff_files([str(base), str(new)])
    assert any_regression
    (_b, _n, result), = results
    assert [r["metric"] for r in result["regressions"]] == ["step_ms_p50"]
    assert result["regressions"][0]["change"] == pytest.approx(0.3)
    assert result["improvements"] == []


def test_perf_diff_quiet_on_identical_rollups(tmp_path):
    perf_diff = _load_tool("perf_diff")
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_record(40.0)))
    b.write_text(json.dumps(_bench_record(40.0)))
    results, any_regression = perf_diff.diff_files([str(a), str(b)])
    assert not any_regression
    (_b, _n, result), = results
    assert result["regressions"] == []
    assert result["improvements"] == []
    assert result["notes"] == []


def test_perf_diff_direction_and_census_notes(tmp_path):
    perf_diff = _load_tool("perf_diff")
    base = _bench_record(40.0)
    new = _bench_record(40.0)
    # overlap collapse (lower is worse) + a census change
    new["detail"]["paged"]["overlap_ratio"] = 0.1
    new["detail"]["paged"]["programs"] = {"decode:w1024:k32:greedy": 90}
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(new))
    results, any_regression = perf_diff.diff_files([str(a), str(b)])
    (_b, _n, result), = results
    assert any_regression
    assert [r["metric"] for r in result["regressions"]] == ["overlap_ratio"]
    assert any("census" in note for note in result["notes"])
    # a faster step time is an improvement, never a regression
    faster = _bench_record(20.0)
    c = tmp_path / "c.json"
    c.write_text(json.dumps(faster))
    results, any_regression = perf_diff.diff_files([str(a), str(c)])
    (_b, _n, result), = results
    assert not any_regression
    assert [i["metric"] for i in result["improvements"]] == ["step_ms_p50"]


def test_perf_diff_reads_flight_dumps(tmp_path):
    perf_diff = _load_tool("perf_diff")

    def dump(step_ms):
        return [{
            "model": "tiny",
            "summary": {
                "totals": {"device_ms": 100.0, "recompiles": 2},
                "window": {"step_ms_p50": step_ms, "overlap_ratio": 0.4},
            },
        }]

    a = tmp_path / "old.json"
    b = tmp_path / "new.json"
    a.write_text(json.dumps(dump(10.0)))
    b.write_text(json.dumps(dump(14.0)))
    results, any_regression = perf_diff.diff_files([str(a), str(b)])
    assert any_regression
    (_b, _n, result), = results
    assert [r["metric"] for r in result["regressions"]] == ["step_ms_p50"]


def test_perf_diff_watches_analyzer_self_stats(tmp_path):
    """The bench record carries graftcheck self-stats (bench.py
    _analyzer_stats): a slower analyzer or suppression creep is a
    declared regression direction, not ignored drift."""
    perf_diff = _load_tool("perf_diff")
    assert perf_diff.METRICS["analyzer_wall_s"] == "up"
    assert perf_diff.METRICS["analyzer_suppressions"] == "up"
    base = _bench_record(40.0)
    new = _bench_record(40.0)
    base["detail"]["analyzer"] = {
        "analyzer_wall_s": 10.0, "suppressions": 10, "violations": 0,
    }
    new["detail"]["analyzer"] = {
        "analyzer_wall_s": 15.0, "suppressions": 10, "violations": 0,
    }
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(new))
    results, any_regression = perf_diff.diff_files([str(a), str(b)])
    (_b, _n, result), = results
    assert any_regression
    assert [r["metric"] for r in result["regressions"]] == [
        "analyzer_wall_s"
    ]


# --------------------------------------------------------------------------
# engine_top: attribution panels + degraded-program flag + cross-run diff
# --------------------------------------------------------------------------


def _attrib_entry(ratios: list[float]) -> dict:
    return {
        "model": "llama3-8b",
        "slots": 64,
        "programs": [
            {
                "program": f"decode:w{512 * (i + 1)}:k32:greedy",
                "kind": "decode",
                "dispatches": 20,
                "device_s_total": 1.0,
                "expected": {"total_bytes": 10**9, "expected_ms": 12.0},
                "measured_ms_p50": 40.0,
                "measured_ms_p95": 50.0,
                "achieved_vs_expected": ratio,
            }
            for i, ratio in enumerate(ratios)
        ],
        "memory": {
            "hbm_bytes_by_owner": {
                "weights": 8 * 2**30,
                "kv-pool": 4 * 2**30,
                "sampler-state": 1024,
                "device-lru": 2048,
                "slack": 4 * 2**30 - 3072,
            },
            "accounted_bytes": 12 * 2**30 + 3072,
            "kv_pool_prefix_bytes": 2**20,
            "limit_bytes": 16 * 2**30,
            "limit_source": "table:v5e",
            "slack_bytes": 4 * 2**30 - 3072,
        },
    }


def _load_engine_top():
    return _load_tool("engine_top")


def test_engine_top_renders_attribution_payload():
    engine_top = _load_engine_top()
    frame = engine_top.render([_attrib_entry([0.3, 0.31, 0.29])])
    assert "hbm" in frame and "table:v5e" in frame
    assert "decode:w512:k32:greedy" in frame
    assert "weights" in frame and "slack" in frame


def test_engine_top_analyze_flags_degraded_program():
    engine_top = _load_engine_top()
    out = engine_top.analyze([_attrib_entry([0.30, 0.28, 0.32, 0.05])])
    assert "program attribution gap" in out
    assert "decode:w2048:k32:greedy" in out
    # a uniform dump stays quiet
    quiet = engine_top.analyze([_attrib_entry([0.30, 0.28, 0.32])])
    assert "program attribution gap" not in quiet
    assert "no attribution anomalies flagged" in quiet


def test_engine_top_analyze_cross_run_diff(tmp_path, capsys):
    engine_top = _load_engine_top()
    a = tmp_path / "r05.json"
    b = tmp_path / "r06.json"
    a.write_text(json.dumps(_bench_record(40.0)))
    b.write_text(json.dumps(_bench_record(52.0)))
    rc = engine_top.main(["--analyze", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 1  # regression flagged
    assert "REGRESSION step_ms_p50" in out
    # identical rounds: analyze both, diff quiet, rc 0
    c = tmp_path / "r07.json"
    c.write_text(json.dumps(_bench_record(52.0)))
    rc = engine_top.main(["--analyze", str(b), str(c)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no regressions" in out
