"""JWT validation, gateway auth providers, admin token filter, quotas."""

from __future__ import annotations

import time

import pytest

from langstream_tpu.auth.jwt import (
    JwtError,
    JwtValidator,
    decode_unverified,
    encode_hs256,
)


# ---------------------------------------------------------------------------
# HS256
# ---------------------------------------------------------------------------


def test_hs256_roundtrip_and_claims():
    token = encode_hs256({"sub": "alice", "role": "admin"}, "s3cret")
    header, claims = decode_unverified(token)
    assert header["alg"] == "HS256" and claims["sub"] == "alice"
    out = JwtValidator(secret="s3cret").validate(token)
    assert out["sub"] == "alice" and out["role"] == "admin"


def test_hs256_rejects_bad_signature_and_expiry():
    v = JwtValidator(secret="right")
    with pytest.raises(JwtError, match="signature"):
        v.validate(encode_hs256({"sub": "x"}, "wrong"))
    with pytest.raises(JwtError, match="expired"):
        v.validate(encode_hs256({"exp": time.time() - 3600}, "right"))
    with pytest.raises(JwtError, match="not yet valid"):
        v.validate(encode_hs256({"nbf": time.time() + 3600}, "right"))


def test_audience_and_issuer_checks():
    v = JwtValidator(secret="s", audience="my-api", issuer="me")
    good = encode_hs256({"aud": ["other", "my-api"], "iss": "me"}, "s")
    assert v.validate(good)["iss"] == "me"
    with pytest.raises(JwtError, match="audience"):
        v.validate(encode_hs256({"aud": "other", "iss": "me"}, "s"))
    with pytest.raises(JwtError, match="issuer"):
        v.validate(encode_hs256({"aud": "my-api", "iss": "them"}, "s"))


# ---------------------------------------------------------------------------
# RS256 (local keypair via cryptography)
# ---------------------------------------------------------------------------


def _rs256_token_and_jwk(claims: dict) -> tuple[str, dict]:
    import base64
    import json

    # RS256 (mint and verify) rides the ``cryptography`` primitives —
    # skip (not fail) on images without the package; HS256 coverage above
    # is pure stdlib and always runs
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    numbers = key.public_key().public_numbers()

    def b64url(data: bytes) -> str:
        return base64.urlsafe_b64encode(data).rstrip(b"=").decode()

    def int_b64(i: int) -> str:
        length = (i.bit_length() + 7) // 8
        return b64url(i.to_bytes(length, "big"))

    header = b64url(json.dumps({"alg": "RS256", "kid": "k1"}).encode())
    payload = b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    token = f"{header}.{payload}.{b64url(sig)}"
    jwk = {"kty": "RSA", "kid": "k1", "n": int_b64(numbers.n), "e": int_b64(numbers.e)}
    return token, jwk


def test_rs256_with_public_jwk():
    token, jwk = _rs256_token_and_jwk({"sub": "svc"})
    assert JwtValidator(public_jwk=jwk).validate(token)["sub"] == "svc"
    # tampered payload fails
    head, payload, sig = token.split(".")
    bad = f"{head}.{payload[:-2]}AA.{sig}"
    with pytest.raises(JwtError):
        JwtValidator(public_jwk=jwk).validate(bad)


def test_jwks_host_allowlist():
    from langstream_tpu.auth.jwt import JwksCache

    cache = JwksCache(allowed_hosts=["trusted.example.com"])
    with pytest.raises(JwtError, match="allowlist"):
        cache.get("https://evil.example.com/jwks.json")


# ---------------------------------------------------------------------------
# gateway providers
# ---------------------------------------------------------------------------


def test_gateway_jwt_provider(run_async):
    from langstream_tpu.gateway.auth import (
        AuthenticationException,
        get_auth_provider,
    )

    async def main():
        provider = get_auth_provider("jwt", {"secret": "gw-secret"})
        claims = await provider.authenticate(
            encode_hs256({"sub": "user-1"}, "gw-secret")
        )
        assert claims["subject"] == "user-1"
        with pytest.raises(AuthenticationException):
            await provider.authenticate("not-a-token")
        with pytest.raises(AuthenticationException):
            await provider.authenticate(None)

    run_async(main())


def test_google_github_gate_cleanly(run_async):
    """Offline: the providers must raise AuthenticationException, not hang
    or crash with an unrelated error."""
    from langstream_tpu.gateway.auth import (
        AuthenticationException,
        get_auth_provider,
    )

    async def main():
        google = get_auth_provider("google", {"clientId": "cid"})
        with pytest.raises(AuthenticationException):
            await google.authenticate("fake-id-token")
        github = get_auth_provider("github", {})
        with pytest.raises(AuthenticationException):
            await github.authenticate("gho_fake")

    run_async(main())


# ---------------------------------------------------------------------------
# control plane: admin filter + quotas
# ---------------------------------------------------------------------------

PIPELINE = """
topics:
  - name: "in-t"
    creation-mode: create-if-not-exists
pipeline:
  - name: "noop"
    type: "compute"
    input: "in-t"
    resources:
      parallelism: {par}
    configuration:
      fields: []
"""

INSTANCE = "instance:\n  streamingCluster:\n    type: memory\n"


def test_admin_token_filter(run_async):
    import aiohttp

    from langstream_tpu.controlplane.server import ControlPlaneServer

    async def main():
        server = ControlPlaneServer(
            port=18991, admin_auth={"secret": "admin-secret"}
        )
        await server.start()
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(
                    "http://127.0.0.1:18991/api/tenants"
                ) as r:
                    assert r.status == 401
                token = encode_hs256({"sub": "admin"}, "admin-secret")
                async with session.get(
                    "http://127.0.0.1:18991/api/tenants",
                    headers={"Authorization": f"Bearer {token}"},
                ) as r:
                    assert r.status == 200
                bad = encode_hs256({"sub": "admin"}, "other")
                async with session.get(
                    "http://127.0.0.1:18991/api/tenants",
                    headers={"Authorization": f"Bearer {bad}"},
                ) as r:
                    assert r.status == 401
        finally:
            await server.stop()

    run_async(main())


def test_tenant_unit_quota(run_async):
    import aiohttp

    from langstream_tpu.controlplane.server import ControlPlaneServer

    async def main():
        server = ControlPlaneServer(port=18992)
        server.store.put_tenant("q", {"max-units": 3})
        await server.start()
        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    "http://127.0.0.1:18992/api/applications/q/app1",
                    json={
                        "files": {"pipeline.yaml": PIPELINE.format(par=2)},
                        "instance": INSTANCE,
                    },
                ) as r:
                    body = await r.json()
                    assert r.status == 200, body
                    assert body["units"] == 2
                # 2 units used; another 2 exceeds the 3-unit quota
                async with session.post(
                    "http://127.0.0.1:18992/api/applications/q/app2",
                    json={
                        "files": {"pipeline.yaml": PIPELINE.format(par=2)},
                        "instance": INSTANCE,
                    },
                ) as r:
                    assert r.status == 409
                    assert "quota" in (await r.text())
                # 1 unit fits
                async with session.post(
                    "http://127.0.0.1:18992/api/applications/q/app3",
                    json={
                        "files": {"pipeline.yaml": PIPELINE.format(par=1)},
                        "instance": INSTANCE,
                    },
                ) as r:
                    assert r.status == 200
        finally:
            await server.stop()

    run_async(main())


def test_google_provider_requires_client_id(run_async):
    """A missing clientId would silently disable the audience check and
    accept any OAuth client's tokens — must refuse to construct instead."""
    from langstream_tpu.gateway.auth import (
        AuthenticationException,
        get_auth_provider,
    )

    async def main():
        with pytest.raises(AuthenticationException, match="clientId"):
            get_auth_provider("google", {})

    run_async(main())


def test_non_numeric_exp_nbf_raise_jwt_error():
    """Garbage exp/nbf in a validly signed token must map to JwtError (→401),
    not leak TypeError/ValueError (→500)."""
    v = JwtValidator(secret="s")
    with pytest.raises(JwtError, match="exp/nbf"):
        v.validate(encode_hs256({"exp": "soon"}, "s"))
    with pytest.raises(JwtError, match="exp/nbf"):
        v.validate(encode_hs256({"nbf": None}, "s"))
    # float() accepts "NaN"/"Infinity" — those would never expire
    with pytest.raises(JwtError, match="non-finite"):
        v.validate(encode_hs256({"exp": "NaN"}, "s"))
    with pytest.raises(JwtError, match="non-finite"):
        v.validate(encode_hs256({"exp": "Infinity"}, "s"))


def test_gateway_auth_validated_at_deploy_time():
    """A google gateway without clientId must fail deploy validation, not
    surface as per-login 401s."""
    from langstream_tpu.api.application import Gateway
    from langstream_tpu.gateway.auth import validate_gateway_authentication

    bad = Gateway.from_dict(
        {
            "id": "chat",
            "type": "chat",
            "chat-options": {"questions-topic": "q", "answers-topic": "a"},
            "authentication": {"provider": "google", "configuration": {}},
        }
    )
    with pytest.raises(ValueError, match="clientId"):
        validate_gateway_authentication([bad])
    good = Gateway.from_dict(
        {
            "id": "chat",
            "type": "chat",
            "chat-options": {"questions-topic": "q", "answers-topic": "a"},
            "authentication": {
                "provider": "google",
                "configuration": {"clientId": "cid"},
            },
        }
    )
    validate_gateway_authentication([good])


def test_auth_provider_instances_memoized():
    """Per-request provider construction would rebuild validator caches on
    every login; same (name, config) must return the same instance."""
    from langstream_tpu.gateway.auth import get_auth_provider

    a = get_auth_provider("jwt", {"secret": "memo"})
    b = get_auth_provider("jwt", {"secret": "memo"})
    c = get_auth_provider("jwt", {"secret": "other"})
    assert a is b and a is not c
