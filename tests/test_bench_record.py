"""Driver-contract test for ``bench.py``: the end-of-round benchmark must
leave a parseable JSON record as the LAST stdout line — and, since the r4
wedge-proofing, re-emit the record after every phase so a driver kill at any
point still finds one. Guards the record machinery — phase budgets, device
probe short-circuit, engine teardown between phases, os._exit — which
otherwise only runs on the real chip at round end."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest


def _bench_env(tmp_path, **overrides) -> dict:
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_MODEL="tiny",
        BENCH_SLOTS="4",
        BENCH_MAX_SEQ="128",
        BENCH_MAX_TOKENS="8",
        BENCH_DECODE_CHUNK="4",
        BENCH_WARMUP_REQUESTS="2",
        BENCH_REQUESTS="8",
        # decode phase only: the gateway/paged/prefix phases have their own
        # coverage (tools/gateway_bench.py main, tests/test_paged.py) and
        # would triple this test's runtime
        BENCH_GATEWAY="0",
        BENCH_PAGED="0",
        BENCH_PREFIX="0",
        BENCH_KV_INT8="0",
        BENCH_SPEC="0",
        BENCH_QOS="0",
        JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jax_cache"),
    )
    env.update(overrides)
    return env


def _repo() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _records(stdout: str) -> list[dict]:
    out = []
    for line in stdout.splitlines():
        if line.startswith("{"):
            out.append(json.loads(line))
    return out


@pytest.mark.slow
def test_bench_record_last_line_parses(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(_repo(), "bench.py")],
        env=_bench_env(tmp_path),
        capture_output=True,
        text=True,
        timeout=600,
        cwd=_repo(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = _records(proc.stdout)
    # wedge-proofing: the record is emitted after the headline phase AND at
    # the end — every intermediate line must already be a full record
    assert len(records) >= 2, proc.stdout
    for record in records:
        assert record["unit"] == "tok/s/chip"
    record = records[-1]
    assert record["value"] > 0
    # vs_baseline is rounded to 3 decimals in the record
    assert record["vs_baseline"] == pytest.approx(
        record["value"] / 2000.0, abs=5e-4
    )
    detail = record["detail"]
    assert detail["dense"]["tok_s"] == record["value"]
    assert "roofline" in detail["dense"]
    # CPU run: the device probe must not have failed the record
    assert detail["dense"].get("error") is None
    assert "device_probe" not in detail


@pytest.mark.slow
def test_bench_probe_failure_emits_record_immediately(tmp_path):
    """A wedged device must still leave a parseable record (round-3 failure
    mode: rc:124, parsed:null). The probe is forced to fail via a tiny
    timeout it cannot meet; the degraded CPU pass is skipped to keep the
    test fast."""
    env = _bench_env(
        tmp_path,
        BENCH_DEGRADED="1",  # reuse the no-recursion guard to skip the pass
        BENCH_TOTAL_TIMEOUT_S="240",
    )
    repo = _repo()
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import bench; bench._probe_device = lambda *a, **k: "
            "'forced wedge (test)'; bench.main()",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
        cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = _records(proc.stdout)
    assert records, proc.stdout
    record = records[-1]
    assert record["value"] == 0.0
    assert record["detail"]["device_probe"] == "forced wedge (test)"
    # the dead-chip record must never masquerade as a chip number
    assert record["vs_baseline"] == 0.0
