"""Driver-contract test for ``bench.py``: the end-of-round benchmark must
print exactly one JSON line with the fields the driver records, even on a
CPU-only machine (tiny model smoke shape). Guards the record machinery —
phase budgets, device probe, engine teardown between phases, os._exit —
which otherwise only runs on the real chip at round end."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_bench_prints_one_json_record(tmp_path):
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_MODEL="tiny",
        BENCH_SLOTS="4",
        BENCH_MAX_SEQ="128",
        BENCH_MAX_TOKENS="8",
        BENCH_DECODE_CHUNK="4",
        BENCH_WARMUP_REQUESTS="2",
        BENCH_REQUESTS="8",
        # decode phase only: the gateway/paged/prefix phases have their own
        # coverage (tools/gateway_bench.py main, tests/test_paged.py) and
        # would triple this test's runtime
        BENCH_GATEWAY="0",
        BENCH_PAGED="0",
        BENCH_PREFIX="0",
        JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jax_cache"),
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    json_lines = [
        line for line in proc.stdout.splitlines() if line.startswith("{")
    ]
    assert len(json_lines) == 1, proc.stdout
    record = json.loads(json_lines[0])
    assert record["unit"] == "tok/s/chip"
    assert record["value"] > 0
    # vs_baseline is rounded to 3 decimals in the record
    assert record["vs_baseline"] == pytest.approx(
        record["value"] / 2000.0, abs=5e-4
    )
    detail = record["detail"]
    assert detail["dense"]["tok_s"] == record["value"]
    assert "roofline" in detail["dense"]
    # CPU run: the device probe must not have failed the record
    assert detail["dense"].get("error") is None
