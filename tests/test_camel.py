"""camel-source: the native timer:/file: subset (agents/camel.py).

Contract parity with the reference CamelSource
(langstream-agent-camel/.../CamelSource.java): component-uri +
component-options merging, key-header, bounded buffer drained by read(),
ack-on-commit driving the file disposition (delete / move to .camel/ /
noop-idempotent).
"""

import asyncio

import pytest

from langstream_tpu.agents.camel import (
    CamelSource,
    merge_component_options,
    parse_camel_uri,
    validate_camel_config,
)


async def _read_some(source, n, timeout=10.0):
    out = []
    deadline = asyncio.get_event_loop().time() + timeout
    while len(out) < n:
        assert asyncio.get_event_loop().time() < deadline, f"only got {out}"
        out.extend(await source.read())
    return out


async def _with_source(config, fn):
    source = CamelSource()
    await source.init(config)
    await source.start()
    try:
        return await fn(source)
    finally:
        await source.close()


def test_uri_parse_and_option_merge():
    uri = merge_component_options("timer:tick?period=100", {"repeatCount": 3})
    assert uri == "timer:tick?period=100&repeatCount=3"
    scheme, path, opts = parse_camel_uri(uri)
    assert (scheme, path) == ("timer", "tick")
    assert opts == {"period": "100", "repeatCount": "3"}
    # file:///abs/path style
    _, path, _ = parse_camel_uri("file:///var/data?delete=true")
    assert path == "/var/data"


def test_validate_rejects_unsupported_scheme_and_missing_uri():
    with pytest.raises(ValueError, match="descope"):
        validate_camel_config({"component-uri": "jms:queue:foo"})
    with pytest.raises(ValueError, match="component-uri"):
        validate_camel_config({})
    validate_camel_config({"component-uri": "timer:t?period=50"})
    validate_camel_config(
        {"component-uri": "file:/tmp/x", "component-options": {"delete": True}}
    )


def test_validate_checks_option_types_at_planning_time():
    """Bad option *values* must fail at planning, not at pod start."""
    with pytest.raises(ValueError, match="period"):
        validate_camel_config({"component-uri": "timer:t?period=abc"})
    with pytest.raises(ValueError, match="regex"):
        validate_camel_config({"component-uri": "file:/tmp/x?include=*broken["})
    with pytest.raises(ValueError, match="max-buffered-records"):
        validate_camel_config(
            {"component-uri": "timer:t", "max-buffered-records": "many"}
        )
    # the route consumes repeatCount with int(); nan/inf/negative never sleep
    with pytest.raises(ValueError, match="repeatCount"):
        validate_camel_config({"component-uri": "timer:t?repeatCount=2.5"})
    for bad in ("timer:t?period=nan", "timer:t?period=inf", "timer:t?delay=-5"):
        with pytest.raises(ValueError):
            validate_camel_config({"component-uri": bad})
    # maxsize<=0 would make asyncio.Queue unbounded — rejected
    with pytest.raises(ValueError, match="max-buffered-records"):
        validate_camel_config(
            {"component-uri": "timer:t", "max-buffered-records": 0}
        )
    with pytest.raises(ValueError, match="component-options"):
        validate_camel_config(
            {"component-uri": "timer:t", "component-options": "delete=true"}
        )


def test_route_crash_surfaces_from_read(run_async):
    """An exception inside the route task must surface from read(), not
    leave the source silently producing nothing forever."""

    async def run():
        source = CamelSource()
        await source.init({"component-uri": "timer:t?period=20&delay=0"})
        source.options["period"] = "not-a-number"  # sabotage the route
        await source.start()
        try:
            with pytest.raises(ValueError):
                for _ in range(20):
                    await source.read()
        finally:
            await source.close()

    run_async(run())


def test_failed_disposition_does_not_duplicate(tmp_path, run_async):
    """If the post-commit move fails, the record must NOT be re-emitted in a
    hot duplicate loop — the idempotent set covers all modes."""
    import os

    (tmp_path / "once.txt").write_text("only once")

    async def scenario(source):
        (record,) = await _read_some(source, 1)
        os.chmod(tmp_path, 0o555)  # .camel/ becomes uncreatable
        try:
            await source.commit([record])  # disposition fails, logged
            await asyncio.sleep(0.15)
            assert await source.read() == []  # no duplicate
        finally:
            os.chmod(tmp_path, 0o755)

    run_async(
        _with_source({"component-uri": f"file:{tmp_path}?delay=30"}, scenario)
    )


def test_timer_component_headers_and_repeat_count(run_async):
    async def scenario(source):
        records = await _read_some(source, 2)
        assert [r.header_map()["CamelTimerCounter"] for r in records[:2]] == [1, 2]
        assert records[0].header_map()["CamelTimerName"] == "tick"
        assert records[0].value is None
        assert records[0].origin.startswith("timer:tick")
        # repeatCount=2: no third record ever arrives
        assert await source.read() == []
        await source.commit(records)
        return records

    run_async(
        _with_source(
            {"component-uri": "timer:tick?period=30&delay=0&repeatCount=2"},
            scenario,
        )
    )


def test_file_component_delete_on_commit(tmp_path, run_async):
    (tmp_path / "a.txt").write_text("alpha")
    (tmp_path / "b.txt").write_text("beta")

    async def scenario(source):
        records = await _read_some(source, 2)
        by_name = {r.header_map()["CamelFileNameOnly"]: r for r in records}
        assert by_name["a.txt"].value == "alpha"
        assert by_name["a.txt"].key == "a.txt"  # key-header
        assert by_name["b.txt"].header_map()["CamelFileLength"] == 4
        # nothing deleted before commit (at-least-once)
        assert (tmp_path / "a.txt").exists()
        await source.commit([by_name["a.txt"]])
        assert not (tmp_path / "a.txt").exists()
        assert (tmp_path / "b.txt").exists()

    run_async(
        _with_source(
            {
                "component-uri": f"file:{tmp_path}?delete=true&delay=30",
                "key-header": "CamelFileNameOnly",
            },
            scenario,
        )
    )


def test_file_component_default_moves_to_camel_dir(tmp_path, run_async):
    (tmp_path / "doc.txt").write_text("payload")

    async def scenario(source):
        (record,) = await _read_some(source, 1)
        await source.commit([record])
        assert not (tmp_path / "doc.txt").exists()
        assert (tmp_path / ".camel" / "doc.txt").read_text() == "payload"
        # the .camel/ dir is never re-crawled
        await asyncio.sleep(0.1)
        assert await source.read() == []

    run_async(
        _with_source({"component-uri": f"file:{tmp_path}?delay=30"}, scenario)
    )


def test_file_component_noop_is_idempotent(tmp_path, run_async):
    (tmp_path / "keep.txt").write_text("stay")

    async def scenario(source):
        (record,) = await _read_some(source, 1)
        await source.commit([record])
        assert (tmp_path / "keep.txt").exists()  # noop leaves it in place
        assert await source.read() == []  # and never re-emits it
        # a rewrite (new mtime) IS re-emitted
        await asyncio.sleep(0.05)
        (tmp_path / "keep.txt").write_text("stay v2")
        (again,) = await _read_some(source, 1)
        assert again.value == "stay v2"

    run_async(
        _with_source({"component-uri": f"file:{tmp_path}?noop=true&delay=30"}, scenario)
    )


def test_file_component_include_filter(tmp_path, run_async):
    (tmp_path / "in.csv").write_text("x")
    (tmp_path / "skip.log").write_text("y")

    async def scenario(source):
        (record,) = await _read_some(source, 1)
        assert record.header_map()["CamelFileNameOnly"] == "in.csv"
        await asyncio.sleep(0.1)
        assert await source.read() == []

    run_async(
        _with_source(
            {"component-uri": f"file:{tmp_path}", "component-options": {
                "include": r".*\.csv", "delay": 30, "noop": "true"}},
            scenario,
        )
    )


def test_permanent_failure_leaves_file(tmp_path, run_async):
    (tmp_path / "bad.txt").write_text("poison")

    async def scenario(source):
        (record,) = await _read_some(source, 1)
        await source.permanent_failure(record, RuntimeError("boom"))
        await source.commit([record])  # commit after failure: no disposition
        assert (tmp_path / "bad.txt").exists()

    run_async(
        _with_source({"component-uri": f"file:{tmp_path}?delete=true&delay=30"}, scenario)
    )
