"""Self-hosted Cassandra lane: the CQL native-protocol v4 client against a
spec-faithful fake server (the role the reference's Cassandra testcontainer
plays — ``CassandraAssetQueryWriteIT``; no broker/cluster binaries exist in
this image, same constraint as kafka/pulsar).

The fake server independently parses every request frame byte-by-byte
(framing, STARTUP, SASL PLAIN auth, PREPARE metadata, EXECUTE value
decoding), so a client-side serialization bug shows up as a server-side
parse failure, not a self-consistent round-trip.
"""

from __future__ import annotations

import asyncio
import re
import struct

import pytest

from langstream_tpu.agents.cassandra_cql import (
    CONSISTENCY,
    OP_AUTH_RESPONSE,
    OP_AUTH_SUCCESS,
    OP_AUTHENTICATE,
    OP_ERROR,
    OP_EXECUTE,
    OP_PREPARE,
    OP_QUERY,
    OP_READY,
    OP_RESULT,
    OP_STARTUP,
    RESULT_PREPARED,
    RESULT_ROWS,
    RESULT_SCHEMA_CHANGE,
    RESULT_VOID,
    CassandraCqlDataSource,
    CqlClient,
    CqlError,
    _Reader,
    _w_bytes,
    _w_int,
    _w_short,
    _w_short_bytes,
    _w_string,
    deserialize_value,
    infer_type_option,
    read_type_option,
    serialize_value,
)

# ---------------------------------------------------------------------------
# type codec unit tests
# ---------------------------------------------------------------------------

_VECTOR_CLS = (
    "org.apache.cassandra.db.marshal.VectorType"
    "(org.apache.cassandra.db.marshal.FloatType, 3)"
)


@pytest.mark.parametrize(
    "opt,value",
    [
        (("varchar",), "héllo"),
        (("ascii",), "plain"),
        (("int",), -42),
        (("bigint",), 1 << 40),
        (("smallint",), -7),
        (("tinyint",), 5),
        (("boolean",), True),
        (("double",), 3.25),
        (("float",), 1.5),
        (("timestamp",), 1721000000000),
        (("varint",), -(1 << 70)),
        (("uuid",), "8be6f1a4-5e5d-4d4e-9f5c-0123456789ab"),
        (("blob",), b"\x00\x01\xff"),
        (("date",), 19000),
        (("list", ("float",)), [1.0, 2.5, -3.0]),
        (("set", ("varchar",)), ["a", "b"]),
        (("map", ("varchar",), ("bigint",)), {"x": 1, "y": 2}),
        (("vector", ("float",), 3), [0.5, 1.0, -2.0]),
    ],
)
def test_type_roundtrip(opt, value):
    assert deserialize_value(opt, serialize_value(opt, value)) == value


def test_null_roundtrip():
    assert serialize_value(("int",), None) is None
    assert deserialize_value(("int",), None) is None


def test_vector_custom_class_parses():
    body = _w_short(0x0000) + _w_string(_VECTOR_CLS)
    assert read_type_option(_Reader(body)) == ("vector", ("float",), 3)


def test_infer_type_option():
    assert infer_type_option(True) == ("boolean",)
    assert infer_type_option(3) == ("bigint",)
    assert infer_type_option(2.5) == ("double",)
    assert infer_type_option("s") == ("varchar",)
    # embeddings convention: float lists ship as list<float>
    assert infer_type_option([0.1, 0.2]) == ("list", ("float",))


# ---------------------------------------------------------------------------
# fake CQL v4 server
# ---------------------------------------------------------------------------


def _w_type_option(opt: tuple) -> bytes:
    scalars = {
        "ascii": 0x0001, "bigint": 0x0002, "blob": 0x0003, "boolean": 0x0004,
        "double": 0x0007, "float": 0x0008, "int": 0x0009,
        "timestamp": 0x000B, "uuid": 0x000C, "varchar": 0x000D,
        "varint": 0x000E, "date": 0x0011, "smallint": 0x0013,
        "tinyint": 0x0014,
    }
    kind = opt[0]
    if kind in scalars:
        return _w_short(scalars[kind])
    if kind == "list":
        return _w_short(0x0020) + _w_type_option(opt[1])
    if kind == "set":
        return _w_short(0x0022) + _w_type_option(opt[1])
    if kind == "map":
        return _w_short(0x0021) + _w_type_option(opt[1]) + _w_type_option(opt[2])
    if kind == "vector":
        cls = (
            "org.apache.cassandra.db.marshal.VectorType"
            f"(org.apache.cassandra.db.marshal.FloatType, {opt[2]})"
        )
        return _w_short(0x0000) + _w_string(cls)
    raise ValueError(opt)


class FakeCassandra:
    """Enough of the v4 server side for the client's full surface: framing,
    STARTUP/auth, QUERY (DDL + SELECT), PREPARE (typed bind metadata from a
    schema), EXECUTE (decodes values with its OWN deserializer and stores /
    serves rows)."""

    def __init__(self, schema: dict[str, tuple], require_auth: bool = False):
        self.schema = schema            # column name -> type option
        self.require_auth = require_auth
        self.rows: dict[object, dict] = {}   # id -> row dict
        self.prepared: dict[bytes, str] = {}
        self.ddl: list[str] = []
        self.auth_token: bytes | None = None
        self._server: asyncio.AbstractServer | None = None
        self.port = 0

    async def start(self):
        self._server = await asyncio.start_server(self._serve, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self):
        self._server.close()
        await self._server.wait_closed()

    def _binds_for(self, cql: str) -> list[tuple[str, tuple]]:
        m = re.match(r"INSERT INTO (\S+) \(([^)]*)\) VALUES", cql)
        if m:
            cols = [c.strip() for c in m.group(2).split(",")]
            return [(c, self.schema[c]) for c in cols]
        m = re.search(r"WHERE (\w+) = \?", cql)
        if m:
            return [(m.group(1), self.schema[m.group(1)])]
        return []

    def _result_rows(self, cols: list[str], rows: list[dict]) -> bytes:
        body = _w_int(RESULT_ROWS)
        body += _w_int(0x0001) + _w_int(len(cols))      # global spec
        body += _w_string("ks") + _w_string("t")
        for c in cols:
            body += _w_string(c) + _w_type_option(self.schema[c])
        body += _w_int(len(rows))
        for row in rows:
            for c in cols:
                body += _w_bytes(serialize_value(self.schema[c], row.get(c)))
        return body

    async def _serve(self, reader, writer):
        authed = not self.require_auth
        try:
            while True:
                header = await reader.readexactly(9)
                ver, _fl, stream, op, length = struct.unpack(">BBhBi", header)
                assert ver == 0x04, f"client must speak v4, got 0x{ver:02x}"
                body = await reader.readexactly(length) if length else b""

                def reply(opcode, payload=b""):
                    writer.write(
                        struct.pack(">BBhBi", 0x84, 0, stream, opcode,
                                    len(payload)) + payload
                    )

                if op == OP_STARTUP:
                    r = _Reader(body)
                    n = r.u16()
                    opts = {r.string(): r.string() for _ in range(n)}
                    assert "CQL_VERSION" in opts
                    if self.require_auth:
                        reply(OP_AUTHENTICATE, _w_string(
                            "org.apache.cassandra.auth.PasswordAuthenticator"
                        ))
                    else:
                        reply(OP_READY)
                elif op == OP_AUTH_RESPONSE:
                    r = _Reader(body)
                    self.auth_token = r.bytes_()
                    if self.auth_token and b"\x00secret" in self.auth_token:
                        authed = True
                        reply(OP_AUTH_SUCCESS, _w_bytes(None))
                    else:
                        reply(OP_ERROR, _w_int(0x0100) + _w_string("bad creds"))
                elif not authed:
                    reply(OP_ERROR, _w_int(0x0100) + _w_string("not authed"))
                elif op == OP_QUERY:
                    r = _Reader(body)
                    cql = r.long_string()
                    r.u16()  # consistency
                    self.ddl.append(cql)
                    reply(OP_RESULT, _w_int(RESULT_SCHEMA_CHANGE)
                          + _w_string("CREATED") + _w_string("TABLE")
                          + _w_string("ks") + _w_string("t"))
                elif op == OP_PREPARE:
                    r = _Reader(body)
                    cql = r.long_string()
                    stmt_id = struct.pack(">I", abs(hash(cql)) & 0xFFFFFFFF)
                    self.prepared[stmt_id] = cql
                    binds = self._binds_for(cql)
                    payload = _w_int(RESULT_PREPARED) + _w_short_bytes(stmt_id)
                    payload += _w_int(0x0001) + _w_int(len(binds))  # flags, cols
                    payload += _w_int(0)                            # pk_count
                    payload += _w_string("ks") + _w_string("t")
                    for name, opt in binds:
                        payload += _w_string(name) + _w_type_option(opt)
                    # result metadata: none
                    payload += _w_int(0x0004) + _w_int(0)
                    reply(OP_RESULT, payload)
                elif op == OP_EXECUTE:
                    r = _Reader(body)
                    stmt_id = r.short_bytes()
                    cql = self.prepared[stmt_id]
                    consistency = r.u16()
                    assert consistency == CONSISTENCY["local-quorum"]
                    flags = r.u8()
                    values = []
                    if flags & 0x01:
                        n = r.u16()
                        values = [r.bytes_() for _ in range(n)]
                    binds = self._binds_for(cql)
                    decoded = [
                        deserialize_value(opt, v)
                        for (name, opt), v in zip(binds, values)
                    ]
                    if cql.startswith("INSERT"):
                        row = {
                            name: val
                            for (name, _), val in zip(binds, decoded)
                        }
                        self.rows[row["id"]] = row
                        reply(OP_RESULT, _w_int(RESULT_VOID))
                    elif cql.startswith("DELETE"):
                        self.rows.pop(decoded[0], None)
                        reply(OP_RESULT, _w_int(RESULT_VOID))
                    elif cql.startswith("SELECT"):
                        hit = self.rows.get(decoded[0])
                        cols = list(self.schema)
                        reply(OP_RESULT, self._result_rows(
                            cols, [hit] if hit else []
                        ))
                    else:
                        reply(OP_ERROR, _w_int(0x2200)
                              + _w_string(f"bad query {cql}"))
                else:
                    reply(OP_ERROR, _w_int(0x000A)
                          + _w_string(f"unsupported opcode {op}"))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()


SCHEMA = {
    "id": ("varchar",),
    "count": ("int",),
    "big": ("bigint",),
    "score": ("double",),
    "vector": ("list", ("float",)),
}


def _resource(port: int, **extra) -> dict:
    return {
        "configuration": {
            "service": "cassandra",
            "contact-points": "127.0.0.1",
            "port": port,
            "keyspace": "ks",
            **extra,
        }
    }


# ---------------------------------------------------------------------------
# end-to-end over a real socket
# ---------------------------------------------------------------------------


def test_datasource_upsert_fetch_delete(run_async):
    async def main():
        fake = FakeCassandra(SCHEMA)
        await fake.start()
        ds = CassandraCqlDataSource(_resource(fake.port))
        try:
            await ds.upsert(
                "docs", "k1", [0.5, 1.0, -2.0],
                {"count": 7, "big": 1 << 40, "score": 2.5},
            )
            # the fake decoded the typed values with its own deserializer
            assert fake.rows["k1"] == {
                "id": "k1", "count": 7, "big": 1 << 40, "score": 2.5,
                "vector": [0.5, 1.0, -2.0],
            }
            rows = await ds.fetch_data(
                "SELECT id, count, big, score, vector FROM ks.docs "
                "WHERE id = ?",
                ["k1"],
            )
            assert rows == [fake.rows["k1"]]
            await ds.delete_item("docs", "k1")
            assert "k1" not in fake.rows
            rows = await ds.fetch_data(
                "SELECT id FROM ks.docs WHERE id = ?", ["k1"]
            )
            assert rows == []
        finally:
            await ds.close()
            await fake.stop()

    run_async(main())


def test_password_auth_plain_token(run_async):
    async def main():
        fake = FakeCassandra(SCHEMA, require_auth=True)
        await fake.start()
        ds = CassandraCqlDataSource(
            _resource(fake.port, username="cassandra", password="secret")
        )
        try:
            await ds.upsert("docs", "a", None, {"count": 1})
            assert fake.auth_token == b"\x00cassandra\x00secret"
        finally:
            await ds.close()
            await fake.stop()

    run_async(main())


def test_bad_credentials_surface_cql_error(run_async):
    async def main():
        fake = FakeCassandra(SCHEMA, require_auth=True)
        await fake.start()
        ds = CassandraCqlDataSource(
            _resource(fake.port, username="u", password="wrong")
        )
        try:
            with pytest.raises((CqlError, ConnectionError), match="bad creds|reachable"):
                await ds.upsert("docs", "a", None, {"count": 1})
        finally:
            await ds.close()
            await fake.stop()

    run_async(main())


def test_asset_managers_run_ddl(run_async):
    from langstream_tpu.agents.assets import AssetManagerRegistry
    from langstream_tpu.api.application import AssetDefinition

    async def main():
        fake = FakeCassandra(SCHEMA)
        await fake.start()
        mgr = AssetManagerRegistry.get("cassandra-table")
        assert mgr is not None
        asset = AssetDefinition(
            id="docs",
            name="docs",
            asset_type="cassandra-table",
            config={
                "datasource": _resource(fake.port),
                "table-name": "docs",
                "keyspace": "ks",
                "create-statements": [
                    "CREATE TABLE IF NOT EXISTS ks.docs (id text PRIMARY KEY)"
                ],
                "delete-statements": ["DROP TABLE IF EXISTS ks.docs"],
            },
        )
        try:
            await mgr.deploy_asset(asset)
            assert any("CREATE TABLE" in d for d in fake.ddl)
            await mgr.delete_asset(asset)
            assert any("DROP TABLE" in d for d in fake.ddl)
        finally:
            await fake.stop()

    run_async(main())


def test_service_routing_split():
    """``cassandra`` is the CQL lane; ``astra`` keeps the JSON Data API —
    no config silently sends HTTP to a CQL-only cluster (r3 weak #5)."""
    from langstream_tpu.agents.astra import AstraVectorDataSource
    from langstream_tpu.agents.vector import resolve_datasource

    resources = {
        "cql": {"type": "datasource", "name": "cql",
                "configuration": {"service": "cassandra",
                                  "contact-points": "10.0.0.1"}},
        "astra": {"type": "datasource", "name": "astra",
                  "configuration": {"service": "astra",
                                    "endpoint": "https://x",
                                    "token": "t"}},
    }
    ds = resolve_datasource("cql", resources)
    assert isinstance(ds, CassandraCqlDataSource)
    ds2 = resolve_datasource("astra", resources)
    assert isinstance(ds2, AstraVectorDataSource)
