"""Unit tests for the graftcheck dataflow layer (analysis/dataflow.py):
CFG construction (branch joins, loop back-edges, try/except/finally,
``with`` spans), reaching definitions, def-use chains, the
use-after-donate path query, and the taint engine. The FLOW rules built
on top are covered by fixtures in test_graftcheck.py — these tests pin
the substrate they all share."""

from __future__ import annotations

import ast
import textwrap

from langstream_tpu.analysis.dataflow import (
    TaintSpec,
    build_cfg,
    def_use_chains,
    flow_index,
    param_refs,
    reaching_definitions,
    reads_before_rebind,
    ref_of,
    run_taint,
)


def _fn(source: str) -> ast.AST:
    # strip the leading blank line so `def` sits on line 1 and the test
    # sources' line numbers match what they assert
    return ast.parse(textwrap.dedent(source).lstrip("\n")).body[0]


def _node_at(cfg, line: int, kind: str = "stmt"):
    for node in cfg.nodes:
        if node.line == line and node.kind == kind:
            return node
    raise AssertionError(f"no {kind} node at line {line}")


def _lines(cfg, idxs) -> set[int]:
    return {cfg.nodes[i].line for i in idxs}


# --------------------------------------------------------------------------
# CFG construction
# --------------------------------------------------------------------------


def test_cfg_if_branches_and_join():
    cfg = build_cfg(_fn("""
        def f(c):
            a = 1
            if c:
                b = 2
            else:
                b = 3
            return b
    """))
    head = _node_at(cfg, 3, "head")
    assert _lines(cfg, head.succs) == {4, 6}  # both branches
    ret = _node_at(cfg, 7)
    assert _lines(cfg, ret.preds) == {4, 6}   # join at the return
    assert ret.succs == [cfg.exit]


def test_cfg_if_without_else_falls_through():
    cfg = build_cfg(_fn("""
        def f(c):
            if c:
                a = 1
            return 0
    """))
    head = _node_at(cfg, 2, "head")
    ret = _node_at(cfg, 4)
    # the test reaches the return both through the body and directly
    assert head.idx in ret.preds
    assert _node_at(cfg, 3).idx in ret.preds


def test_cfg_while_back_edge_break_continue():
    cfg = build_cfg(_fn("""
        def f(n):
            while n:
                if n == 1:
                    break
                if n == 2:
                    continue
                n = step(n)
            return n
    """))
    head = _node_at(cfg, 2, "head")
    body_tail = _node_at(cfg, 7)
    assert head.idx in body_tail.succs          # loop back edge
    brk = _node_at(cfg, 4)
    ret = _node_at(cfg, 8)
    assert ret.idx in brk.succs                 # break -> after loop
    cont = _node_at(cfg, 6)
    assert head.idx in cont.succs               # continue -> head
    assert ret.idx in head.succs                # loop exit


def test_cfg_for_head_writes_target():
    cfg = build_cfg(_fn("""
        def f(items):
            for x in items:
                use(x)
    """))
    head = _node_at(cfg, 2, "head")
    assert "x" in head.writes
    assert "items" in head.reads
    body = _node_at(cfg, 3)
    assert head.idx in body.succs or body.idx in head.succs


def test_cfg_try_except_finally_paths():
    cfg = build_cfg(_fn("""
        def f():
            try:
                a = risky()
                b = 2
            except ValueError:
                c = 3
            finally:
                d = 4
            return d
    """))
    handler = _node_at(cfg, 5, "head")
    # every try-body statement may raise into the handler
    assert {_node_at(cfg, 3).idx, _node_at(cfg, 4).idx} <= set(handler.preds)
    fin = _node_at(cfg, 8)
    # both the normal exit and the handler route through finally
    assert _node_at(cfg, 4).idx in fin.preds
    assert _node_at(cfg, 6).idx in fin.preds
    ret = _node_at(cfg, 9)
    assert fin.idx in ret.preds


def test_cfg_return_edges_to_exit_kills_fallthrough():
    cfg = build_cfg(_fn("""
        def f(c):
            if c:
                return 1
            return 2
    """))
    ret1 = _node_at(cfg, 3)
    assert ret1.succs == [cfg.exit]
    ret2 = _node_at(cfg, 4)
    assert ret1.idx not in ret2.preds


def test_cfg_with_span_binds_optional_vars():
    cfg = build_cfg(_fn("""
        def f(path):
            with open(path) as fh:
                data = fh.read()
            return data
    """))
    head = _node_at(cfg, 2, "head")
    assert "fh" in head.writes
    assert "path" in head.reads


def test_cfg_subscript_store_reads_not_writes_the_ref():
    # self.X[i] = v touches the object X holds; the binding survives —
    # exactly the semantics use-after-donate needs
    cfg = build_cfg(_fn("""
        def f(self, i, v):
            self.table[i] = v
            self.table = {}
    """))
    store = _node_at(cfg, 2)
    assert "self.table" in store.reads
    assert "self.table" not in store.writes
    rebind = _node_at(cfg, 3)
    assert "self.table" in rebind.writes


def test_cfg_nested_defs_are_opaque():
    cfg = build_cfg(_fn("""
        def f(self):
            def helper():
                return self.cache_k
            return helper
    """))
    defstmt = _node_at(cfg, 2)
    assert defstmt.writes == {"helper"}
    assert "self.cache_k" not in defstmt.reads


# --------------------------------------------------------------------------
# reaching definitions / def-use
# --------------------------------------------------------------------------


def test_reaching_defs_branch_join_merges_both():
    cfg = build_cfg(_fn("""
        def f(c):
            if c:
                x = 1
            else:
                x = 2
            return x
    """))
    in_sets = reaching_definitions(cfg, param_refs(_fn("""
        def f(c):
            pass
    """)))
    ret = _node_at(cfg, 6)
    defs = {d for d in in_sets[ret.idx] if d[0] == "x"}
    assert _lines(cfg, {idx for _, idx in defs}) == {3, 5}


def test_reaching_defs_loop_back_edge_reaches_head():
    cfg = build_cfg(_fn("""
        def f(n):
            x = 0
            while n:
                x = x + 1
            return x
    """))
    in_sets = reaching_definitions(cfg)
    head = _node_at(cfg, 3, "head")
    defs = {idx for ref, idx in in_sets[head.idx] if ref == "x"}
    assert _lines(cfg, defs) == {2, 4}  # initial def AND the loop body's


def test_def_use_chains_straight_line_and_kill():
    cfg = build_cfg(_fn("""
        def f():
            x = 1
            use(x)
            x = 2
            use(x)
    """))
    chains = def_use_chains(cfg)
    d1 = ("x", _node_at(cfg, 2).idx)
    d2 = ("x", _node_at(cfg, 4).idx)
    assert _lines(cfg, chains[d1]) == {3}   # first def killed by line 4
    assert _lines(cfg, chains[d2]) == {5}


def test_def_use_chains_param_defined_at_entry():
    fn = _fn("""
        def f(x):
            return use(x)
    """)
    cfg = build_cfg(fn)
    chains = def_use_chains(cfg, param_refs(fn))
    assert _lines(cfg, chains[("x", cfg.entry)]) == {2}


def test_def_use_chains_dead_def_has_no_uses():
    cfg = build_cfg(_fn("""
        def f():
            t = spawn()
            other = 1
            return other
    """))
    chains = def_use_chains(cfg)
    assert ("t", _node_at(cfg, 2).idx) not in chains


# --------------------------------------------------------------------------
# the use-after-donate path query
# --------------------------------------------------------------------------


def test_reads_before_rebind_branch_read_fires():
    cfg = build_cfg(_fn("""
        def f(self, c):
            out = fn(self.cache_k)
            if c:
                bad = self.cache_k.sum()
            self.cache_k = out
    """))
    call = _node_at(cfg, 2)
    hits = reads_before_rebind(cfg, call.idx, "self.cache_k")
    assert [line for _, line in hits] == [4]


def test_reads_before_rebind_immediate_rebind_is_clean():
    cfg = build_cfg(_fn("""
        def f(self):
            out = fn(self.cache_k)
            self.cache_k = out
            return self.cache_k
    """))
    call = _node_at(cfg, 2)
    assert reads_before_rebind(cfg, call.idx, "self.cache_k") == []


def test_reads_before_rebind_loop_carries_the_read_back():
    # second loop iteration reads the ref donated by the first: the back
    # edge must carry the read even though it is textually BEFORE the call
    cfg = build_cfg(_fn("""
        def f(self, n):
            for _ in range(n):
                out = fn(self.cache_k)
            return 0
    """))
    call = _node_at(cfg, 3)
    hits = reads_before_rebind(cfg, call.idx, "self.cache_k")
    assert [line for _, line in hits] == [3]


def test_exits_without_rebind_detects_the_quiet_path():
    from langstream_tpu.analysis.dataflow import exits_without_rebind

    cfg = build_cfg(_fn("""
        def f(self, c):
            out = fn(self.cache_k)
            if c:
                self.cache_k = out
            return 0
    """))
    call = _node_at(cfg, 2)
    # the else path reaches the return with the donated attr unbound
    assert exits_without_rebind(cfg, call.idx, "self.cache_k")


def test_exits_without_rebind_clean_when_all_paths_rebind():
    from langstream_tpu.analysis.dataflow import exits_without_rebind

    cfg = build_cfg(_fn("""
        def f(self):
            out = fn(self.cache_k)
            self.cache_k = out
            return 0
    """))
    call = _node_at(cfg, 2)
    assert not exits_without_rebind(cfg, call.idx, "self.cache_k")


def test_reads_before_rebind_read_and_write_same_stmt_counts_as_read():
    cfg = build_cfg(_fn("""
        def f(self):
            out = fn(self.cache_k)
            self.cache_k = self.cache_k.copy()
    """))
    call = _node_at(cfg, 2)
    hits = reads_before_rebind(cfg, call.idx, "self.cache_k")
    assert [line for _, line in hits] == [3]


# --------------------------------------------------------------------------
# taint
# --------------------------------------------------------------------------


class _Spec(TaintSpec):
    def source_label(self, expr):
        if isinstance(expr, ast.Attribute) and expr.attr == "request":
            return "request"
        return None

    def is_sanctioner(self, call):
        return isinstance(call.func, ast.Name) and call.func.id == "_bucket"


def _taint_of(source: str, line: int, seed=None):
    fn = _fn(source)
    cfg = build_cfg(fn)
    state = run_taint(cfg, _Spec(), seed=seed)
    node = _node_at(cfg, line)
    assert isinstance(node.ast_node, (ast.Assign, ast.Return, ast.Expr))
    expr = getattr(node.ast_node, "value", node.ast_node)
    return set(state.expr_labels(expr, node.idx))


def test_taint_propagates_through_assignments_and_len():
    assert _taint_of("""
        def f(self):
            n = len(self.slot.request.tokens)
            m = n + 1
            return m
    """, 4) == {"request"}


def test_taint_sanctioner_launders():
    assert _taint_of("""
        def f(self):
            n = _bucket(len(self.slot.request.tokens))
            return n
    """, 3) == set()


def test_taint_merges_at_branch_join():
    assert _taint_of("""
        def f(self, c):
            if c:
                n = 4
            else:
                n = self.slot.request.size
            return n
    """, 6) == {"request"}


def test_taint_rebinding_clears():
    assert _taint_of("""
        def f(self):
            n = self.slot.request.size
            n = 8
            return n
    """, 4) == set()


def test_taint_seed_labels_params():
    assert _taint_of("""
        def f(rows):
            padded = rows * 2
            return padded
    """, 3, seed={"rows": frozenset({"param:rows"})}) == {"param:rows"}


def test_taint_weak_update_through_append_and_subscript_store():
    assert _taint_of("""
        def f(self, items):
            batch = []
            for it in items:
                batch.append(self.slot.request)
            return len(batch)
    """, 5) == {"request"}
    assert _taint_of("""
        def f(self, table):
            table["k"] = self.slot.request.size
            return table
    """, 3) == {"request"}


def test_taint_with_as_carries_context_labels():
    assert _taint_of("""
        def f(self):
            with self.queue.request as item:
                got = item
            return got
    """, 4) == {"request"}


def test_taint_multi_item_with_labels_each_target_from_its_own_item():
    # a multi-item `with` builds one head node per item: the tainted
    # first item must not be overwritten by the clean second (and the
    # clean second must not inherit the first's taint)
    src = """
        def f(self, p):
            with self.ctx.request as rows, open(p) as fh:
                a = rows
                b = fh
            return a, b
    """
    assert _taint_of(src, 3) == {"request"}   # a = rows
    assert _taint_of(src, 4) == set()          # b = fh


# --------------------------------------------------------------------------
# the flow index
# --------------------------------------------------------------------------


def test_flow_index_qnames_and_cache():
    src = textwrap.dedent("""
        class Engine:
            def step(self):
                def inner():
                    return 1
                return inner

        def helper():
            try:
                pass
            except Exception:
                def fallback():
                    return 0
    """)
    ff = flow_index("serving/engine.py", src)
    assert set(ff.functions) == {
        "serving.engine.Engine.step",
        "serving.engine.Engine.step.inner",
        "serving.engine.helper",
        "serving.engine.helper.fallback",
    }
    assert flow_index("serving/engine.py", src) is ff  # content-hash hit


def test_ref_of_spellings():
    assert ref_of(ast.parse("x", mode="eval").body) == "x"
    assert ref_of(ast.parse("self.cache_k", mode="eval").body) == "self.cache_k"
    assert ref_of(ast.parse("cls.table", mode="eval").body) == "self.table"
    assert ref_of(ast.parse("obj.attr", mode="eval").body) is None
