from langstream_tpu.api.record import MutableRecord
from langstream_tpu.core.expressions import (
    ExpressionError,
    evaluate,
    evaluate_accessor,
    render_template,
)

import pytest


def rec(value=None, key=None, props=None):
    return MutableRecord(value=value, key=key, properties=props or {})


def test_dotted_access():
    r = rec(value={"question": "hi", "nested": {"x": 3}})
    assert evaluate("value.question", r) == "hi"
    assert evaluate("value.nested.x", r) == 3
    assert evaluate("value.missing", r) is None


def test_operators_and_el_normalisation():
    r = rec(value={"a": 2, "b": "yes"})
    assert evaluate("value.a == 2 && value.b == 'yes'", r) is True
    assert evaluate("value.a > 5 || value.b == 'yes'", r) is True
    assert evaluate("!(value.a == 2)", r) is False
    assert evaluate("value.a + 3", r) == 5


def test_fn_helpers():
    r = rec(value={"s": "  Hello  "})
    assert evaluate("fn:trim(value.s)", r) == "Hello"
    assert evaluate("fn:lowercase(value.s)", r) == "  hello  "
    assert evaluate("fn:concat('a', 'b', 1)", r) == "ab1"
    assert evaluate("fn:coalesce(value.missing, 'x')", r) == "x"
    assert evaluate("fn:len(value.s)", r) == 9


def test_properties_access():
    r = rec(value="v", props={"lang": "en"})
    assert evaluate("properties.lang == 'en'", r) is True


def test_safety():
    r = rec(value={})
    with pytest.raises(ExpressionError):
        evaluate("__import__('os')", r)
    with pytest.raises(ExpressionError):
        evaluate("[x for x in value]", r)
    with pytest.raises(ExpressionError):
        evaluate("value.__class__", r)


def test_string_literals_survive_normalisation():
    # regression: EL keyword rewriting must not touch string literals
    r = rec(value={"flag": "true", "op": "eq", "brace": "}"})
    assert evaluate("value.flag == 'true'", r) is True
    assert evaluate("value.op == 'eq'", r) is True
    assert evaluate("value.brace == '}'", r) is True
    assert evaluate("fn:contains('not a keyword', 'a')", r) is True


def test_dict_literals_parse():
    r = rec(value={})
    assert evaluate("{'a': 1}", r) == {"a": 1}


def test_accessor_fast_path():
    r = rec(value={"a": {"b": 1}})
    assert evaluate_accessor("value.a.b", r) == 1
    assert evaluate_accessor("value.a.b + 1", r) == 2


def test_accessor_hyphenated_segments():
    """Gateway headers like langstream-client-session-id are reachable as
    dotted accessors; misses still evaluate as EL (subtraction)."""
    r = rec(
        value={"a": 7, "b": 3},
        props={"langstream-client-session-id": "s1"},
    )
    assert evaluate_accessor("properties.langstream-client-session-id", r) == "s1"
    assert evaluate_accessor("value.a - value.b", r) == 4
    assert evaluate_accessor("value.a-value.b", r) == 4  # miss → EL fallback


def test_template_basic():
    r = rec(value={"question": "what?"})
    assert render_template("Q: {{ value.question }}", r) == "Q: what?"
    assert render_template("{{ value.missing }}", r) == ""


def test_template_sections():
    r = rec(value={"docs": [{"text": "a"}, {"text": "b"}], "none": []})
    out = render_template("{{# value.docs}}[{{ text}}]{{/ value.docs}}", r)
    assert out == "[a][b]"
    assert render_template("{{^ value.none}}empty{{/ value.none}}", r) == "empty"


def test_template_scalar_list():
    r = rec(value={"items": ["x", "y"]})
    assert render_template("{{# value.items}}{{.}},{{/ value.items}}", r) == "x,y,"
