"""Device-survival plane tests (docs/RESILIENCE.md).

Layers covered: the fault-injection registry units (plan validation,
arm/disarm fire counting, LS_TPU_FAULTS parsing), the broadened
RESOURCE_EXHAUSTED classifier (one test per jaxlib spelling), the
BlockManager budget surface (reduce/restore clamps + a shrink/restore
storm whose ledger must stay exact), the crash-requeue journal units
(admit/retire/compaction/eviction/torn lines), the chaos e2e acceptance
(injected OOM at pool-grow mid-flood → pool-shrink with evidence →
every request completes byte-identically → budget restores; injected
hang → watchdog WEDGED → ``/healthz`` 503 → recovery), journal
replay-after-restart (zero silent loss, exactly-once retire,
front-of-class order), the default-config hot path staying bit-for-bit
(no injector, zero survival counters, identical greedy tokens), and the
downstream consumers: the health shrink-pressure predicate, the
autoscaler's pool-shrink signal, engine_top's survival panel + thrash
flag, the oom_storm bench phase, and perf_diff's worse-directions.
"""

import asyncio
import importlib.util
import json
import random
import time
from pathlib import Path

import pytest

from langstream_tpu.models.paged import BlockManager, PagedLayout
from langstream_tpu.serving.faults import (
    FaultInjector,
    FaultPlan,
    InjectedFault,
    plans_from_env,
)
from langstream_tpu.serving.journal import RequestJournal


def _load_tool(name: str):
    path = Path(__file__).resolve().parents[1] / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _base_config(**kw):
    from langstream_tpu.serving.engine import ServingConfig

    d = dict(
        model="tiny", slots=4, max_seq_len=192, model_dtype="float32",
        kv_layout="paged", kv_block_size=16, decode_chunk=4,
        default_max_tokens=24, shrink_recovery_s=0.3,
    )
    d.update(kw)
    return ServingConfig(**d)


# ---------------------------------------------------------------------------
# FaultPlan / FaultInjector units
# ---------------------------------------------------------------------------


def test_fault_plan_validation_rejects():
    with pytest.raises(ValueError):
        FaultPlan(site="nonsense")
    with pytest.raises(ValueError):
        FaultPlan(site="pool-grow", shape="explode")
    with pytest.raises(ValueError):
        FaultPlan(site="pool-grow", count=0)
    with pytest.raises(ValueError):
        FaultPlan(site="pool-grow", after=-1)
    with pytest.raises(ValueError):
        FaultPlan(site="prefill", shape="hang", hang_ms=0)


def test_fault_plan_round_trip():
    plan = FaultPlan(site="prefill", shape="hang", after=3, count=2,
                     hang_ms=250.0)
    assert FaultPlan.from_dict(plan.to_dict()) == plan


def test_injector_fires_after_then_disarms():
    inj = FaultInjector((FaultPlan(site="pool-grow", after=2, count=2),))
    # two passes let through, then exactly two fires, then disarmed
    assert inj.fire("pool-grow") is None
    assert inj.fire("pool-grow") is None
    a1 = inj.fire("pool-grow")
    a2 = inj.fire("pool-grow")
    assert a1 is not None and a1.seq == 1
    assert a2 is not None and a2.seq == 2
    assert inj.fire("pool-grow") is None  # fail-then-recover
    assert inj.fire("prefill") is None    # other sites untouched
    st = inj.stats()[0]
    assert st["fired"] == 2 and not st["armed"]


def test_plans_from_env_parse_and_reject():
    env = {"LS_TPU_FAULTS": json.dumps(
        [{"site": "fetch", "shape": "oom", "after": 1}]
    )}
    (plan,) = plans_from_env(env)
    assert plan.site == "fetch" and plan.after == 1
    assert plans_from_env({}) == ()
    with pytest.raises(ValueError):
        plans_from_env({"LS_TPU_FAULTS": "{\"site\": \"fetch\"}"})
    with pytest.raises(Exception):
        plans_from_env({"LS_TPU_FAULTS": "not json"})


# ---------------------------------------------------------------------------
# the RESOURCE_EXHAUSTED classifier: one test per jaxlib spelling
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "message",
    [
        "RESOURCE_EXHAUSTED: Out of memory allocating 123 bytes",
        "Out of memory while trying to allocate 17179869184 bytes",
        "Failed to allocate request for 1.20GiB (1288490189B) on device",
        "Allocation of 4096000000 bytes exceeds 90% of free system memory",
        "paged KV pool exhausted despite reservation accounting",
    ],
)
def test_resource_exhausted_spellings(message):
    from langstream_tpu.serving.engine import TpuServingEngine

    assert TpuServingEngine._resource_exhausted(RuntimeError(message))


def test_resource_exhausted_negative():
    from langstream_tpu.serving.engine import TpuServingEngine

    for message in (
        "ValueError: shapes do not match",
        "connection reset by peer",
        "INVALID_ARGUMENT: bad block table",
    ):
        assert not TpuServingEngine._resource_exhausted(
            RuntimeError(message)
        )


def test_injected_fault_matches_classifier():
    from langstream_tpu.serving.engine import TpuServingEngine

    err = InjectedFault("pool-grow", "RESOURCE_EXHAUSTED: injected")
    assert TpuServingEngine._resource_exhausted(err)
    assert err.fault_site == "pool-grow"


# ---------------------------------------------------------------------------
# BlockManager budget surface
# ---------------------------------------------------------------------------


def _mgr(num_blocks=33, block_size=16, max_seq=256, slots=4):
    layout = PagedLayout(
        block_size=block_size, num_blocks=num_blocks,
        max_blocks_per_slot=-(-max_seq // block_size),
    )
    return BlockManager(layout, slots)


def test_budget_reduce_restore_clamps():
    mgr = _mgr()  # 32 usable, floor = max_blocks_per_slot = 16
    assert mgr.configured_blocks == 32
    assert mgr.reduce_budget(10) == 10
    assert mgr.usable_blocks == 22
    # clamped at the floor: only 6 more can be withheld
    assert mgr.reduce_budget(100) == 6
    assert mgr.usable_blocks == 16
    assert mgr.reduce_budget(1) == 0  # at the floor
    assert mgr.restore_budget(4) == 4
    assert mgr.usable_blocks == 20
    assert mgr.restore_budget() == 12  # the rest
    assert mgr.usable_blocks == 32 and mgr.budget_reduction == 0
    assert mgr.restore_budget(5) == 0  # nothing withheld


def test_budget_gates_admission_and_used_ratio():
    mgr = _mgr()
    assert mgr.can_admit(16 * 16)  # a max-size slot fits the fresh pool
    mgr.admit(0, 10 * 16)
    assert mgr.used_ratio() == pytest.approx(10 / 32)
    mgr.reduce_budget(16)  # usable 16 < reserved 10 + need 10
    assert not mgr.can_admit(10 * 16)
    assert mgr.can_admit(6 * 16)
    assert mgr.used_ratio() == pytest.approx(10 / 16)
    mgr.restore_budget()
    assert mgr.can_admit(10 * 16)
    stats = mgr.stats()
    assert stats["budget_blocks"] == 32 and stats["withheld_blocks"] == 0


def test_ensure_capacity_returns_block_count():
    mgr = _mgr()
    mgr.admit(0, 80)  # 5 blocks reserved
    assert mgr.ensure_capacity(0, 40) == 3
    assert mgr.ensure_capacity(0, 40) == 0
    assert mgr.ensure_capacity(0, 80) == 2


def test_budget_ledger_exact_under_shrink_restore_storm():
    """Property test: a random storm of admit/release/reduce/restore ops
    never breaks the budget invariants — usable stays within
    [floor, configured], reduction always equals configured - usable,
    full restore returns exactly to configured, and reservation
    accounting is untouched by budget moves."""
    rng = random.Random(1234)
    mgr = _mgr(num_blocks=65, slots=8)
    floor = min(mgr.layout.max_blocks_per_slot, mgr.configured_blocks)
    admitted: dict[int, int] = {}
    for _ in range(600):
        op = rng.choice(("admit", "release", "reduce", "restore"))
        if op == "admit":
            slot = rng.randrange(8)
            tokens = rng.randrange(16, 200)
            if slot not in admitted and mgr.can_admit(tokens):
                mgr.admit(slot, tokens)
                admitted[slot] = mgr.blocks_needed(tokens)
        elif op == "release" and admitted:
            slot = rng.choice(list(admitted))
            mgr.release(slot)
            del admitted[slot]
        elif op == "reduce":
            want = rng.randrange(0, 30)
            got = mgr.reduce_budget(want)
            assert got <= want
        else:
            want = rng.choice([None, rng.randrange(0, 30)])
            before = mgr.budget_reduction
            got = mgr.restore_budget(want)
            assert got <= before
        assert floor <= mgr.usable_blocks <= mgr.configured_blocks
        assert (
            mgr.budget_reduction
            == mgr.configured_blocks - mgr.usable_blocks
        )
        assert mgr.reserved_blocks == sum(admitted.values())
    mgr.restore_budget()
    assert mgr.usable_blocks == mgr.configured_blocks


# ---------------------------------------------------------------------------
# ServingConfig round trip
# ---------------------------------------------------------------------------


def test_config_round_trips_survival_keys():
    from langstream_tpu.serving.engine import ServingConfig

    cfg = ServingConfig(
        model="tiny", kv_layout="paged", shrink_fraction=0.25,
        shrink_recovery_s=7.5, journal_dir="/tmp/j",
        faults=(FaultPlan(site="fetch", after=1),),
    )
    back = ServingConfig.from_dict(cfg.to_dict())
    assert back.shrink_fraction == 0.25
    assert back.shrink_recovery_s == 7.5
    assert back.journal_dir == "/tmp/j"
    assert back.faults == (FaultPlan(site="fetch", after=1),)
    # hashable: engines are singleton-cached by config
    hash(back)


def test_engine_rejects_bad_shrink_config():
    from langstream_tpu.serving.engine import TpuServingEngine

    with pytest.raises(ValueError):
        TpuServingEngine(_base_config(shrink_fraction=0.0))
    with pytest.raises(ValueError):
        TpuServingEngine(_base_config(shrink_recovery_s=0.0))


# ---------------------------------------------------------------------------
# crash-requeue journal units
# ---------------------------------------------------------------------------


def _entry(i: int) -> dict:
    return {
        "id": f"req-{i}", "prompt": [1, 2, 3 + i], "max-tokens": 8,
        "temperature": 0.0, "top-k": 0, "top-p": 1.0,
        "presence-penalty": 0.0, "frequency-penalty": 0.0,
        "stop": [], "tenant": f"t{i}", "priority": "default",
    }


def test_journal_admit_retire_and_reload(tmp_path):
    j = RequestJournal(str(tmp_path))
    for i in range(4):
        j.admit(_entry(i))
    j.retire("req-1")
    j.retire("req-1")  # idempotent double retire
    j.retire("never-admitted")
    assert j.flush(5.0)
    st = j.stats()
    assert st["appended"] == 4 and st["retired"] == 1
    j.close()
    # a fresh journal (the restarted process) sees exactly the live set
    j2 = RequestJournal(str(tmp_path))
    pending = j2.pending()
    assert [e["id"] for e in pending] == ["req-0", "req-2", "req-3"]
    assert pending[0]["prompt"] == [1, 2, 3]
    j2.close()


def test_journal_bound_evicts_oldest_loudly(tmp_path):
    evicted = []
    j = RequestJournal(str(tmp_path), max_entries=3,
                       on_evict=evicted.append)
    for i in range(5):
        j.admit(_entry(i))
    assert j.flush(5.0)
    assert evicted == ["req-0", "req-1"]
    assert j.stats()["live"] == 3 and j.stats()["evicted"] == 2
    j.close()
    j2 = RequestJournal(str(tmp_path))
    assert [e["id"] for e in j2.pending()] == ["req-2", "req-3", "req-4"]
    j2.close()


def test_journal_compacts_and_tolerates_torn_tail(tmp_path):
    j = RequestJournal(str(tmp_path), max_entries=4)
    # enough churn to exceed the 256-op compaction threshold
    for i in range(200):
        j.admit(_entry(i))
        j.retire(f"req-{i}")
    j.admit(_entry(999))
    assert j.flush(10.0)
    j.close()
    path = tmp_path / "requests.jsonl"
    lines = path.read_text().strip().splitlines()
    assert len(lines) <= 16  # compacted to ~the live set, not 401 ops
    # torn trailing line (crash mid-append) is skipped, never fatal
    with open(path, "a") as fh:
        fh.write('{"op": "admit", "id": "torn-req", "pro')
    j2 = RequestJournal(str(tmp_path))
    assert [e["id"] for e in j2.pending()] == ["req-999"]
    j2.close()


def test_journal_refuses_mismatched_fingerprint(tmp_path):
    """Entries journaled under a different model/tokenizer identity are
    never offered for replay (their token ids mean nothing here), but
    stay live — counted, never silently erased."""
    j = RequestJournal(str(tmp_path), fingerprint={"model": "tiny"})
    j.admit(_entry(0))
    assert j.flush(5.0)
    j.close()
    other = RequestJournal(
        str(tmp_path), fingerprint={"model": "llama-1b"}
    )
    assert other.pending() == []
    assert other.stats()["mismatched"] == 1
    assert other.stats()["live"] == 1  # preserved, not erased
    other.close()
    # the same identity replays it
    same = RequestJournal(str(tmp_path), fingerprint={"model": "tiny"})
    assert [e["id"] for e in same.pending()] == ["req-0"]
    same.close()


def test_journal_file_stays_bounded_across_restarts(tmp_path):
    """The compaction threshold counts ops ON DISK, not the live set —
    a crash-looping pod (many lives, each journaling a few ops) must
    not grow the file without bound."""
    for _ in range(6):
        j = RequestJournal(str(tmp_path), max_entries=8)
        for i in range(40):
            j.admit(_entry(i))
            j.retire(f"req-{i}")
        assert j.flush(10.0)
        j.close()
    lines = (tmp_path / "requests.jsonl").read_text().splitlines()
    # 6 lives x 80 ops = 480 ops written; the bound (max(256, 32)) must
    # have compacted along the way instead of resetting every restart
    assert len(lines) <= 256 + 80


# ---------------------------------------------------------------------------
# chaos e2e: injected OOM at pool-grow mid-flood
# ---------------------------------------------------------------------------


def test_chaos_oom_at_pool_grow_byte_identical_and_recovers(run_async):
    """The acceptance proof: a RESOURCE_EXHAUSTED burst injected at the
    pool-grow seam mid-flood shrinks the budget (pool-shrink event with
    evidence BEFORE any admission against it), every submitted request
    still completes with byte-identical greedy output (f32), and the
    recovery probe restores the full budget after the quiet window."""
    from langstream_tpu.serving.engine import TpuServingEngine

    prompts = [f"chaos request {i} says hello" for i in range(6)]

    async def run(faults=()):
        engine = TpuServingEngine(_base_config(faults=faults))
        try:
            outs = await asyncio.gather(*(
                engine.generate(p, {"max-tokens": 16, "temperature": 0})
                for p in prompts
            ))
            if faults:
                for _ in range(100):
                    if not engine.stats()["survival"]["withheld_blocks"]:
                        break
                    await asyncio.sleep(0.05)
            survival = engine.stats()["survival"]
            events = engine.flight.recent_events(0)
            return outs, survival, events
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    base, surv0, _ = run_async(run())
    assert surv0["shrinks"] == 0 and surv0["restores"] == 0

    faults = (FaultPlan(site="pool-grow", after=3, count=2),)
    outs, survival, events = run_async(run(faults))

    # zero loss, byte-identical resumes (greedy, f32-pinned)
    assert [o["text"] for o in outs] == [o["text"] for o in base]
    assert [o["tokens"] for o in outs] == [o["tokens"] for o in base]
    assert survival["shrinks"] >= 1
    assert survival["restores"] >= 1
    assert survival["withheld_blocks"] == 0  # fully recovered
    kinds = [e["kind"] for e in events]
    assert "fault-injected" in kinds
    assert "pool-shrink" in kinds and "pool-restore" in kinds
    shrink = next(e for e in events if e["kind"] == "pool-shrink")
    # the evidence the issue demands: site, bytes, new budget
    assert shrink["site"] == "pool-grow"
    assert shrink["withheld_blocks"] >= 1
    assert shrink["withheld_bytes"] > 0
    assert shrink["budget_blocks"] < shrink["configured_blocks"]
    assert shrink["recovery_s"] == pytest.approx(0.3)
    # cause precedes effect in the ring
    assert kinds.index("fault-injected") < kinds.index("pool-shrink")


def test_chaos_oom_at_prefill_dispatch_byte_identical(run_async):
    """An allocator failure in the PREFILL dispatch itself strands the
    just-admitted batch in slots with no KV written — the shrink pass
    must sweep those un-prefilled slots back to the queue (decoding
    them would emit garbage from unwritten cache rows) and every
    request must still complete byte-identically."""
    from langstream_tpu.serving.engine import TpuServingEngine

    prompts = [f"prefill fault request {i}" for i in range(5)]

    async def run(faults=()):
        engine = TpuServingEngine(_base_config(faults=faults))
        try:
            outs = await asyncio.gather(*(
                engine.generate(p, {"max-tokens": 12, "temperature": 0})
                for p in prompts
            ))
            return [o["text"] for o in outs], engine.stats()["survival"]
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    base, _ = run_async(run())
    faults = (FaultPlan(site="prefill", shape="oom", count=1),)
    texts, survival = run_async(run(faults))
    assert texts == base
    assert survival["shrinks"] >= 1


def test_replay_refuses_request_that_no_longer_fits(tmp_path, run_async):
    """A journaled request that can never fit the restarted engine's
    pool is retired loudly instead of head-blocking admission forever
    (and re-wedging every restart)."""
    from langstream_tpu.serving.engine import TpuServingEngine

    journal_dir = str(tmp_path / "jfit")
    # hand-write a journal whose entry wants far more KV than the tiny
    # pool can EVER hold (generate() would have refused it up front)
    j = RequestJournal(
        journal_dir,
        fingerprint={"model": "tiny", "tokenizer": "byte"},
    )
    poison = dict(_entry(0), **{"prompt": list(range(64)),
                                "max-tokens": 100000})
    j.admit(poison)
    j.admit(_entry(1))
    assert j.flush(5.0)
    j.close()

    async def run():
        engine = TpuServingEngine(
            _base_config(journal_dir=journal_dir, slots=2)
        )
        try:
            # a fresh request must still serve: the poison entry was
            # refused (max-tokens clamps to the window; had it still
            # not fit, fits_ever refuses) — never left to head-block
            fresh = await engine.generate(
                "post-restart request", {"max-tokens": 4,
                                         "temperature": 0}
            )
            for _ in range(200):
                if engine.journal.depth() == 0:
                    break
                await asyncio.sleep(0.05)
            return fresh, engine.journal.stats()
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    fresh, stats = run_async(run())
    assert fresh["tokens"]
    assert stats["live"] == 0  # both entries answered or refused-retired


def test_chaos_oom_at_chunked_prefill_grow_byte_identical(run_async):
    """An allocator failure in the CHUNKED-prefill admission grow (the
    slot is claimed, prefilling=True, but its table never grew) must
    requeue that request — left in place its chunks would scatter into
    the scratch block and read back silent garbage — and every request
    still completes byte-identically."""
    from langstream_tpu.serving.engine import TpuServingEngine

    prompts = [
        f"chunked prefill fault request number {i} with a longer prompt"
        for i in range(4)
    ]

    async def run(faults=()):
        engine = TpuServingEngine(
            _base_config(faults=faults, prefill_chunk=8, slots=2)
        )
        try:
            outs = await asyncio.gather(*(
                engine.generate(p, {"max-tokens": 10, "temperature": 0})
                for p in prompts
            ))
            return [o["text"] for o in outs], engine.stats()["survival"]
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    base, _ = run_async(run())
    faults = (FaultPlan(site="pool-grow", shape="oom", count=1),)
    texts, survival = run_async(run(faults))
    assert texts == base
    assert survival["shrinks"] >= 1


def test_persistent_prefill_failure_sheds_instead_of_livelocking(run_async):
    """A dispatch that fails EVERY time (pressure that never clears)
    must not livelock the loop in an admit→OOM→requeue cycle: after the
    bounded retry cap the request is shed loudly with RateLimited +
    Retry-After, and the engine keeps serving."""
    from langstream_tpu.serving.engine import TpuServingEngine
    from langstream_tpu.serving.qos import RateLimited

    faults = (FaultPlan(site="prefill", shape="oom", count=1000),)

    async def run():
        engine = TpuServingEngine(_base_config(faults=faults))
        try:
            with pytest.raises(RateLimited) as e:
                await asyncio.wait_for(
                    engine.generate("doomed request",
                                    {"max-tokens": 8, "temperature": 0}),
                    timeout=20.0,
                )
            events = engine.flight.recent_events(0)
            return e.value, events
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    err, events = run_async(run())
    assert err.reason == "device-oom"
    assert err.retry_after > 0
    sheds = [e for e in events
             if e["kind"] == "shed" and e.get("reason") == "device-oom"]
    assert sheds and sheds[0]["retries"] >= 3


def test_prefill_pool_handoff_settles_journal(tmp_path, run_async):
    """A prefill-role engine's handoff finish parks the journal entry
    UNSETTLED (the decode side may still die before completion —
    docs/RESILIENCE.md "Distributed failure domain"); the chainer's
    handoff_settled() is what retires it, exactly once. PR 14 retired
    at handoff, which made a decode-side death invisible."""
    from langstream_tpu.serving.engine import TpuServingEngine

    journal_dir = str(tmp_path / "jprefill")

    async def run():
        engine = TpuServingEngine(
            _base_config(journal_dir=journal_dir, slots=2,
                         pool_role="prefill")
        )
        try:
            out = await engine.generate(
                "handoff me", {"max-tokens": 8, "temperature": 0}
            )
            assert out["finish_reason"] == "handoff"
            assert engine.journal.flush(5.0)
            # live until the decode side ANSWERS: a crash in between
            # replays the request instead of losing it invisibly
            mid = engine.journal.stats()
            assert mid["live"] == 1
            assert engine.stats()["kvtransfer"]["unsettled_handoffs"] == 1
            engine.handoff_settled(out["handoff"])
            engine.handoff_settled(out["handoff"])  # idempotent
            assert engine.journal.flush(5.0)
            assert (
                engine.stats()["kvtransfer"]["unsettled_handoffs"] == 0
            )
            return engine.journal.stats()
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    stats = run_async(run())
    assert stats["appended"] == 1
    assert stats["retired"] == 1 and stats["live"] == 0


def test_chaos_oom_preempts_lowest_class_victims(run_async):
    """When the shrunk budget no longer covers the live reservations,
    the LOWEST-class victims are preempted (worst-case reservations
    freed, requeued front-of-class) — and still complete correctly."""
    from langstream_tpu.serving.engine import TpuServingEngine
    from langstream_tpu.serving.qos import QosSpec

    config = _base_config(
        slots=4,
        qos=QosSpec.from_dict({}),
        # half the budget vanishes per shrink: reservations must spill
        shrink_fraction=0.5,
        faults=(FaultPlan(site="pool-grow", after=6, count=1),),
    )

    async def run():
        engine = TpuServingEngine(config)
        try:
            outs = await asyncio.gather(*(
                engine.generate(
                    f"victim candidate {i} reporting",
                    {
                        "max-tokens": 24, "temperature": 0,
                        "priority": "batch" if i % 2 else "interactive",
                    },
                )
                for i in range(6)
            ))
            sched = engine.stats()["scheduler"]
            survival = engine.stats()["survival"]
            events = engine.flight.recent_events(0)
            return outs, sched, survival, events
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    outs, sched, survival, events = run_async(run())
    assert len(outs) == 6 and all("text" in o for o in outs)
    assert survival["shrinks"] >= 1
    if survival["shrink_preempted"]:
        # victims were needed: the batch class pays before interactive
        assert sched["classes"]["batch"]["preempted"] >= 1
        assert sched["classes"]["interactive"]["preempted"] == 0
        preempts = [
            e for e in events
            if e["kind"] == "preempt" and e.get("reason") == "pool-shrink"
        ]
        assert preempts and all(
            p["priority"] == "batch" for p in preempts
        )


def test_chaos_hang_wedges_healthz_then_recovers(run_async):
    """The r03 shape: an injected hang at the prefill seam stalls the
    dispatch, the watchdog heartbeat stops while work is pending, and
    ``/healthz`` flips 503 WEDGED — then recovers when the stall ends."""
    from langstream_tpu.runtime.pod import _probe_healthz
    from langstream_tpu.serving.engine import TpuServingEngine

    config = _base_config(
        wedge_window_s=0.25,
        faults=(
            FaultPlan(site="prefill", shape="hang", hang_ms=1200.0),
        ),
    )

    async def run():
        engine = TpuServingEngine.get_or_create(config)
        try:
            task = asyncio.ensure_future(
                engine.generate("hang me", {"max-tokens": 4,
                                            "temperature": 0})
            )
            wedged_status = None
            wedged_body = None
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline:
                status, body = _probe_healthz()
                if status == 503:
                    wedged_status, wedged_body = status, body
                    break
                await asyncio.sleep(0.05)
            result = await task  # the stall resolves; the request serves
            # progress resumed: health recovers
            recovered = None
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline:
                status, _ = _probe_healthz()
                if status == 200:
                    recovered = status
                    break
                await asyncio.sleep(0.05)
            # the fault-injected evidence drains at the loop's next
            # safe point — give the loop a pass before reading the ring
            deadline = time.monotonic() + 2.0
            events = engine.flight.recent_events(0)
            while time.monotonic() < deadline and not any(
                e["kind"] == "fault-injected" for e in events
            ):
                await asyncio.sleep(0.05)
                events = engine.flight.recent_events(0)
            return wedged_status, wedged_body, result, recovered, events
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    wedged_status, body, result, recovered, events = run_async(run())
    assert wedged_status == 503
    assert body["wedged"] == ["tiny"]
    assert result["tokens"]  # zero loss: the hung request still answered
    assert recovered == 200
    assert any(e["kind"] == "fault-injected" and e["shape"] == "hang"
               for e in events)


# ---------------------------------------------------------------------------
# journal replay-after-restart e2e
# ---------------------------------------------------------------------------


def test_journal_replay_after_restart_zero_loss(tmp_path):
    """Engine A accepts work and 'crashes' (the process's loop dies
    without close()); engine B on the same journal dir replays the
    admitted-but-unfinished requests front-of-class, completes them,
    and retires each exactly once — the journal converges to empty."""
    from langstream_tpu.serving.engine import TpuServingEngine

    journal_dir = str(tmp_path / "journal")

    async def crash_phase():
        # a long hang at the prefill seam pins the 'crashing' engine:
        # no accepted request can finish (and so retire its entry)
        # before the process abandons them
        engine = TpuServingEngine(
            _base_config(
                journal_dir=journal_dir, slots=2,
                faults=(FaultPlan(site="prefill", shape="hang",
                                  hang_ms=3000.0, count=1),),
            )
        )
        # submissions journaled at accept; the engine never gets to run
        # them (we abandon the loop mid-flight — the crash)
        tasks = [
            asyncio.ensure_future(engine.generate(
                f"journaled request {i}",
                {"max-tokens": 6, "temperature": 0,
                 "qos-tenant": f"t{i}"},
            ))
            for i in range(3)
        ]
        await asyncio.sleep(0)  # submissions enqueue
        assert engine.journal.flush(5.0)
        assert engine.journal.stats()["live"] == 3
        # the crash: the engine dies FIRST (its loop never observes the
        # callers going away — an explicitly cancelled caller would be
        # ANSWERED and legitimately retired), then the callers' futures
        # die with the process
        if engine._loop_task is not None:
            engine._loop_task.cancel()
        for t in tasks:
            t.cancel()
        # no close(): the 'crash' leaves the journal's live set on disk
        TpuServingEngine.reset_instances()

    asyncio.run(crash_phase())

    async def restart_phase():
        engine = TpuServingEngine(
            _base_config(journal_dir=journal_dir, slots=2)
        )
        try:
            # a brand-new submission arrives first; the replay must still
            # serve the recovered work FRONT-of-class
            fresh = await engine.generate(
                "fresh post-restart request", {"max-tokens": 4,
                                               "temperature": 0}
            )
            for _ in range(200):
                if engine.journal.depth() == 0:
                    break
                await asyncio.sleep(0.05)
            stats = engine.journal.stats()
            events = engine.flight.recent_events(0)
            completed = engine.completed_requests
            return fresh, stats, events, completed
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    fresh, stats, events, completed = asyncio.run(restart_phase())
    assert fresh["tokens"]
    # zero silent loss: all three recovered requests replayed + finished,
    # each retired exactly once (+1 retire for the fresh request this
    # process both admitted and served)
    assert stats["replayed"] == 3
    assert stats["retired"] == 4
    assert stats["live"] == 0 and stats["pending_ops"] == 0
    assert completed >= 4  # 3 replays + the fresh request
    assert any(
        e["kind"] == "journal-replay" and e["requests"] == 3
        for e in events
    )
    # a third process finds nothing to replay (exactly-once)
    j = RequestJournal(journal_dir)
    assert j.pending() == []
    j.close()


def test_journal_retires_on_finish_and_fail(run_async, tmp_path):
    """Finished requests retire their entries inline; an engine-level
    failure retires too (the caller was ANSWERED with the error — a
    restart must not replay served failures)."""
    from langstream_tpu.serving.engine import TpuServingEngine
    from langstream_tpu.serving.qos import RateLimited

    journal_dir = str(tmp_path / "j2")

    async def run():
        engine = TpuServingEngine(
            _base_config(journal_dir=journal_dir, slots=2)
        )
        try:
            await engine.generate("finish me", {"max-tokens": 4,
                                                "temperature": 0})
            assert engine.journal.flush(5.0)
            assert engine.journal.stats()["live"] == 0
            # queued work failed explicitly by a drain-expiry shed is
            # answered → retired
            task = asyncio.ensure_future(engine.generate(
                "shed me", {"max-tokens": 64, "temperature": 0}
            ))
            await asyncio.sleep(0)
            engine._fail_inflight(RateLimited("draining", 1.0, "test"))
            with pytest.raises(RateLimited):
                await task
            assert engine.journal.flush(5.0)
            return engine.journal.stats()
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    stats = run_async(run())
    assert stats["live"] == 0
    assert stats["retired"] == 2


# ---------------------------------------------------------------------------
# default config: the hot path stays bit-for-bit
# ---------------------------------------------------------------------------


def test_default_config_hot_path_unchanged(run_async):
    """Fault injection disabled (default) leaves the engine with NO
    injector (one attribute test per seam), zero survival counters, and
    greedy output identical to an engine whose armed plan never fires."""
    from langstream_tpu.serving.engine import TpuServingEngine

    prompts = [f"default path request {i}" for i in range(3)]

    async def run(cfg):
        engine = TpuServingEngine(cfg)
        try:
            outs = [
                await engine.generate(p, {"max-tokens": 8,
                                          "temperature": 0})
                for p in prompts
            ]
            return outs, engine._faults, engine.stats()["survival"]
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    outs_default, injector, survival = run_async(run(_base_config()))
    assert injector is None
    assert survival["shrinks"] == 0 and survival["restores"] == 0
    assert "journal" not in survival and "faults" not in survival

    inert = (FaultPlan(site="pool-grow", after=10**9),)
    outs_armed, injector_armed, _ = run_async(
        run(_base_config(faults=inert))
    )
    assert injector_armed is not None
    assert [o["tokens"] for o in outs_default] == [
        o["tokens"] for o in outs_armed
    ]
    assert [o["text"] for o in outs_default] == [
        o["text"] for o in outs_armed
    ]


def test_pool_grow_events_carry_bytes(run_async):
    from langstream_tpu.serving.engine import TpuServingEngine

    async def run():
        engine = TpuServingEngine(_base_config())
        try:
            await engine.generate("grow the pool please",
                                  {"max-tokens": 40, "temperature": 0})
            return (
                engine.flight.recent_events(0), engine._kv_block_bytes
            )
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    events, block_bytes = run_async(run())
    grows = [e for e in events if e["kind"] == "pool-grow"]
    assert grows, "decode growth must emit pool-grow"
    for e in grows:
        assert e["blocks"] >= 1
        assert e["bytes"] == e["blocks"] * block_bytes


def test_memory_ledger_reflects_withheld_budget(run_async):
    """The HBM ledger sums exactly across shrink/restore: the pool's
    bytes never move (the arrays stay allocated), and the withheld
    budget is reported as a sub-owner."""
    from langstream_tpu.serving.engine import TpuServingEngine

    async def run():
        engine = TpuServingEngine(_base_config())
        try:
            before = engine._memory_ledger()
            engine.block_mgr.reduce_budget(4)
            during = engine._memory_ledger()
            engine.block_mgr.restore_budget()
            after = engine._memory_ledger()
            return before, during, after, engine._kv_block_bytes
        finally:
            await engine.close()
            TpuServingEngine.reset_instances()

    before, during, after, block_bytes = run_async(run())
    for ledger in (before, during, after):
        owners = ledger["hbm_bytes_by_owner"]
        if ledger["limit_bytes"] is not None:
            assert sum(owners.values()) == ledger["limit_bytes"]
    assert before["kv_pool_withheld_bytes"] == 0
    assert during["kv_pool_withheld_bytes"] == 4 * block_bytes
    assert after["kv_pool_withheld_bytes"] == 0
    # the pool owner itself is constant across shrink/restore
    assert (
        before["hbm_bytes_by_owner"]["kv-pool"]
        == during["hbm_bytes_by_owner"]["kv-pool"]
        == after["hbm_bytes_by_owner"]["kv-pool"]
    )


# ---------------------------------------------------------------------------
# health predicate + autoscaler signal
# ---------------------------------------------------------------------------


def test_shrink_pressure_predicate():
    from langstream_tpu.serving.health import shrink_pressure

    now = 1000.0
    mk = lambda age, rec=10.0: {
        "kind": "pool-shrink", "m_s": now - age, "recovery_s": rec,
    }
    # one shrink: adapting, not degraded
    assert shrink_pressure([mk(1.0)], now) is None
    # two inside one recovery window: sustained pressure
    reason = shrink_pressure([mk(8.0), mk(1.0)], now)
    assert reason and "pool-shrink" in reason
    # two but far apart relative to the window: quiet
    assert shrink_pressure([mk(50.0), mk(1.0)], now) is None
    # stampless payloads never flag
    assert shrink_pressure(
        [{"kind": "pool-shrink", "recovery_s": 10.0}], now
    ) is None


def test_watchdog_degrades_on_repeated_shrinks():
    from langstream_tpu.serving.health import EngineWatchdog

    clock = [100.0]
    wd = EngineWatchdog(wedge_window_s=60.0, clock=lambda: clock[0])
    wd.beat(0)
    events = [
        {"kind": "pool-shrink", "m_s": 99.0, "recovery_s": 30.0},
        {"kind": "pool-shrink", "m_s": 99.5, "recovery_s": 30.0},
    ]
    verdict = wd.evaluate(queued=0, occupancy=0, events=events)
    assert verdict["state"] == "degraded"
    assert any("pool-shrink" in r for r in verdict["reasons"])


def test_autoscaler_scales_up_on_pool_shrink_pressure():
    from langstream_tpu.controlplane.autoscaler import (
        AutoscaleSpec,
        FleetAutoscaler,
        ReplicaObservation,
        observation_from_summary,
    )

    # the observation folds the flight-summary survival section
    obs = observation_from_summary(
        "pod-0",
        [{
            "model": "tiny", "slots": 4,
            "survival": {"shrinks": 2, "withheld_blocks": 5},
        }],
    )
    assert obs.pool_shrinks == 2 and obs.budget_withheld
    assert obs.to_dict()["budget_withheld"] is True

    spec = AutoscaleSpec.from_dict(
        {"max-replicas": 4, "scale-up-window-s": 0, "cooldown-s": 0}
    )
    assert spec.pool_shrink  # default on, kebab round-trips
    assert AutoscaleSpec.from_dict(spec.to_dict()).pool_shrink
    scaler = FleetAutoscaler(spec, backend=None, clock=lambda: 100.0)
    decision = scaler.decide(
        [ReplicaObservation(replica="pod-0", budget_withheld=True)],
        now=100.0,
    )
    assert decision.action == "up"
    assert any("pool-shrink" in r for r in decision.reasons)
    # the signal is declinable
    off = AutoscaleSpec.from_dict(
        {"pool-shrink": False, "scale-up-window-s": 0, "cooldown-s": 0}
    )
    scaler_off = FleetAutoscaler(off, backend=None, clock=lambda: 100.0)
    decision_off = scaler_off.decide(
        [ReplicaObservation(replica="pod-0", budget_withheld=True)],
        now=100.0,
    )
    assert decision_off.action == "none"


# ---------------------------------------------------------------------------
# engine_top: survival panel + thrash analyzer
# ---------------------------------------------------------------------------


def test_engine_top_renders_survival_panel():
    top = _load_tool("engine_top")
    out = top.render([{
        "model": "tiny", "slots": 4,
        "summary": {"totals": {}, "window": {}},
        "survival": {
            "shrinks": 2, "restores": 1, "shrink_preempted": 3,
            "budget_blocks": 18, "configured_blocks": 24,
            "withheld_blocks": 6, "withheld_bytes": 98304,
            "recovering": True, "recovery_s": 30.0,
            "journal": {"live": 2, "replayed": 5},
        },
        "events": [{
            "kind": "pool-shrink", "t_ms": 1000.0, "site": "pool-grow",
            "withheld_blocks": 3, "freed_blocks": 6, "preempted": 2,
            "budget_blocks": 18, "configured_blocks": 24,
        }],
    }])
    assert "18/24 blocks" in out
    assert "WITHHELD 6" in out
    assert "shrinks 2" in out
    assert "journal 2 live/5 replayed" in out
    assert "site pool-grow" in out


def test_engine_top_analyze_flags_shrink_thrash():
    top = _load_tool("engine_top")
    events = [
        {"kind": "pool-shrink", "t_ms": 1000.0 + i * 2000.0,
         "recovery_s": 30.0}
        for i in range(3)
    ]
    flags = top._anomalies({
        "summary": {"totals": {}, "window": {}},
        "events": events,
        "survival": {"withheld_blocks": 4, "configured_blocks": 24},
    })
    assert any("shrink-recover thrash" in f for f in flags)
    assert any("KV budget withheld" in f for f in flags)
    # two shrinks, or three spread far beyond the window, stay quiet
    spread = [
        {"kind": "pool-shrink", "t_ms": 1000.0 + i * 120000.0,
         "recovery_s": 30.0}
        for i in range(3)
    ]
    flags_quiet = top._anomalies({
        "summary": {"totals": {}, "window": {}},
        "events": spread,
    })
    assert not any("thrash" in f for f in flags_quiet)


# ---------------------------------------------------------------------------
# bench phase + perf_diff directions
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_oom_storm_bench_phase_smoke():
    gateway_bench = _load_tool("gateway_bench")
    out = asyncio.run(
        gateway_bench.run_oom_storm_phase(
            requests=8, max_tokens=8, burst_after=2, burst_count=1
        )
    )
    assert out["submitted"] == 8
    assert out["zero_silent_loss"] is True
    assert out["completed"] + out["shed"] == 8
    assert out["oom_storm_shrinks"] >= 1
    assert out["budget_recovered"] is True
    assert out["faults_injected"] >= 1
    assert out["shrink_evidence"][0]["site"] == "pool-grow"


def test_perf_diff_extracts_oom_storm_metrics():
    perf_diff = _load_tool("perf_diff")
    record = {
        "schema": 2,
        "value": 100.0,
        "detail": {
            "oom_storm": {
                "oom_storm_shed_rate": 0.1,
                "oom_storm_completed_fraction": 0.9,
                "oom_storm_shrinks": 2,
                "oom_storm_ttft_p50_s": 0.5,
                "oom_storm_ttft_p99_s": 1.5,
            }
        },
    }
    metrics = perf_diff.extract_metrics(record)["metrics"]
    assert metrics["oom_storm_shed_rate"] == 0.1
    assert metrics["oom_storm_shrinks"] == 2
    # directions are declared, worse-direction semantics verified
    assert perf_diff.METRICS["oom_storm_shed_rate"] == "up"
    assert perf_diff.METRICS["oom_storm_completed_fraction"] == "down"
    base = {"schema": 2, "value": 100.0,
            "detail": {"oom_storm": {"oom_storm_shed_rate": 0.05}}}
    new = {"schema": 2, "value": 100.0,
           "detail": {"oom_storm": {"oom_storm_shed_rate": 0.5}}}
    results, regressed = perf_diff.diff_payloads(
        [("base", base), ("new", new)]
    )
    assert regressed
    (_, _, result), = results
    assert any(
        r["metric"] == "oom_storm_shed_rate"
        for r in result["regressions"]
    )
