"""Fleet plane tests: autoscaler, drain-before-terminate, replica router.

Layers covered: AutoscaleSpec parsing/validation (deploy → 400 on a
malformed section), the decide() hysteresis/cooldown state machine on a
fake clock, the step() apply path (drain-before-terminate ordering,
cooldown refusals in the decision ring), the engine drain() round trip —
including the acceptance byte-identity: a generation preempted by drain
completes identically to an undisturbed run — the pod ``/drain``
endpoint + readiness gating, the k8s manifests (preStop hook, PDB) and
the operator's autoscaled-replica preservation, the compute runtime's
scale/observe/drain surface over the in-memory kube API, the gateway
replica router (least-loaded, affinity, never a draining/wedged/
unreachable member) with the runner-side header honoring, the
``engine_top`` fleet panel + scale-thrash flag, and the chaos e2e:
flood until scale-up fires over a fake kube, then starve until
scale-down drains the victim, with zero lost requests.
"""

import asyncio
import importlib.util
import json
import socket
from pathlib import Path

import aiohttp
import pytest

from langstream_tpu.controlplane.autoscaler import (
    AUTOSCALE_ANNOTATION,
    AutoscaleSpec,
    Decision,
    FleetAutoscaler,
    ReplicaObservation,
    application_autoscale_spec,
    observation_from_summary,
    validate_application_autoscale,
)
from langstream_tpu.gateway.router import (
    BOUNCE_HEADER,
    REPLICA_HEADER,
    ReplicaRouter,
    split_replica_target,
)
from langstream_tpu.k8s.client import InMemoryKubeApi


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _close_engines():
    from langstream_tpu.serving.engine import TpuServingEngine

    with TpuServingEngine._instances_lock:
        engines = list(TpuServingEngine._instances.values())
    for engine in engines:
        await engine.close()


def _load_engine_top():
    path = Path(__file__).resolve().parents[1] / "tools" / "engine_top.py"
    spec = importlib.util.spec_from_file_location("engine_top", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _fleet_config():
    from langstream_tpu.serving.engine import ServingConfig

    # f32 + paged: greedy streams are exactly shape-independent, so a
    # drain-preempted request's resume is bit-identical (the same
    # posture test_qos pins for KV-pressure preemption)
    return ServingConfig(
        model="tiny", slots=2, max_seq_len=128, decode_chunk=4,
        model_dtype="float32", kv_layout="paged", kv_block_size=16,
        kv_pool_blocks=16, prefix_cache=False,
    )


# --------------------------------------------------------------------------
# AutoscaleSpec parsing + deploy validation
# --------------------------------------------------------------------------


def test_autoscale_spec_roundtrip():
    spec = AutoscaleSpec.from_dict(
        {
            "min-replicas": 2,
            "max-replicas": 6,
            "scale-up-window-s": 10,
            "scale-down-window-s": 60,
            "cooldown-s": 30,
            "queue-depth-per-replica": 4,
            "agent": "ai",
        }
    )
    assert spec.min_replicas == 2 and spec.max_replicas == 6
    assert spec.agent == "ai"
    assert AutoscaleSpec.from_dict(spec.to_dict()) == spec
    assert AutoscaleSpec.from_dict(None) is None
    assert AutoscaleSpec.from_dict(spec) is spec


@pytest.mark.parametrize(
    "bad, msg",
    [
        ({"min-replicas": 0}, "min-replicas must be >= 1"),
        ({"min-replicas": 3, "max-replicas": 2}, "must be >= "),
        ({"cooldown-s": -1}, "cooldown-s must be >= 0"),
        ({"drain-grace-s": 0}, "drain-grace-s must be > 0"),
        ({"kv-reserved": 1.5}, "kv-reserved must be in"),
        ({"idle-occupancy": 1.0}, "idle-occupancy must be in"),
        ({"queue-depth-per-replica": 0}, "must be > 0"),
        ({"shed-delta": 0}, "shed-delta must be >= 1"),
        ({"replicas": 4}, "unknown key"),
        ("everything", "must be a mapping"),
    ],
)
def test_autoscale_spec_validation_rejects(bad, msg):
    with pytest.raises(ValueError, match=msg):
        AutoscaleSpec.from_dict(bad)


def test_validate_application_autoscale():
    class _Res:
        type = "tpu-serving-configuration"
        configuration = {"autoscale": {"min-replicas": 0}}

    class _App:
        resources = {"tpu": _Res()}

    with pytest.raises(ValueError, match="tpu.*invalid autoscale"):
        validate_application_autoscale(_App())
    _Res.configuration = {"autoscale": None}
    validate_application_autoscale(_App())  # missing section is fine
    assert application_autoscale_spec(_App()) is None
    _Res.configuration = {"autoscale": {"max-replicas": 3}}
    spec = application_autoscale_spec(_App())
    assert spec is not None and spec.max_replicas == 3
    _Res.configuration = {"autoscale": {"enabled": False}}
    assert application_autoscale_spec(_App()) is None


# --------------------------------------------------------------------------
# decide(): hysteresis + signals (fake clock, pure)
# --------------------------------------------------------------------------


def _scaler(spec_dict, clock, backend=None):
    return FleetAutoscaler(
        AutoscaleSpec.from_dict(spec_dict), backend, clock=lambda: clock[0]
    )


def _obs(replica="app-0", **kw):
    return {"replica": replica, "slots": 8, **kw}


def test_decide_pressure_needs_a_full_window_and_blips_reset():
    clock = [0.0]
    scaler = _scaler(
        {"max-replicas": 3, "scale-up-window-s": 10,
         "queue-depth-per-replica": 4},
        clock,
    )
    busy = [_obs(queued=40)]
    calm = [_obs(queued=0)]
    assert scaler.decide(busy).action == "none"  # streak just began
    clock[0] = 5.0
    assert scaler.decide(busy).action == "none"  # half a window
    clock[0] = 7.0
    assert scaler.decide(calm).action == "none"  # blip: streak resets
    clock[0] = 12.0
    assert scaler.decide(busy).action == "none"  # fresh streak at t=12
    clock[0] = 23.0
    decision = scaler.decide(busy)
    assert decision.action == "up" and decision.target == 2
    assert any("queue depth" in r for r in decision.reasons)
    assert decision.evidence["pressure_for_s"] >= 10.0


def test_decide_clamps_at_max_and_reports_why():
    clock = [0.0]
    scaler = _scaler(
        {"max-replicas": 2, "scale-up-window-s": 0,
         "queue-depth-per-replica": 1},
        clock,
    )
    fleet = [_obs("a-0", queued=9), _obs("a-1", queued=9)]
    decision = scaler.decide(fleet)
    assert decision.action == "none"
    assert any("max-replicas" in r for r in decision.reasons)


def test_decide_signals_kv_shed_slo_degraded():
    clock = [0.0]
    scaler = _scaler({"scale-up-window-s": 0}, clock)
    # KV saturation on one replica
    d = scaler.decide([_obs(kv_used=0.99), _obs("app-1")])
    assert d.action == "up" and any("KV reservation" in r for r in d.reasons)
    # shed delta between observations
    scaler2 = _scaler({"scale-up-window-s": 0}, clock)
    assert scaler2.decide([_obs(shed_total=5)]).action == "none"  # baseline
    d = scaler2.decide([_obs(shed_total=9)])
    assert d.action == "up" and any("shed" in r for r in d.reasons)
    # SLO fast burn
    scaler3 = _scaler({"scale-up-window-s": 0}, clock)
    d = scaler3.decide([_obs(slo_alerting=("ttft",))])
    assert d.action == "up" and any("SLO fast burn" in r for r in d.reasons)
    # degraded health (recompile storm / overlap collapse predicates)
    scaler4 = _scaler({"scale-up-window-s": 0}, clock)
    d = scaler4.decide([_obs(state="degraded")])
    assert d.action == "up" and any("degraded" in r for r in d.reasons)


def test_decide_wedged_replicas_do_not_count_as_capacity():
    """A wedged pod's queue is meaningless and its 'capacity' serves
    nothing: per-replica thresholds divide by HEALTHY replicas only."""
    clock = [0.0]
    scaler = _scaler(
        {"scale-up-window-s": 0, "queue-depth-per-replica": 4,
         "max-replicas": 4},
        clock,
    )
    fleet = [_obs("a-0", queued=5), _obs("a-1", state="wedged", queued=0)]
    decision = scaler.decide(fleet)
    assert decision.action == "up"  # 5 queued / 1 healthy > 4


def test_decide_scale_down_needs_idle_window_and_full_visibility():
    clock = [0.0]
    scaler = _scaler(
        {"min-replicas": 1, "max-replicas": 3, "scale-down-window-s": 20,
         "idle-occupancy": 0.2},
        clock,
    )
    idle = [_obs("a-0", queued=0, occupancy=0), _obs("a-1", queued=0)]
    assert scaler.decide(idle).action == "none"
    clock[0] = 25.0
    decision = scaler.decide(idle)
    assert decision.action == "down" and decision.target == 1
    # an unreachable replica blocks scale-down: the missing pod may hold
    # work the observation cannot see
    scaler2 = _scaler(
        {"scale-down-window-s": 0, "idle-occupancy": 0.2}, clock
    )
    blocked = [_obs("a-0"), {"replica": "a-1", "unreachable": True}]
    assert scaler2.decide(blocked).action == "none"
    # at min-replicas nothing fires
    scaler3 = _scaler({"scale-down-window-s": 0}, clock)
    assert scaler3.decide([_obs("a-0")]).action == "none"


# --------------------------------------------------------------------------
# step(): cooldown gate + drain-before-terminate ordering
# --------------------------------------------------------------------------


class _ScriptedBackend:
    """Fake backend with a scripted observation list and a call log."""

    def __init__(self, observations):
        self.observations = observations
        self.calls = []

    def observe(self):
        return self.observations

    def set_replicas(self, n):
        self.calls.append(("set_replicas", n))

    def drain(self, replica, grace_s):
        self.calls.append(("drain", replica))
        return {"requeued": 1, "completed": 1, "shed": 0}


def test_step_scales_up_once_then_cooldown_refuses(run_async):
    clock = [100.0]
    backend = _ScriptedBackend([_obs(queued=50)])
    scaler = FleetAutoscaler(
        AutoscaleSpec.from_dict(
            {"max-replicas": 3, "scale-up-window-s": 0, "cooldown-s": 60,
             "queue-depth-per-replica": 4}
        ),
        backend,
        clock=lambda: clock[0],
    )

    async def main():
        entry = await scaler.step()
        assert entry["outcome"] == "scaled" and entry["action"] == "up"
        assert backend.calls == [("set_replicas", 2)]
        # pressure persists; the cooldown refuses the second write and
        # the refusal lands in the decision ring with the remaining time
        clock[0] = 110.0
        entry = await scaler.step()
        assert entry["outcome"] == "cooldown"
        assert entry["cooldown_remaining_s"] == pytest.approx(50.0)
        assert backend.calls == [("set_replicas", 2)]
        status = scaler.status()
        assert status["scale_ups"] == 1
        assert [d["outcome"] for d in status["decisions"]] == [
            "scaled", "cooldown",
        ]
        json.dumps(status)  # the /autoscaler route serves this verbatim

    run_async(main())


def test_step_drains_highest_ordinal_before_decrementing(run_async):
    clock = [0.0]
    backend = _ScriptedBackend(
        [_obs("app-0"), _obs("app-1"), _obs("app-2")]
    )
    scaler = FleetAutoscaler(
        AutoscaleSpec.from_dict(
            {"min-replicas": 1, "max-replicas": 3,
             "scale-down-window-s": 0, "cooldown-s": 0}
        ),
        backend,
        clock=lambda: clock[0],
    )

    async def main():
        entry = await scaler.step()
        assert entry["action"] == "down" and entry["outcome"] == "scaled"
        # the victim is the highest ordinal (the pod the STS controller
        # deletes first) and it drains BEFORE the replica write
        assert backend.calls == [("drain", "app-2"), ("set_replicas", 2)]
        assert entry["victim"] == "app-2"
        assert entry["drain"]["requeued"] == 1

    run_async(main())


def test_step_scale_down_write_failure_retries_without_redrain(run_async):
    """A scale-down whose drain succeeded but whose replica write failed
    must not strand the drained pod as a zombie: the failure lands in
    the decision ring WITH the drain evidence, and the next tick retries
    the write alone — no second drain, no waiting out a fresh idle
    streak around a pod that now sheds everything it's assigned."""
    clock = [0.0]

    class _FlakyBackend(_ScriptedBackend):
        fail_next_set = True

        def set_replicas(self, n):
            if self.fail_next_set:
                self.fail_next_set = False
                raise RuntimeError("k8s api momentarily away")
            super().set_replicas(n)

    backend = _FlakyBackend([_obs("app-0"), _obs("app-1"), _obs("app-2")])
    scaler = FleetAutoscaler(
        AutoscaleSpec.from_dict(
            {"min-replicas": 1, "max-replicas": 3,
             "scale-down-window-s": 0, "cooldown-s": 30}
        ),
        backend,
        clock=lambda: clock[0],
    )

    async def main():
        with pytest.raises(RuntimeError):
            await scaler.step()
        assert backend.calls == [("drain", "app-2")]
        failed = scaler.decisions[-1]
        assert failed["outcome"] == "apply-failed"
        assert failed["drain"]["requeued"] == 1
        # next tick: the write lands exactly once, with NO second drain
        clock[0] = 5.0
        entry = await scaler.step()
        assert entry["outcome"] == "scaled" and entry.get("retried") is True
        assert backend.calls == [("drain", "app-2"), ("set_replicas", 2)]
        assert scaler.scale_downs == 1
        # the cooldown clock starts when the scale LANDED, not when the
        # (possibly grace-budget-long) drain began
        assert scaler._last_scale_t == 5.0

    run_async(main())


def test_pending_apply_tick_still_feeds_the_observation_hook(run_async):
    """A k8s-API flake mid scale-down must not starve the gateway
    router's fleet feed: the retry tick runs the observation hook and
    refreshes the /autoscaler snapshot before finishing the apply."""
    clock = [0.0]

    class _FlakyBackend(_ScriptedBackend):
        fail_next_set = True

        def set_replicas(self, n):
            if self.fail_next_set:
                self.fail_next_set = False
                raise RuntimeError("k8s api momentarily away")
            super().set_replicas(n)

    backend = _FlakyBackend([_obs("app-0"), _obs("app-1")])
    fed = []
    scaler = FleetAutoscaler(
        AutoscaleSpec.from_dict(
            {"min-replicas": 1, "max-replicas": 2,
             "scale-down-window-s": 0, "cooldown-s": 0}
        ),
        backend,
        clock=lambda: clock[0],
        on_observation=lambda snap: fed.append(len(snap)),
    )

    async def main():
        with pytest.raises(RuntimeError):
            await scaler.step()
        clock[0] = 5.0
        entry = await scaler.step()
        assert entry["outcome"] == "scaled"
        assert fed == [2, 2]  # both ticks fed the router
        assert scaler.status()["replicas"]  # snapshot stayed fresh

    run_async(main())


def test_refusal_decisions_collapse_instead_of_flooding_the_ring(run_async):
    """A fleet pinned at max-replicas under sustained pressure records
    one refusal per 5 s tick: steady-state clamps collapse into their
    transition entry (repeats + last_m_s) so the bounded ring keeps the
    scale/drain history an operator needs post-incident."""
    clock = [0.0]
    backend = _ScriptedBackend([_obs(queued=50)])
    scaler = FleetAutoscaler(
        AutoscaleSpec.from_dict(
            {"min-replicas": 1, "max-replicas": 1, "scale-up-window-s": 0,
             "queue-depth-per-replica": 4}
        ),
        backend,
        clock=lambda: clock[0],
    )

    async def main():
        for tick in range(5):
            clock[0] = tick * 5.0
            entry = await scaler.step()
            assert entry["outcome"] == "clamped"
        assert len(scaler.decisions) == 1
        only = scaler.decisions[0]
        assert only["repeats"] == 4
        assert only["last_m_s"] == 20.0
        assert only["m_s"] == 0.0  # the transition stamp survives

    run_async(main())


# --------------------------------------------------------------------------
# engine drain: byte-identity, shed semantics, grace expiry
# --------------------------------------------------------------------------


def test_drain_grace_expiry_sheds_leftovers_explicitly(run_async, monkeypatch):
    """A wedged loop (admission gated shut) cannot finish its backlog:
    the grace budget expires and every leftover fails with RateLimited
    (retry_after > 0) — explicitly shed, never silently lost."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine
    from langstream_tpu.serving.qos import RateLimited

    async def main():
        engine = TpuServingEngine(
            ServingConfig(model="tiny", slots=2, max_seq_len=64, decode_chunk=4)
        )
        try:
            gate = asyncio.Event()
            real_admit = engine._admit

            async def wedged_admit(loop):
                await gate.wait()
                await real_admit(loop)

            monkeypatch.setattr(engine, "_admit", wedged_admit)
            stuck = asyncio.ensure_future(
                engine.generate("stuck request", {"max-tokens": 4})
            )
            await asyncio.sleep(0.05)
            report = await engine.drain(grace_s=0.3)
            assert report["shed"] >= 1
            with pytest.raises(RateLimited) as exc:
                await stuck
            assert exc.value.retry_after > 0
            gate.set()
        finally:
            await engine.close()

    run_async(main())


def test_drain_engines_budget_is_shared_across_engines(run_async, monkeypatch):
    """grace_s budgets the WHOLE pod: every preStop/termination-grace/
    drain-HTTP timeout upstream is sized to one grace, so a multi-model
    pod's engines split the deadline instead of each taking the full
    budget (2 engines x 45 s would blow the 90 s termination grace with
    nothing left for the broker drain)."""
    from langstream_tpu.serving import engine as engine_mod

    class _FakeEngine:
        def __init__(self, name, cost_s):
            self.config = type("C", (), {"model": name})()
            self.cost_s = cost_s
            self.granted = None

        async def drain(self, grace_s):
            self.granted = grace_s
            await asyncio.sleep(self.cost_s)
            return {"requeued": 0, "completed": 0, "shed": 0}

    slow, fast = _FakeEngine("slow", 0.2), _FakeEngine("fast", 0.0)
    monkeypatch.setattr(
        engine_mod.TpuServingEngine, "_instances",
        {"a": slow, "b": fast},
    )

    async def main():
        reports = await engine_mod.drain_engines(grace_s=1.0)
        assert set(reports) == {"slow", "fast"}
        assert slow.granted == pytest.approx(1.0, abs=0.05)
        # the first engine's spend came out of the second's budget
        assert 0.5 <= fast.granted <= 0.9

    run_async(main())


def test_healthz_fails_an_orphaned_drain(run_async):
    """A drain is supposed to end in termination. When it never comes
    (control plane died mid scale-down, stray /drain call), the pod must
    not be a permanent zero-capacity zombie — liveness flips 503 once
    the drain has outlived any budget that could still be waiting on it,
    and the kubelet recycles the pod back into capacity."""
    from langstream_tpu.runtime.pod import PodHealth, _probe_healthz

    async def main():
        await _close_engines()
        health = PodHealth()
        health.agent_ready = True
        status, _ = _probe_healthz(health)
        assert status == 200
        health.mark_draining(grace_s=30)
        status, body = _probe_healthz(health)
        assert status == 200  # a fresh drain is not an orphan
        assert body["drain_expired"] is False
        health.draining_since -= 1000  # far past 3x grace
        status, body = _probe_healthz(health)
        assert status == 503
        assert body["drain_expired"] is True
        assert body["status"] == "drain-expired"

    run_async(main())


def test_pod_drain_endpoint_flips_readiness(run_async, monkeypatch):
    """The /drain endpoint (the preStop hook's target): answers the
    per-model drain reports, flips /ready to 503 with a draining
    blocker, and leaves /healthz alone (draining is not wedged)."""
    from langstream_tpu.runtime.pod import PodHealth, _serve_info
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def main():
        await _close_engines()
        engine = TpuServingEngine.get_or_create(
            ServingConfig(model="tiny", slots=2, max_seq_len=64, decode_chunk=4)
        )
        port = free_port()
        monkeypatch.setenv("LS_HTTP_PORT", str(port))
        health = PodHealth()
        health.agent_ready = True
        server = await _serve_info(None, health=health)
        session = aiohttp.ClientSession()
        base = f"http://127.0.0.1:{port}"
        try:
            # an idle engine drains instantly — the endpoint semantics
            # (reports + readiness flip) are what this test pins; the
            # loaded-drain path is the chaos e2e's job
            async with session.get(f"{base}/ready") as resp:
                assert resp.status == 200
            async with session.get(f"{base}/drain?grace-s=30") as resp:
                assert resp.status == 200
                body = await resp.json()
            assert body["draining"] is True
            assert body["engines"]["tiny"]["shed"] == 0
            async with session.get(f"{base}/ready") as resp:
                assert resp.status == 503
                blockers = (await resp.json())["blockers"]
            assert "draining" in blockers
            assert any("engine:tiny:draining" == b for b in blockers)
            async with session.get(f"{base}/healthz") as resp:
                assert resp.status == 200  # draining is not wedged
        finally:
            await session.close()
            server.close()
            await engine.close()

    run_async(main())


# --------------------------------------------------------------------------
# k8s manifests: preStop + PDB; operator preservation; compute surface
# --------------------------------------------------------------------------


def _agent_cr(parallelism=1):
    from langstream_tpu.k8s.crds import (
        AgentCustomResource,
        AgentResourcesCR,
        AgentSpec,
    )

    return AgentCustomResource(
        name="chat-ai",
        namespace="langstream-t1",
        spec=AgentSpec(
            tenant="t1",
            application_id="chat",
            agent_id="ai",
            image="img",
            agent_config_secret_ref="cfg",
            agent_config_secret_ref_checksum="abc",
            resources=AgentResourcesCR(parallelism=parallelism),
        ),
    )


def test_statefulset_prestop_drain_and_pdb():
    from langstream_tpu.k8s.resources import AgentResourcesFactory

    sts = AgentResourcesFactory.generate_statefulsets(_agent_cr())[0]
    pod_spec = sts["spec"]["template"]["spec"]
    container = pod_spec["containers"][0]
    pre_stop = container["lifecycle"]["preStop"]["httpGet"]
    assert pre_stop["path"].startswith("/drain?grace-s=")
    assert pre_stop["port"] == 8080
    # the kubelet must not SIGKILL a pod mid-requeue: termination grace
    # strictly exceeds the drain budget the hook hands the engines
    grace = float(pre_stop["path"].split("=")[1])
    assert pod_spec["terminationGracePeriodSeconds"] > grace

    pdbs = AgentResourcesFactory.generate_pod_disruption_budgets(_agent_cr())
    assert len(pdbs) == 1
    pdb = pdbs[0]
    assert pdb["kind"] == "PodDisruptionBudget"
    assert pdb["spec"]["maxUnavailable"] == 1
    assert pdb["spec"]["selector"] == sts["spec"]["selector"]
    assert pdb["metadata"]["name"] == sts["metadata"]["name"]


def test_operator_preserves_autoscaled_replicas_and_applies_pdb():
    from langstream_tpu.k8s.operator import AgentController

    api = InMemoryKubeApi()
    controller = AgentController(api)
    cr = _agent_cr(parallelism=1)
    cr_dict = {
        "apiVersion": "langstream.tpu/v1alpha1",
        "kind": "Agent",
        "metadata": {"name": cr.name, "namespace": cr.namespace},
        "spec": {
            "tenant": "t1",
            "applicationId": "chat",
            "agentId": "ai",
            "image": "img",
            "agentConfigSecretRef": "cfg",
            "agentConfigSecretRefChecksum": "abc",
            "resources": {"parallelism": 1, "size": 1},
        },
    }
    api.apply(cr_dict)
    controller.reconcile(api.get("Agent", cr.namespace, cr.name))
    sts = api.get("StatefulSet", cr.namespace, "chat-ai")
    assert sts["spec"]["replicas"] == 1
    assert api.get("PodDisruptionBudget", cr.namespace, "chat-ai") is not None

    # the autoscaler scales to 3 and stamps its annotation ...
    sts["spec"]["replicas"] = 3
    sts["metadata"].setdefault("annotations", {})[AUTOSCALE_ANNOTATION] = "true"
    api.apply(sts)
    # ... and the next reconcile preserves the live count instead of
    # resetting it to the CR's parallelism
    controller.reconcile(api.get("Agent", cr.namespace, cr.name))
    sts = api.get("StatefulSet", cr.namespace, "chat-ai")
    assert sts["spec"]["replicas"] == 3
    assert sts["metadata"]["annotations"][AUTOSCALE_ANNOTATION] == "true"

    # without the stamp, the CR's parallelism wins again (a manual
    # kubectl scale on a non-autoscaled app is reverted by design)
    del sts["metadata"]["annotations"][AUTOSCALE_ANNOTATION]
    sts["spec"]["replicas"] = 5
    api.apply(sts)
    controller.reconcile(api.get("Agent", cr.namespace, cr.name))
    assert api.get("StatefulSet", cr.namespace, "chat-ai")["spec"][
        "replicas"
    ] == 1


def test_compute_scale_observe_drain_surface():
    from langstream_tpu.k8s.compute import (
        KubernetesComputeRuntime,
        StatefulSetFleetBackend,
    )

    api = InMemoryKubeApi()
    api.apply(
        {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": "chat-ai",
                "namespace": "langstream-t1",
                "labels": {"langstream-application": "chat"},
            },
            "spec": {
                "serviceName": "chat-ai",
                "replicas": 2,
                "template": {"spec": {"containers": [{"name": "runtime"}]}},
            },
        }
    )
    # a multi-host slice STS must never be offered for scaling: its
    # replica count is the slice's HOST count, not serving capacity
    api.apply(
        {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": "chat-big-r0",
                "namespace": "langstream-t1",
                "labels": {"langstream-application": "chat"},
            },
            "spec": {
                "serviceName": "chat-big",
                "replicas": 2,
                "template": {
                    "spec": {
                        "containers": [
                            {
                                "name": "runtime",
                                "env": [
                                    {"name": "LS_SLICE_HOSTS", "value": "2"}
                                ],
                            }
                        ]
                    }
                },
            },
        }
    )
    rt = KubernetesComputeRuntime.__new__(KubernetesComputeRuntime)
    rt.api = api
    rt.logs = {}
    scalable = rt.serving_statefulsets("t1", "chat")
    assert [s["metadata"]["name"] for s in scalable] == ["chat-ai"]

    rt.scale_statefulset("t1", "chat", "chat-ai", 3)
    sts = api.get("StatefulSet", "langstream-t1", "chat-ai")
    assert sts["spec"]["replicas"] == 3
    assert sts["metadata"]["annotations"][AUTOSCALE_ANNOTATION] == "true"

    # fleet_observe folds the /flight/summary fan-in; unreachable pods
    # surface as unreachable members of the right STS only
    rt._pod_json_fanin = lambda t, n, p: [
        (
            "chat-ai-0",
            [
                {
                    "model": "tiny",
                    "slots": 8,
                    "scheduler": {
                        "policy": "qos", "depth": 5, "shed": 2,
                        "classes": {"interactive": {"depth": 3}},
                    },
                    "health": {
                        "state": "ok", "occupancy": 4, "draining": False,
                    },
                    "slo": {"alerting": ["ttft"]},
                    "summary": {"window": {"kv_used_ratio_last": 0.97}},
                }
            ],
        ),
        ("chat-ai-1", None),
        ("chat-big-r0-0", [{"model": "big"}]),
    ]
    obs = rt.fleet_observe("t1", "chat", "chat-ai")
    assert len(obs) == 2
    first = next(o for o in obs if o["replica"] == "chat-ai-0")
    assert first["queued"] == 5 and first["queue_interactive"] == 3
    assert first["occupancy"] == 4 and first["slots"] == 8
    assert first["kv_used"] == 0.97 and first["shed_total"] == 2
    assert first["slo_alerting"] == ["ttft"]
    assert next(o for o in obs if o["replica"] == "chat-ai-1")["unreachable"]

    # the lazy backend resolves once the operator materialized the STS
    backend = StatefulSetFleetBackend(rt, "t1", "chat", None)
    assert backend.resolve() == "chat-ai"
    assert len(backend.observe()) == 2


def test_observation_from_summary_unreachable_and_worst_state():
    assert observation_from_summary("p-0", None).unreachable is True
    obs = observation_from_summary(
        "p-0",
        [
            {"model": "a", "health": {"state": "ok", "occupancy": 1}},
            {
                "model": "b",
                "health": {"state": "degraded", "draining": True},
                "drain": {"shed": 3},
            },
        ],
    )
    assert obs.state == "degraded" and obs.draining is True
    assert obs.shed_total == 3 and obs.occupancy == 1
    assert observation_from_summary(
        "p-0", [], healthz={"status": "wedged"}
    ).state == "wedged"


# --------------------------------------------------------------------------
# replica router + header honoring
# --------------------------------------------------------------------------


def test_router_picks_least_loaded_and_skips_ineligible():
    clock = [0.0]
    router = ReplicaRouter(fresh_s=10.0, clock=lambda: clock[0])
    assert router.pick() is None  # no snapshot yet
    router.observe(
        [
            {"replica": "a-0", "queued": 4, "occupancy": 8, "slots": 8},
            {"replica": "a-1", "queued": 0, "occupancy": 2, "slots": 8},
            {"replica": "a-2", "queued": 0, "occupancy": 0, "slots": 8,
             "draining": True},
            {"replica": "a-3", "state": "wedged"},
            {"replica": "a-4", "unreachable": True},
        ]
    )
    assert router.eligible() == ["a-0", "a-1"]
    for _ in range(8):
        assert router.pick() == "a-1"  # never the drained/wedged/dead ones
    # stale snapshots stamp nothing: routing on old evidence is worse
    # than the topic's default partition spread
    clock[0] = 20.0
    assert router.pick() is None
    assert router.stats()["fresh"] is False


def test_router_affinity_pins_until_ineligible():
    clock = [0.0]
    router = ReplicaRouter(
        fresh_s=100.0, affinity_ttl_s=50.0, clock=lambda: clock[0]
    )
    router.observe(
        [
            {"replica": "a-0", "queued": 0, "occupancy": 0, "slots": 8},
            {"replica": "a-1", "queued": 3, "occupancy": 4, "slots": 8},
        ]
    )
    assert router.pick("alice") == "a-0"
    # load flips — but alice stays pinned (her prefix blocks live there)
    router.observe(
        [
            {"replica": "a-0", "queued": 9, "occupancy": 8, "slots": 8},
            {"replica": "a-1", "queued": 0, "occupancy": 0, "slots": 8},
        ]
    )
    assert router.pick("alice") == "a-0"
    assert router.pick("bob") == "a-1"  # fresh tenants go least-loaded
    # the pinned replica drains: affinity breaks immediately
    router.observe(
        [
            {"replica": "a-0", "queued": 0, "occupancy": 0, "slots": 8,
             "draining": True},
            {"replica": "a-1", "queued": 0, "occupancy": 0, "slots": 8},
        ]
    )
    assert router.pick("alice") == "a-1"
    stats = router.stats()
    assert stats["affinity_hits"] >= 1
    assert stats["affinity_rerouted"] == 1
    assert stats["replicas"]["a-0"]["eligible"] is False


def test_split_replica_target():
    assert split_replica_target("chat-ai-2") == ("chat-ai", 2)
    assert split_replica_target("2") == ("", 2)
    assert split_replica_target("chat-ai") == ("chat-ai", None)


def test_runner_honors_replica_header(run_async):
    """The consumer half of routing: records stamped for a sibling
    replica of the SAME agent re-produce to the input topic (bounce
    header incremented) and commit; records for this replica, for other
    agents' pods, unstamped, or over the bounce cap process locally."""
    from langstream_tpu.api.record import SimpleRecord
    from langstream_tpu.runtime.runner import AgentRunner
    from langstream_tpu.runtime.tracker import SourceRecordTracker

    class _Producer:
        def __init__(self):
            self.written = []
            self.started = False

        async def start(self):
            self.started = True

        async def write(self, record):
            self.written.append(record)

        async def close(self):
            pass

    class _Runtime:
        def __init__(self, producer):
            self.producer = producer

        def create_producer(self, agent_id, config):
            return self.producer

    class _Input:
        topic = "in-topic"

    class _Node:
        input = _Input()

    async def main():
        runner = AgentRunner.__new__(AgentRunner)
        runner.node = _Node()
        runner.agent_id = "chat-ai"
        runner._routing_base = "chat-ai"
        runner._routing_ordinal = 0
        runner._reroute_producer = None
        runner.records_rerouted = 0
        producer = _Producer()
        runner.topics_runtime = _Runtime(producer)
        committed = []

        async def commit(records):
            committed.extend(records)

        runner.tracker = SourceRecordTracker(commit)

        mine = SimpleRecord("a", headers=((REPLICA_HEADER, "chat-ai-0"),))
        unstamped = SimpleRecord("b")
        other_agent = SimpleRecord(
            "c", headers=((REPLICA_HEADER, "chat-out-1"),)
        )
        sibling = SimpleRecord(
            "d", headers=((REPLICA_HEADER, "chat-ai-1"),)
        )
        capped = SimpleRecord(
            "e",
            headers=(
                (REPLICA_HEADER, "chat-ai-1"),
                (BOUNCE_HEADER, "2"),
            ),
        )
        kept = await runner._honor_replica_routing(
            [mine, unstamped, other_agent, sibling, capped]
        )
        assert [r.value for r in kept] == ["a", "b", "c", "e"]
        assert [r.value for r in producer.written] == ["d"]
        assert producer.written[0].header(BOUNCE_HEADER) == "1"
        assert [r.value for r in committed] == ["d"]
        assert runner.records_rerouted == 1

    run_async(main())


def test_runner_routing_is_defensive(run_async):
    """Hostile or unlucky inputs must never kill the consume loop: a
    garbage bounce header (client-suppliable via gateway payloads) reads
    as over the cap, a keyed record serves locally (its key hashes back
    to this very partition — a bounce cannot converge), and a broker
    failure during the re-produce falls back to local serving instead of
    becoming the replica's fatal loop error."""
    from langstream_tpu.api.record import SimpleRecord
    from langstream_tpu.runtime.runner import AgentRunner
    from langstream_tpu.runtime.tracker import SourceRecordTracker

    class _BrokenProducer:
        async def start(self):
            pass

        async def write(self, record):
            raise ConnectionResetError("leader election in progress")

        async def close(self):
            pass

    class _Runtime:
        def create_producer(self, agent_id, config):
            return _BrokenProducer()

    class _Input:
        topic = "in-topic"

    class _Node:
        input = _Input()

    async def main():
        runner = AgentRunner.__new__(AgentRunner)
        runner.node = _Node()
        runner.agent_id = "chat-ai"
        runner._routing_base = "chat-ai"
        runner._routing_ordinal = 0
        runner._reroute_producer = None
        runner.records_rerouted = 0
        runner.topics_runtime = _Runtime()
        committed = []

        async def commit(records):
            committed.extend(records)

        runner.tracker = SourceRecordTracker(commit)

        garbage_bounce = SimpleRecord(
            "a",
            headers=(
                (REPLICA_HEADER, "chat-ai-1"),
                (BOUNCE_HEADER, "not-a-number"),
            ),
        )
        keyed = SimpleRecord(
            "b", key="tenant-42", headers=((REPLICA_HEADER, "chat-ai-1"),)
        )
        broker_down = SimpleRecord(
            "c", headers=((REPLICA_HEADER, "chat-ai-1"),)
        )
        kept = await runner._honor_replica_routing(
            [garbage_bounce, keyed, broker_down]
        )
        # every record survives locally; nothing rerouted, nothing raised
        assert [r.value for r in kept] == ["a", "b", "c"]
        assert runner.records_rerouted == 0
        assert committed == []
        # the broken producer was dropped so the next bounce rebuilds it
        assert runner._reroute_producer is None

    run_async(main())


def test_gateway_stamps_routing_header():
    from langstream_tpu.gateway.server import GatewayRegistry, GatewayServer

    registry = GatewayRegistry()
    server = GatewayServer(registry=registry, port=free_port())
    # no router yet: nothing stamped
    headers = {}
    server._stamp_replica(headers, "t1", "chat", {}, {})
    assert REPLICA_HEADER not in headers
    registry.update_fleet(
        "t1", "chat",
        [
            {"replica": "chat-ai-0", "queued": 5, "occupancy": 2, "slots": 4},
            {"replica": "chat-ai-1", "queued": 0, "occupancy": 0, "slots": 4},
        ],
    )
    headers = {}
    server._stamp_replica(
        headers, "t1", "chat", {"tenant": "alice"}, {}
    )
    assert headers[REPLICA_HEADER] == "chat-ai-1"
    # a client-supplied stamp is honored, never overwritten
    explicit = {REPLICA_HEADER: "chat-ai-0"}
    server._stamp_replica(explicit, "t1", "chat", {"tenant": "alice"}, {})
    assert explicit[REPLICA_HEADER] == "chat-ai-0"
    # unregister drops the router with the app
    registry.unregister("t1", "chat")
    assert registry.router("t1", "chat") is None


# --------------------------------------------------------------------------
# control plane: /autoscaler route + deploy validation 400
# --------------------------------------------------------------------------


def test_controlplane_autoscaler_route_and_bad_autoscale_400(run_async):
    from langstream_tpu.controlplane.server import (
        ControlPlaneServer,
        LocalComputeRuntime,
    )
    from langstream_tpu.controlplane.stores import InMemoryApplicationStore

    pipeline = """
module: default
id: app
topics:
  - name: "in-topic"
    creation-mode: create-if-not-exists
  - name: "out-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "svc"
    type: "ai-chat-completions"
    input: "in-topic"
    output: "out-topic"
    configuration:
      model: "tiny"
      completion-field: "value.answer"
      prompt:
        - role: user
          content: "{{% value.q}}"
"""
    configuration = """
configuration:
  resources:
    - type: "tpu-serving-configuration"
      name: "tpu"
      configuration:
        model: "tiny"
        autoscale:
          min-replicas: 3
          max-replicas: 2
"""
    instance = "instance:\n  streamingCluster:\n    type: memory\n"

    async def main():
        control = ControlPlaneServer(
            store=InMemoryApplicationStore(),
            compute=LocalComputeRuntime(),
            port=free_port(),
        )
        await control.start()
        session = aiohttp.ClientSession()
        api = f"http://127.0.0.1:{control.port}"
        try:
            async with session.put(f"{api}/api/tenants/t1") as resp:
                assert resp.status == 200
            # malformed autoscale: 400 at deploy, before any pod exists
            async with session.post(
                f"{api}/api/applications/t1/badfleet",
                json={
                    "files": {
                        "pipeline.yaml": pipeline,
                        "configuration.yaml": configuration,
                    },
                    "instance": instance,
                },
            ) as resp:
                assert resp.status == 400
                assert "autoscale" in (await resp.text())
            # an app without an active autoscaler answers enabled: false
            async with session.get(
                f"{api}/api/applications/t1/ghost/autoscaler"
            ) as resp:
                assert resp.status == 200
                assert await resp.json() == {"enabled": False}
        finally:
            await session.close()
            await control.stop()
            await _close_engines()

    run_async(main())


# --------------------------------------------------------------------------
# engine_top: fleet panel + scale-thrash analyze
# --------------------------------------------------------------------------


def _fleet_payload(decisions=()):
    return {
        "enabled": True,
        "spec": {
            "min-replicas": 1, "max-replicas": 4, "cooldown-s": 60,
            "scale-up-window-s": 10, "scale-down-window-s": 120,
        },
        "replicas": [
            {"replica": "chat-ai-0", "queued": 2, "occupancy": 6,
             "slots": 8, "state": "ok", "draining": False,
             "slo_alerting": []},
            {"replica": "chat-ai-1", "queued": 0, "occupancy": 1,
             "slots": 8, "state": "ok", "draining": True,
             "slo_alerting": ["ttft"]},
            {"replica": "chat-ai-2", "unreachable": True},
        ],
        "decisions": list(decisions),
        "scale_ups": 2,
        "scale_downs": 1,
        "cooldown_remaining_s": 12.5,
        "pressure_for_s": 4.0,
        "idle_for_s": None,
    }


def test_engine_top_renders_fleet_panel():
    engine_top = _load_engine_top()
    frame = engine_top.render_fleet(
        _fleet_payload(
            [
                {
                    "m_s": 100.0, "action": "up", "from": 1, "to": 2,
                    "outcome": "scaled",
                    "reasons": ["queue depth 40 over 1 healthy replicas"],
                    "evidence": {},
                }
            ]
        )
    )
    assert "== fleet ==" in frame
    assert "replicas 3 (min 1 / max 4)" in frame
    assert "chat-ai-1" in frame and "DRAINING" in frame
    assert "SLO:ttft" in frame
    assert "UNREACHABLE" in frame
    assert "scale    up 1->2 [scaled] queue depth 40" in frame
    assert "not active" in engine_top.render_fleet({"enabled": False})


def test_engine_top_analyze_flags_scale_thrash(tmp_path):
    engine_top = _load_engine_top()
    # up/down flip-flops inside one cooldown window: thrash
    decisions = []
    t = 0.0
    for action in ("up", "down", "up", "down", "up"):
        decisions.append(
            {"m_s": t, "action": action, "from": 1, "to": 2,
             "outcome": "scaled", "reasons": []}
        )
        t += 5.0
    text = engine_top.analyze(_fleet_payload(decisions))
    assert "scale thrash" in text
    # a well-spaced history stays unflagged
    calm = [
        {"m_s": i * 400.0, "action": a, "from": 1, "to": 2,
         "outcome": "scaled", "reasons": []}
        for i, a in enumerate(("up", "down", "up", "down"))
    ]
    text = engine_top.analyze(_fleet_payload(calm))
    assert "scale thrash" not in text
    assert "no scale anomalies" in text


# --------------------------------------------------------------------------
# the chaos acceptance e2e: flood → scale up, starve → drain + scale down
# --------------------------------------------------------------------------


class FakeFleetBackend:
    """A fake-kube fleet: the StatefulSet lives in InMemoryKubeApi, each
    'pod' is a REAL in-process serving engine — so scale/drain decisions
    exercise the true drain/preempt/requeue machinery while the cluster
    state stays scripted."""

    def __init__(self, api, namespace, sts_name, config):
        self.api = api
        self.namespace = namespace
        self.sts_name = sts_name
        self.config = config
        self.engines = {}
        self.calls = []
        self._sync_engines()

    def _sts(self):
        return self.api.get("StatefulSet", self.namespace, self.sts_name)

    def replicas(self) -> int:
        return int(self._sts()["spec"]["replicas"])

    def _sync_engines(self):
        from langstream_tpu.serving.engine import TpuServingEngine

        for i in range(self.replicas()):
            pod = f"{self.sts_name}-{i}"
            if pod not in self.engines:
                self.engines[pod] = TpuServingEngine(self.config)

    def observe(self):
        out = []
        for i in range(self.replicas()):
            pod = f"{self.sts_name}-{i}"
            engine = self.engines.get(pod)
            if engine is None:
                out.append({"replica": pod, "unreachable": True})
                continue
            stats = engine.stats()
            health = stats["health"]
            scheduler = stats["scheduler"]
            classes = scheduler.get("classes") or {}
            out.append(
                {
                    "replica": pod,
                    "queued": stats["queued"],
                    "queue_interactive": (
                        (classes.get("interactive") or {}).get("depth", 0)
                    ),
                    "occupancy": stats["active"],
                    "slots": stats["slots"],
                    "shed_total": scheduler.get("shed", 0) or 0,
                    "state": health["state"],
                    "draining": health["draining"],
                    "slo_alerting": tuple(
                        (stats.get("slo") or {}).get("alerting", ())
                    ),
                }
            )
        return out

    def set_replicas(self, n: int):
        self.calls.append(("set_replicas", n))
        sts = self._sts()
        sts["spec"]["replicas"] = int(n)
        sts.setdefault("metadata", {}).setdefault("annotations", {})[
            AUTOSCALE_ANNOTATION
        ] = "true"
        self.api.apply(sts)
        self._sync_engines()

    async def drain(self, replica: str, grace_s: float):
        self.calls.append(("drain", replica))
        engine = self.engines.get(replica)
        if engine is None:
            return None
        return await engine.drain(grace_s)

    async def close(self):
        for engine in self.engines.values():
            await engine.close()


def test_chaos_flood_scales_up_starve_drains_down_zero_lost(run_async):
    """The acceptance chaos e2e: flood one replica until the autoscaler
    scales the fake-kube StatefulSet up, then starve until it drains
    the victim (highest ordinal) and scales back down — asserting that
    every submitted request completes or is explicitly shed with a
    retry hint, that the drain requeues the victim's in-flight
    generation with byte-identical output, and that the router never
    selects a draining replica."""
    from langstream_tpu.gateway.server import GatewayRegistry
    from langstream_tpu.serving.qos import RateLimited

    api = InMemoryKubeApi()
    api.apply(
        {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": "chat-ai",
                "namespace": "langstream-t1",
                "labels": {"langstream-application": "chat"},
            },
            "spec": {
                "serviceName": "chat-ai",
                "replicas": 1,
                "template": {"spec": {"containers": [{"name": "runtime"}]}},
            },
        }
    )
    spec = AutoscaleSpec.from_dict(
        {
            "min-replicas": 1,
            "max-replicas": 2,
            "scale-up-window-s": 0,
            "scale-down-window-s": 0,
            "cooldown-s": 0,
            "drain-grace-s": 120,
            "queue-depth-per-replica": 3,
            "idle-occupancy": 0.6,
            # this e2e pins queue-driven scaling + no-loss drain; the
            # degraded-health signal (own unit tests) stays off because
            # a CPU flood leaves flood-era KV-saturation samples in the
            # flight ring that read as lingering scale-up pressure during
            # the starve phase, blocking the "down" decision
            "degraded": False,
        }
    )

    async def main():
        backend = FakeFleetBackend(
            api, "langstream-t1", "chat-ai", _fleet_config()
        )
        registry = GatewayRegistry()
        scaler = FleetAutoscaler(
            spec,
            backend,
            on_observation=lambda obs: registry.update_fleet(
                "t1", "chat", obs
            ),
        )
        submitted: list[asyncio.Task] = []
        try:
            # ---- flood: queue depth past the threshold on one replica
            eng0 = backend.engines["chat-ai-0"]
            for i in range(8):
                submitted.append(
                    asyncio.ensure_future(
                        eng0.generate(f"flood request {i}", {"max-tokens": 4})
                    )
                )
            await asyncio.sleep(0)  # let submissions enqueue
            entry = await scaler.step()
            assert entry is not None and entry["action"] == "up", entry
            assert entry["outcome"] == "scaled"
            assert any("queue depth" in r for r in entry["reasons"])
            assert backend.replicas() == 2
            sts = api.get("StatefulSet", "langstream-t1", "chat-ai")
            assert sts["metadata"]["annotations"][AUTOSCALE_ANNOTATION] == (
                "true"
            )
            # the router consumed the same snapshot the scaler judged
            assert registry.router("t1", "chat") is not None

            # the flood completes: nothing lost while scaling
            flood = await asyncio.gather(*submitted, return_exceptions=True)
            submitted.clear()

            # ---- byte-identity baseline for the victim's generation:
            # run it undisturbed on the SURVIVOR engine (identical
            # config + seed → identical weights; f32 greedy is exactly
            # shape-independent, so batch composition cannot leak in)
            prompt = "chaos drain victim generation"
            baseline = await backend.engines["chat-ai-0"].generate(
                prompt, {"max-tokens": 20}
            )

            # ---- starve with one generation in flight on the victim
            eng1 = backend.engines["chat-ai-1"]
            progressed = asyncio.Event()
            seen = 0

            def on_token(token, logprob, last):
                nonlocal seen
                seen += 1
                if seen >= 3:
                    progressed.set()

            victim_task = asyncio.ensure_future(
                eng1.generate(prompt, {"max-tokens": 20}, on_token=on_token)
            )
            submitted.append(victim_task)
            await asyncio.wait_for(progressed.wait(), timeout=60)

            entry = await scaler.step()
            assert entry is not None and entry["action"] == "down", entry
            assert entry["outcome"] == "scaled"
            assert entry["victim"] == "chat-ai-1"
            # drain-before-terminate ordering: the victim drained before
            # the replica count dropped
            assert backend.calls[-2:] == [
                ("drain", "chat-ai-1"),
                ("set_replicas", 1),
            ]
            assert backend.replicas() == 1
            drain_report = entry["drain"]
            assert drain_report["requeued"] >= 1
            assert drain_report["shed"] == 0

            # the drained generation completed byte-identically: the
            # acceptance invariant — preempt-by-drain + front-of-class
            # resume reproduces the undisturbed stream exactly
            victim_result = await asyncio.wait_for(victim_task, timeout=60)
            assert victim_result["tokens"] == baseline["tokens"]
            assert victim_result["text"] == baseline["text"]

            # the victim engine's evidence trail: drain begin/end events
            # bracket a preempt with reason="drain"; stats/health carry
            # the terminal drain posture
            events = eng1.flight.recent_events(0)
            stages = [e["stage"] for e in events if e["kind"] == "drain"]
            assert stages == ["begin", "end"]
            assert any(
                e.get("reason") == "drain"
                for e in events
                if e["kind"] == "preempt"
            )
            section = eng1.stats()["drain"]
            assert section["draining"] is True
            assert section["requeued"] >= 1 and section["shed"] == 0
            health = eng1.health()
            assert health["draining"] is True and health["ready"] is False

            # the router never selects the drained replica; affinity
            # lands every tenant on the survivor
            registry.update_fleet("t1", "chat", backend.observe())
            router = registry.router("t1", "chat")
            assert router.eligible() == ["chat-ai-0"]
            for tenant in ("alice", "bob", None):
                assert router.pick(tenant) == "chat-ai-0"

            # new arrivals on the drained engine shed explicitly
            with pytest.raises(RateLimited) as exc:
                await eng1.generate("late", {"max-tokens": 2})
            assert exc.value.retry_after > 0

            # ---- the zero-lost ledger: every submitted request either
            # returned a result or an explicit RateLimited with a retry
            # hint — nothing vanished
            for outcome in [*flood, victim_result]:
                if isinstance(outcome, dict):
                    assert outcome["tokens"]
                else:
                    assert isinstance(outcome, RateLimited)
                    assert outcome.retry_after > 0

            # the autoscaler status is a serializable operator surface
            status = scaler.status()
            assert status["scale_ups"] == 1 and status["scale_downs"] == 1
            json.dumps(status)
        finally:
            for task in submitted:
                if not task.done():
                    task.cancel()
            await backend.close()

    run_async(main())


# --------------------------------------------------------------------------
# graftcheck FLEET rules: TP/TN beyond the registry fixtures
# --------------------------------------------------------------------------


def test_fleet601_gated_write_anywhere_up_the_if_chain():
    """The cooldown gate may sit any number of ifs above the write —
    what matters is that SOME enclosing condition names it."""
    import textwrap

    from langstream_tpu.analysis import ALL_RULES, analyze_source

    path = "langstream_tpu/controlplane/autoscaler.py"
    gated = textwrap.dedent(
        """
        def step(self, backend, decision, now):
            if self._cooldown_ok(now):
                if decision.action == "up":
                    backend.set_replicas(decision.target)
        """
    )
    assert [f.rule for f in analyze_source(gated, path, ALL_RULES)] == []
    ungated = textwrap.dedent(
        """
        def step(self, backend, decision, now):
            if decision.action == "up":
                backend.set_replicas(decision.target)
        """
    )
    assert [f.rule for f in analyze_source(ungated, path, ALL_RULES)] == [
        "FLEET601"
    ]
    # scale_statefulset is the other write spelling; other modules are
    # out of scope
    other = analyze_source(
        ungated.replace("set_replicas", "scale_statefulset"),
        path,
        ALL_RULES,
    )
    assert [f.rule for f in other] == ["FLEET601"]
    assert (
        analyze_source(
            ungated, "langstream_tpu/k8s/compute.py", ALL_RULES
        )
        == []
    )


def test_fleet602_blocking_in_decision_but_not_in_observe():
    import textwrap

    from langstream_tpu.analysis import ALL_RULES, analyze_source

    path = "langstream_tpu/controlplane/autoscaler.py"
    blocking_decide = textwrap.dedent(
        """
        import time

        def decide(self, observations, now):
            time.sleep(0.1)
            return "none"
        """
    )
    ids = [f.rule for f in analyze_source(blocking_decide, path, ALL_RULES)]
    assert "FLEET602" in ids
    lock_in_helper = textwrap.dedent(
        """
        def _pressure_reasons(self, obs):
            with self._lock:
                return []
        """
    )
    ids = [f.rule for f in analyze_source(lock_in_helper, path, ALL_RULES)]
    assert "FLEET602" in ids
    # observe/apply are the sanctioned I/O edges — not policed
    io_in_observe = textwrap.dedent(
        """
        import urllib.request

        def observe(self):
            with urllib.request.urlopen("http://pod:8080/x") as r:
                return r.read()
        """
    )
    ids = [f.rule for f in analyze_source(io_in_observe, path, ALL_RULES)]
    assert "FLEET602" not in ids
