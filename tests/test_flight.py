"""Engine flight recorder tests.

Layers covered: the recorder ring (bounded size, drop accounting, rollup
math), the engine integration on the CPU backend under concurrent load
(the acceptance decomposition: device + host + stall sums to the measured
wall clock), recompile-event detection via a fake compile-cache miss, the
pod ``/flight`` endpoints, the control-plane fan-in over the memory broker
(mirroring ``test_tracing.py``'s e2e shape), the k8s fan-in pod tagging,
and the ``engine_top --analyze`` post-mortem on a canned dump."""

import asyncio
import importlib.util
import json
import socket
import time
from pathlib import Path

import aiohttp
import pytest

from langstream_tpu.serving.flight import FlightRecorder, bench_rollup


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _close_engines():
    from langstream_tpu.serving.engine import TpuServingEngine

    with TpuServingEngine._instances_lock:
        engines = list(TpuServingEngine._instances.values())
    for engine in engines:
        await engine.close()


def _load_engine_top():
    path = Path(__file__).resolve().parents[1] / "tools" / "engine_top.py"
    spec = importlib.util.spec_from_file_location("engine_top", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


# --------------------------------------------------------------------------
# recorder units: bounded ring, drop accounting, rollup math
# --------------------------------------------------------------------------


def test_ring_is_bounded_and_counts_drops():
    recorder = FlightRecorder(slots=4, maxlen=8)
    for _ in range(20):
        recorder.sample("decode", device_s=0.001, tokens=4)
    assert len(recorder.recent(0)) == 8
    assert recorder.recorded == 20
    assert recorder.dropped == 12
    # cumulative totals survive eviction
    assert recorder.tokens == 80
    assert recorder.steps_by_phase == {"decode": 20}


def test_no_drops_below_capacity():
    recorder = FlightRecorder(slots=4, maxlen=64)
    for _ in range(63):
        recorder.sample("decode")
    assert recorder.dropped == 0
    summary = recorder.summary()
    assert summary["dropped"] == 0
    assert summary["recorded"] == 63


def test_buffer_size_env(monkeypatch):
    monkeypatch.setenv("LS_TPU_FLIGHT_BUFFER", "100")
    assert FlightRecorder().capacity == 100
    monkeypatch.setenv("LS_TPU_FLIGHT_BUFFER", "3")  # clamped to the floor
    assert FlightRecorder().capacity == 64
    monkeypatch.setenv("LS_TPU_FLIGHT_BUFFER", "junk")
    assert FlightRecorder().capacity == 4096


def test_rollup_decomposition_is_exact():
    """wall == device + host per dispatch sample, and the totals tile the
    timeline: dispatch walls + stall walls == total wall."""
    recorder = FlightRecorder(slots=2, maxlen=32)
    time.sleep(0.02)
    recorder.sample("prefill", device_s=0.005, tokens=2)
    time.sleep(0.03)
    recorder.sample("decode", device_s=0.01, tokens=16, stall="no-free-slot")
    time.sleep(0.01)
    recorder.stall("queue-empty")
    totals = recorder.summary()["totals"]
    # each total is independently rounded to 3 decimals for JSON, so the
    # identity holds to rounding precision
    assert totals["wall_ms"] == pytest.approx(
        totals["device_ms"] + totals["host_ms"] + totals["stall_ms"], abs=0.01
    )
    assert totals["tokens"] == 18
    assert totals["steps_by_phase"] == {"prefill": 1, "decode": 1}
    # two disjoint attributions: idle gaps are STALL (decompose stall_ms),
    # annotated busy dispatches are BLOCKED (queue pressure while decoding)
    assert set(totals["stall_s_by_reason"]) == {"queue-empty"}
    assert set(totals["blocked_s_by_reason"]) == {"no-free-slot"}
    assert totals["blocked_s_by_reason"]["no-free-slot"] >= 0.03
    # the dict rounds to 4 decimals of seconds (0.1 ms steps), stall_ms to
    # 3 decimals of ms — equal up to half a rounding step
    assert sum(totals["stall_s_by_reason"].values()) * 1000 == pytest.approx(
        totals["stall_ms"], abs=0.06
    )


def test_device_time_clamped_to_wall():
    """A device_s overestimate (overlapped pipelined fetch) must not drive
    host_ms negative."""
    recorder = FlightRecorder(slots=1, maxlen=8)
    sample = recorder.sample("decode", device_s=999.0)
    assert sample["device_ms"] <= sample["wall_ms"]
    assert sample["host_ms"] >= 0.0


def test_events_ring_and_counters():
    recorder = FlightRecorder(slots=1, maxlen=8)
    recorder.event("recompile", what="decode", variant="w128")
    recorder.event("pool-grow", slots=3)
    recorder.event("warmup", stage="begin")
    assert recorder.recompiles == 1
    assert recorder.events_by_type == {
        "recompile": 1, "pool-grow": 1, "warmup": 1,
    }
    kinds = [e["kind"] for e in recorder.recent_events()]
    assert kinds == ["recompile", "pool-grow", "warmup"]


def test_bench_rollup_carries_the_record_keys():
    recorder = FlightRecorder(slots=2, maxlen=32)
    recorder.sample("decode", device_s=0.001, tokens=8, stall="no-kv-blocks")
    recorder.event("recompile", what="decode")
    rollup = bench_rollup(recorder.summary())
    assert set(rollup) == {
        "host_overhead_ms_p50", "host_exposed_ms_p50", "overlap_ratio",
        "step_ms_p50", "stall_s_by_reason", "blocked_s_by_reason",
        "queue_depth_p95", "recompile_count", "totals",
    }
    assert rollup["recompile_count"] == 1
    # the annotated dispatch sample is queue pressure, not engine stall
    assert "no-kv-blocks" in rollup["blocked_s_by_reason"]
    assert rollup["stall_s_by_reason"] == {}
    assert set(rollup["totals"]) == {
        "wall_ms", "device_ms", "host_ms", "host_overlapped_ms", "stall_ms",
        "tokens", "steps_by_phase",
    }
    # rollups must be JSON-clean for the bench record line
    json.dumps(rollup)


# --------------------------------------------------------------------------
# engine integration (CPU backend): the acceptance decomposition
# --------------------------------------------------------------------------


def test_paged_engine_under_load_decomposes_wall_time(run_async):
    """A paged engine under concurrent generate(): the flight rollup's
    device + host + stall components sum to within 10% of the measured
    wall time, at least one recompile event lands during the (implicit)
    warmup wave, and nothing is dropped below buffer capacity."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def main():
        engine = TpuServingEngine(
            ServingConfig(
                model="tiny", slots=4, max_seq_len=128, decode_chunk=8,
                kv_layout="paged", prefix_cache=True,
            )
        )
        t0 = time.monotonic()
        try:
            results = await asyncio.gather(
                *(
                    engine.generate(
                        f"flight recorder load prompt {i}", {"max-tokens": 16}
                    )
                    for i in range(12)
                )
            )
            elapsed = time.monotonic() - t0
            assert all(r["tokens"] for r in results)
            summary = engine.flight.summary()
            totals = summary["totals"]
            covered_s = (
                totals["device_ms"] + totals["host_ms"] + totals["stall_ms"]
            ) / 1000.0
            # the samples tile the engine-loop timeline, so the decomposed
            # components must reproduce the measured wall clock
            assert covered_s == pytest.approx(elapsed, rel=0.10)
            # ... and the decomposition itself is internally exact (up to
            # the per-total JSON rounding)
            assert totals["wall_ms"] / 1000.0 == pytest.approx(
                covered_s, abs=1e-4
            )
            # first-sight compiles (the warmup wave) are recorded as events
            recompiles = [
                e for e in engine.flight.recent_events()
                if e["kind"] == "recompile"
            ]
            assert recompiles, "warmup compiles must surface as events"
            assert totals["recompiles"] == len(recompiles)
            assert summary["dropped"] == 0
            assert totals["tokens"] == sum(len(r["tokens"]) for r in results)
            # every dispatch phase the run used shows up in the step counts
            assert totals["steps_by_phase"].get("prefill", 0) >= 1
            assert totals["steps_by_phase"].get("decode", 0) >= 1
            # stats() mirrors the per-phase counts for live introspection
            assert engine.stats()["steps"] == totals["steps_by_phase"]
        finally:
            await engine.close()

    run_async(main())


def test_timeline_mark_recompile_events_and_idle_stall(run_async):
    """One engine, three recorder behaviors (shared to keep tier-1 wall
    time down): the loop re-marks the timeline at start so an idle
    deploy's construction→first-request gap isn't billed as host time; a
    fake compile-cache miss surfaces as exactly one recompile event; and
    idle gaps are recorded as queue-empty stall."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def main():
        engine = TpuServingEngine(
            ServingConfig(model="tiny", slots=2, max_seq_len=64, decode_chunk=4)
        )
        try:
            await asyncio.sleep(0.6)  # idle deploy: no loop, no samples
            t0 = time.monotonic()
            await engine.generate("late first request", {"max-tokens": 4})
            elapsed = time.monotonic() - t0
            totals = engine.flight.summary()["totals"]
            # without the loop-start mark the first sample would absorb
            # the 0.6 s pre-request gap
            assert totals["wall_ms"] / 1000.0 <= elapsed + 0.2

            # fake a compile-cache miss: forget a variant and re-request it
            before = engine.flight.recompiles
            engine._decode_chunk_fns.clear()
            engine._compiled_shapes.clear()
            engine._decode_fn((False, False, True), None)
            assert engine.flight.recompiles == before + 1
            newest = engine.flight.recent_events()[-1]
            assert newest["kind"] == "recompile"
            assert newest["what"] == "decode"
            # the same variant again is NOT a new compile
            engine._decode_fn((False, False, True), None)
            assert engine.flight.recompiles == before + 1

            # let the loop hit its idle wait once (1s wake timeout)
            await asyncio.sleep(1.2)
            assert engine.flight.stall_s_by_reason.get("queue-empty", 0.0) > 0
        finally:
            await engine.close()

    run_async(main())


def test_draft_tokens_report_real_draft_count(run_async):
    """Padding zeros are not drafts: the rejected-drafts accounting counts
    only genuine prompt-lookup continuations."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def main():
        engine = TpuServingEngine(
            ServingConfig(
                model="tiny", slots=2, max_seq_len=64, decode_chunk=4,
                kv_layout="paged", speculative_drafts=4,
            )
        )
        try:
            from langstream_tpu.serving.engine import _Request

            def fake_request(prompt):
                return _Request(
                    prompt_tokens=prompt, max_tokens=8, temperature=0.0,
                    top_k=0, top_p=1.0, on_token=None, future=None,
                )

            # repeated bigram (1,2): the continuation [3,1,2] drafts 3 real
            # tokens, padded to 4
            engine.slots[0].request = fake_request([1, 2, 3, 1, 2])
            drafts, n_real = engine._draft_tokens(0, 4)
            assert drafts == [3, 1, 2, 0]
            assert n_real == 3
            # no bigram repeats: zero real drafts, all padding
            engine.slots[1].request = fake_request([5, 6, 7, 8])
            drafts, n_real = engine._draft_tokens(1, 4)
            assert drafts == [0, 0, 0, 0]
            assert n_real == 0
            engine.slots[0].request = None
            engine.slots[1].request = None
        finally:
            await engine.close()

    run_async(main())


# --------------------------------------------------------------------------
# pod /flight endpoints
# --------------------------------------------------------------------------


def test_pod_serves_flight_and_summary(run_async, monkeypatch):
    from langstream_tpu.runtime.pod import _serve_info
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def main():
        # get_or_create registers the engine in the instance map the
        # /flight endpoint reports (direct construction stays private)
        engine = TpuServingEngine.get_or_create(
            ServingConfig(model="tiny", slots=2, max_seq_len=64, decode_chunk=4)
        )
        port = free_port()
        monkeypatch.setenv("LS_HTTP_PORT", str(port))
        server = await _serve_info(None)
        try:
            await engine.generate("pod flight probe", {"max-tokens": 4})
            async with aiohttp.ClientSession() as session:
                base = f"http://127.0.0.1:{port}"
                async with session.get(f"{base}/flight") as resp:
                    assert resp.status == 200
                    assert resp.headers["Content-Type"] == "application/json"
                    report = await resp.json()
                entry = next(e for e in report if e["model"] == "tiny")
                assert entry["samples"], "full report carries samples"
                assert entry["events"], "…and the event tail"
                assert entry["summary"]["totals"]["steps_by_phase"]
                async with session.get(f"{base}/flight/summary") as resp:
                    assert resp.status == 200
                    summaries = await resp.json()
                entry = next(e for e in summaries if e["model"] == "tiny")
                assert "samples" not in entry  # rollups only
                assert entry["summary"]["totals"]["wall_ms"] > 0
        finally:
            server.close()
            await engine.close()

    run_async(main())


# --------------------------------------------------------------------------
# control-plane fan-in e2e over the memory broker
# --------------------------------------------------------------------------

PIPELINE = """
topics:
  - name: "input-topic"
    creation-mode: create-if-not-exists
  - name: "output-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "chat"
    id: "chat"
    type: "ai-chat-completions"
    input: "input-topic"
    output: "output-topic"
    configuration:
      completion-field: "value.answer"
      max-tokens: 8
      messages:
        - role: user
          content: "{{ value.q }}"
"""

# a real (tiny) TPU engine behind the agent — without the resource the
# agent resolves the mock provider and no flight recorder exists; the
# slo section exercises the declared-objective path end to end
CONFIGURATION = """
configuration:
  resources:
    - type: "tpu-serving-configuration"
      name: "tpu"
      configuration:
        model: "tiny"
        slots: 2
        max-seq-len: 128
        decode-chunk: 4
        slo:
          objectives:
            availability:
              target: 0.999
            ttft:
              target: 0.99
              threshold-ms: 60000
"""

GATEWAYS = """
gateways:
  - id: "produce-input"
    type: produce
    topic: "input-topic"
    parameters: [sessionId]
    produce-options:
      headers:
        - key: "langstream-client-session-id"
          value-from-parameters: sessionId
  - id: "consume-output"
    type: consume
    topic: "output-topic"
    parameters: [sessionId]
    consume-options:
      filters:
        headers:
          - key: "langstream-client-session-id"
            value-from-parameters: sessionId
"""

INSTANCE = """
instance:
  streamingCluster:
    type: memory
"""


def test_e2e_flight_via_pod_and_controlplane(run_async, monkeypatch):
    """Gateway → ai-chat-completions over the memory broker, then the same
    flight data from the pod endpoint and the control-plane fan-in route
    (the ``test_tracing.py`` e2e shape, pointed at /flight)."""
    from langstream_tpu.controlplane.server import (
        ControlPlaneServer,
        LocalComputeRuntime,
    )
    from langstream_tpu.controlplane.stores import InMemoryApplicationStore
    from langstream_tpu.gateway.server import GatewayRegistry, GatewayServer
    from langstream_tpu.runtime.pod import _serve_info

    async def main():
        registry = GatewayRegistry()
        compute = LocalComputeRuntime(gateway_registry=registry)
        control = ControlPlaneServer(
            store=InMemoryApplicationStore(), compute=compute, port=free_port()
        )
        gateway = GatewayServer(registry=registry, port=free_port())
        pod_port = free_port()
        monkeypatch.setenv("LS_HTTP_PORT", str(pod_port))
        await control.start()
        await gateway.start()
        pod_server = await _serve_info(None)
        session = aiohttp.ClientSession()
        try:
            api = f"http://127.0.0.1:{control.port}"
            async with session.put(f"{api}/api/tenants/t1") as resp:
                assert resp.status == 200
            payload = {
                "files": {
                    "pipeline.yaml": PIPELINE,
                    "configuration.yaml": CONFIGURATION,
                    "gateways.yaml": GATEWAYS,
                },
                "instance": INSTANCE,
            }
            async with session.post(
                f"{api}/api/applications/t1/flightapp", json=payload
            ) as resp:
                body = await resp.json()
                assert resp.status == 200, body

            ws_base = f"ws://127.0.0.1:{gateway.port}"
            consume_url = (
                f"{ws_base}/v1/consume/t1/flightapp/consume-output"
                "?param:sessionId=s1&option:position=earliest"
            )
            produce_url = (
                f"{ws_base}/v1/produce/t1/flightapp/produce-input"
                "?param:sessionId=s1"
            )
            async with session.ws_connect(consume_url) as consumer:
                async with session.ws_connect(produce_url) as producer:
                    await producer.send_json({"value": {"q": "hello flight"}})
                    ack = await producer.receive_json()
                    assert ack["status"] == "OK"
                push = await asyncio.wait_for(
                    consumer.receive_json(), timeout=30
                )
            assert push["record"]["value"]["answer"]

            # the pod endpoint serves the engine that just ran
            pod_base = f"http://127.0.0.1:{pod_port}"
            async with session.get(f"{pod_base}/flight") as resp:
                assert resp.status == 200
                pod_report = await resp.json()
            assert pod_report, "a live engine must be reported"
            assert any(
                e["summary"]["totals"]["tokens"] > 0 for e in pod_report
            )

            # ... and the control-plane route fans in the same engines
            async with session.get(
                f"{api}/api/applications/t1/flightapp/flight"
            ) as resp:
                assert resp.status == 200
                cp_report = await resp.json()
            assert {e["model"] for e in cp_report} == {
                e["model"] for e in pod_report
            }
            entry = cp_report[0]
            assert entry["summary"]["totals"]["steps_by_phase"]
            assert "samples" in entry  # dev-mode fan-in carries the window

            # ... and the health/slo routes judge the same engines: the
            # served request left a healthy watchdog verdict and SLO
            # evidence (availability good, TTFT under its 60s threshold)
            async with session.get(
                f"{api}/api/applications/t1/flightapp/health"
            ) as resp:
                assert resp.status == 200
                health = await resp.json()
            assert health["status"] == "ok"
            assert health["pods"], "dev mode reports in-process members"
            engine_health = health["pods"][0]["engines"][0]
            assert engine_health["state"] == "ok"
            assert engine_health["ready"] is True
            async with session.get(
                f"{api}/api/applications/t1/flightapp/slo"
            ) as resp:
                assert resp.status == 200
                slo = await resp.json()
            assert "availability" in slo["configured"]["tpu"]["objectives"]
            engine_slo = next(
                e["slo"] for e in slo["engines"] if e["model"] == "tiny"
            )
            assert engine_slo["objectives"]["availability"]["window_good"] >= 1
            assert engine_slo["alerting"] == []

            # a malformed slo section fails the deploy with 400
            bad = {
                **payload,
                "files": {
                    **payload["files"],
                    "configuration.yaml": CONFIGURATION.replace(
                        "availability:", "uptime:"
                    ),
                },
            }
            async with session.post(
                f"{api}/api/applications/t1/badslo", json=bad
            ) as resp:
                assert resp.status == 400
                assert "slo" in (await resp.text())

            # an app this control plane never deployed reports nothing
            async with session.get(
                f"{api}/api/applications/t1/ghost/flight"
            ) as resp:
                assert resp.status == 200
                assert await resp.json() == []
        finally:
            await session.close()
            pod_server.close()
            await gateway.stop()
            await control.stop()
            await _close_engines()

    run_async(main())


def test_dev_flight_scoped_to_declared_models(monkeypatch):
    """Dev-mode engines are process-global: an app's flight route must
    only show the models its own serving resources declare (a sibling
    tenant's engine telemetry must not leak), and an app with no TPU
    resource (mock provider) sees nothing."""
    import langstream_tpu.serving.engine as engine_mod
    from langstream_tpu.controlplane.server import LocalComputeRuntime

    monkeypatch.setattr(
        engine_mod,
        "flight_report",
        lambda **kw: [
            {"model": "tiny", "summary": {}},
            {"model": "llama-1b", "summary": {}},
        ],
    )

    class _Resource:
        def __init__(self, rtype, configuration):
            self.type = rtype
            self.configuration = configuration

    def runner_with(resources):
        class _App:
            pass

        class _Runner:
            pass

        _Runner.application = _App()
        _Runner.application.resources = resources
        return _Runner()

    compute = LocalComputeRuntime()
    compute.runners[("t", "app")] = runner_with(
        {"tpu": _Resource("tpu-serving-configuration", {"model": "tiny"})}
    )
    compute.runners[("t", "plain")] = runner_with({})
    assert [e["model"] for e in compute.flight("t", "app")] == ["tiny"]
    assert compute.flight("t", "plain") == []
    assert compute.flight("t", "ghost") == []


def test_k8s_flight_fanin_tags_pods():
    """The k8s compute runtime concatenates per-pod /flight entries and
    tags each with its pod (engines don't merge across pods the way trace
    rollups do)."""
    from langstream_tpu.k8s.compute import KubernetesComputeRuntime

    class _Stub:
        def _pod_json_fanin(self, tenant, name, path):
            assert path == "/flight"
            return [
                ("app-chat-0", [{"model": "tiny", "summary": {}}]),
                ("app-chat-1", [{"model": "tiny", "summary": {}}, "junk"]),
                ("app-chat-2", []),
            ]

    report = KubernetesComputeRuntime.flight(_Stub(), "t", "app")
    assert [e["pod"] for e in report] == ["app-chat-0", "app-chat-1"]
    assert all(e["model"] == "tiny" for e in report)


# --------------------------------------------------------------------------
# engine_top: render + --analyze golden on a canned dump
# --------------------------------------------------------------------------


def _canned_entry() -> dict:
    return {
        "model": "llama3-8b",
        "slots": 64,
        "summary": {
            "capacity": 4096,
            "recorded": 120,
            "dropped": 0,
            "totals": {
                "wall_ms": 4800.0,
                "device_ms": 2952.0,
                "host_ms": 1608.0,
                "stall_ms": 240.0,
                "tokens": 7680,
                "steps_by_phase": {"decode": 110, "prefill": 10},
                "stall_s_by_reason": {
                    "no-kv-blocks": 0.18,
                    "queue-empty": 0.06,
                },
                "recompiles": 4,
                "events_by_type": {"recompile": 4, "pool-grow": 7},
                "spec_accepted": 0,
                "spec_rejected": 0,
            },
            "window": {
                "samples": 120,
                "span_s": 4.8,
                "tokens": 7680,
                "tok_s": 1600.0,
                "step_ms_p50": 40.0,
                "step_ms_p95": 66.0,
                "host_overhead_ms_p50": 13.4,
                "device_ms_p50": 24.6,
                "queue_depth_p95": 9,
                "occupancy_mean": 61.5,
                "kv_used_ratio_last": 0.97,
            },
        },
        "samples": [
            {
                "seq": i, "t_ms": 1000.0 + 40.0 * i, "phase": "decode",
                "wall_ms": 40.0, "device_ms": 24.6, "host_ms": 15.4,
                "occupancy": 60, "slots": 64, "tokens": 64,
                "queue_depth": 1 + i // 10, "stall": None, "kv_used": 0.97,
                "prefix_hits": 0,
            }
            for i in range(120)
        ],
        "events": [
            {"seq": 3, "t_ms": 1100.0, "kind": "recompile", "what": "decode"},
            {"seq": 4, "t_ms": 1600.0, "kind": "recompile", "what": "decode"},
            {"seq": 5, "t_ms": 2100.0, "kind": "recompile", "what": "prefill"},
            {"seq": 9, "t_ms": 3000.0, "kind": "pool-grow", "slots": 4},
        ],
    }


def test_engine_top_analyze_golden(capsys, tmp_path):
    engine_top = _load_engine_top()
    text = engine_top.analyze([_canned_entry()])
    # decomposition: the three components with their shares
    assert "device  61.5%" in text
    assert "host    33.5%" in text
    assert "stall    5.0%" in text
    # mean step = busy wall (wall − stall) / steps: (4800−240)/120
    assert "mean step 38.0ms" in text
    assert "stall[no-kv-blocks] 0.18s" in text
    # anomaly windows: compiles clustered within 2 s + pool pressure
    assert "recompile storm" in text
    assert "KV pool" in text
    # queue depth grows 1 → 12 across the canned window
    assert "queue growth" in text

    # the CLI path: same analysis from a file, exit 0
    dump = tmp_path / "dump.json"
    dump.write_text(json.dumps([_canned_entry()]))
    assert engine_top.main(["--analyze", str(dump)]) == 0
    assert "device  61.5%" in capsys.readouterr().out


def test_engine_top_analyze_accepts_bench_record():
    """A bench JSON whose detail carries the flight rollup (no raw
    samples) still decomposes without error."""
    engine_top = _load_engine_top()
    record = {
        "metric": "tok/s/chip",
        "value": 1600.0,
        "detail": {
            "paged": {
                "tok_s": 1600.0,
                "flight": {
                    "host_overhead_ms_p50": 13.4,
                    "stall_s_by_reason": {"no-free-slot": 2.0},
                    "queue_depth_p95": 30,
                    "recompile_count": 2,
                    "totals": {
                        "wall_ms": 10000.0,
                        "device_ms": 6000.0,
                        "host_ms": 3000.0,
                        "stall_ms": 1000.0,
                        "tokens": 30000,
                        "steps_by_phase": {"decode": 200},
                    },
                },
            }
        },
    }
    text = engine_top.analyze(record)
    assert "device  60.0%" in text
    assert "host    30.0%" in text
    assert "stall   10.0%" in text
    assert "stall[no-free-slot] 2.00s" in text

    with pytest.raises(ValueError):
        engine_top.analyze({"no": "flight here"})


def test_engine_top_render_smoke():
    engine_top = _load_engine_top()
    frame = engine_top.render([_canned_entry()])
    assert "engine llama3-8b" in frame
    assert "60/64" in frame          # occupancy
    assert "tok/s 1600.0" in frame
    assert "recompiles 4" in frame
    assert "kv pool" in frame
    # empty report renders a hint, not a crash
    assert "no live engines" in engine_top.render([])
