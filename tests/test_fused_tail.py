"""Device-resident decode tail: fused sample+pack, device drafting,
auto-disable.

The contracts this file pins: the packed decode output is a lossless
bit-exact fold of the unpacked outputs (the engine's ONE host fetch per
chunk carries everything the loop needs); the device prompt-lookup
drafter matches the engine's host bigram drafter token-for-token (greedy
byte-identity rests on verify, but draft parity keeps the accept ratio —
and so the perf posture — identical); the engine's dispatch/fetch
ledgers track 1:1 on both the plain and speculative paths; and the
measured-uplift plane flips speculation off (with a flight event) when
the fused step is not paying for itself, then re-auditions it after
enough plain chunks.
"""

import asyncio
import dataclasses
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(autouse=True)
def _fresh_engines():
    from langstream_tpu.serving.engine import TpuServingEngine

    TpuServingEngine.reset_instances()
    yield
    TpuServingEngine.reset_instances()


def _tool(name: str):
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    return __import__(name)


def greedy(logits, key):
    t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return t, jnp.zeros_like(t, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# model level: packed decode ≡ unpacked decode, bit for bit
# ---------------------------------------------------------------------------


def test_decode_chunk_packed_matches_unpacked():
    """``return_packed=True`` is a pure re-layout: parsing the packed
    buffer back on the host reproduces the unpacked chunk outputs
    bit-exactly (tokens int-equal, logprobs bitwise-equal through the
    int32 bitcast), and the carry outputs are untouched."""
    from langstream_tpu.models.llama import LlamaConfig, init_llama_params
    from langstream_tpu.models.llama_paged import (
        llama_decode_chunk_paged,
        llama_prefill_paged,
    )
    from langstream_tpu.models.paged import (
        BlockManager,
        PagedLayout,
        init_paged_kv_cache,
    )

    c = dataclasses.replace(
        LlamaConfig.tiny(max_seq_len=128), dtype=jnp.float32
    )
    params = init_llama_params(c, jax.random.PRNGKey(5))
    layout = PagedLayout.for_model(128, 2, block_size=16)
    prompts = jnp.array(
        [[5, 9, 17, 3, 11, 2, 7, 1], [4, 4, 8, 2, 9, 9, 1, 6]], jnp.int32
    )
    B, n, K = 2, 8, 6

    def logp_sample(logits, key):
        t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), t[:, None], axis=1
        ).squeeze(1)
        return t, lp

    def fresh():
        bm = BlockManager(layout, B)
        for b in range(B):
            bm.admit(b, 40)
            bm.ensure_capacity(b, 24)
        pk, pv = init_paged_kv_cache(c, layout)
        t = jnp.asarray(bm.tables[:B])
        logits, pk, pv = llama_prefill_paged(
            c, params, prompts, jnp.full((B,), n), pk, pv, t, use_flash=False
        )
        tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return tok0, pk, pv, t

    tok0, pk, pv, t = fresh()
    args = (
        c, params, tok0, jnp.full((B,), n), jnp.array([True, True]),
        pk, pv, t, logp_sample, jax.random.PRNGKey(0), K,
    )
    ct, clp, ft, fl, _, _ = llama_decode_chunk_paged(
        *args, num_read_blocks=2
    )
    tok0b, pkb, pvb, tb = fresh()
    argsb = (
        c, params, tok0b, jnp.full((B,), n), jnp.array([True, True]),
        pkb, pvb, tb, logp_sample, jax.random.PRNGKey(0), K,
    )
    packed, ft2, fl2, _, _ = llama_decode_chunk_paged(
        *argsb, num_read_blocks=2, return_packed=True
    )
    flat = np.asarray(packed)
    assert flat.dtype == np.int32 and flat.shape == (2 * K * B,)
    np.testing.assert_array_equal(
        flat[: K * B].reshape(K, B), np.asarray(ct)
    )
    # logprobs round-trip through the bitcast losslessly
    np.testing.assert_array_equal(
        flat[K * B:].view(np.float32).reshape(K, B), np.asarray(clp)
    )
    np.testing.assert_array_equal(np.asarray(ft), np.asarray(ft2))
    np.testing.assert_array_equal(np.asarray(fl), np.asarray(fl2))


# ---------------------------------------------------------------------------
# model level: device drafter ≡ host bigram drafter
# ---------------------------------------------------------------------------


def test_prompt_lookup_draft_matches_host_bigram():
    """The jitted drafter reproduces the engine's host semantics on
    random repetitive contexts at every length: last occurrence of the
    final bigram wins, continuation clipped to the valid region and
    zero-padded, no match (or n < 3) drafts nothing."""
    from langstream_tpu.models.llama_paged import prompt_lookup_draft

    S, D = 96, 4
    rng = np.random.default_rng(7)

    def host_ref(ctx, n):
        # the engine's _draft_tokens over an explicit context list
        idx = {}
        for i in range(1, n - 1):
            idx[(ctx[i - 1], ctx[i])] = i - 1
        if n >= 3:
            pos = idx.get((ctx[n - 2], ctx[n - 1]))
            if pos is not None:
                cont = list(ctx[pos + 2 : pos + 2 + D])
                return cont + [0] * (D - len(cont)), len(cont)
        return [0] * D, 0

    draft_fn = jax.jit(
        jax.vmap(lambda row, ln: prompt_lookup_draft(row, ln, D))
    )
    # small alphabet → bigrams repeat; include the degenerate lengths
    ctx = rng.integers(1, 7, size=(32, S)).astype(np.int32)
    lengths = np.concatenate(
        [[1, 2, 3], rng.integers(4, S + 1, size=29)]
    ).astype(np.int32)
    for b in range(32):
        ctx[b, lengths[b]:] = 0  # zero-padded like the engine's rows
    drafts, n_real = draft_fn(jnp.asarray(ctx), jnp.asarray(lengths))
    drafts, n_real = np.asarray(drafts), np.asarray(n_real)
    hit = 0
    for b in range(32):
        # the engine's host context is exactly n long (prompt+generated) —
        # slice the padding off before handing it to the reference
        ref_d, ref_n = host_ref(
            [int(x) for x in ctx[b, : lengths[b]]], int(lengths[b])
        )
        assert list(drafts[b]) == ref_d, (b, lengths[b])
        assert int(n_real[b]) == ref_n
        hit += ref_n > 0
    assert hit > 5  # the fixture actually exercises the match path


# ---------------------------------------------------------------------------
# engine level: one fetch per chunk, one fetch per spec step
# ---------------------------------------------------------------------------

BASE = dict(
    model="tiny", slots=4, max_seq_len=256, decode_chunk=4,
    kv_layout="paged", kv_block_size=16, paged_kernel="xla",
    model_dtype="float32",
)
REPETITIVE = "the cat sat on the mat. " * 6


def _gen(cfg_kwargs, prompt, options):
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def run():
        eng = TpuServingEngine(ServingConfig(**cfg_kwargs))
        try:
            out = await eng.generate(prompt, options)
        finally:
            # the final chunk's fetch may still be on the executor when
            # generate() resolves — close() joins the loop, so the
            # dispatch/fetch ledger read below is the settled one
            await eng.close()
        return out, eng.stats()

    return asyncio.run(run())


def test_decode_host_fetches_track_dispatches_one_to_one():
    """The one-fetch invariant, observable: every dispatched decode chunk
    costs exactly one packed host fetch — no separate token/logprob/pack
    crossings survive in the tail."""
    _, stats = _gen(BASE, REPETITIVE, {"max-tokens": 16})
    chunks = stats["decode-chunks"]
    assert chunks["dispatched"] >= 2
    assert chunks["fetched"] == chunks["dispatched"]
    assert chunks["host_fetches_per_chunk"] == 1.0


def test_spec_fetches_track_dispatches_one_to_one():
    """The fused speculative step is one dispatch + one packed fetch:
    draft, verify, sample, advance and pack all live in the program."""
    _, stats = _gen(
        {**BASE, "speculative_drafts": 4}, REPETITIVE, {"max-tokens": 24}
    )
    spec = stats["speculative"]
    assert spec["steps"] >= 2
    assert spec["dispatches"] == spec["steps"]
    assert spec["fetches"] == spec["dispatches"]


def test_fused_spec_path_graftcheck_clean():
    """The zero-host-sync contract, enforced: the hot decode/speculative
    closures carry no HOT1401/HOT1402 host syncs and the ctx-buffer
    handoff carries no RACE801/INV902 — the whole-tree gate already fails
    on ANY finding, this pins the specific rules the fused tail is built
    against (the content-hash cache keeps the repeat run cheap)."""
    from langstream_tpu.analysis import ALL_RULES, PROJECT_RULES, run

    report = run(ALL_RULES, project_rules=PROJECT_RULES)
    hot = [
        f.format() for f in report.new
        if f.rule in ("HOT1401", "HOT1402", "RACE801", "INV902")
    ]
    assert not hot, "\n".join(hot)


# ---------------------------------------------------------------------------
# engine level: measured-uplift auto-disable
# ---------------------------------------------------------------------------


def test_spec_auto_disable_on_measured_uplift_below_one(run_async):
    """Force uplift < 1 through the rolling windows: the engine flips
    speculation off, emits the ``spec-auto-disable`` flight event with
    the measured value, and after enough plain chunks re-enables with
    ``spec-auto-enable`` and an immediately-due recalibration."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def main():
        eng = TpuServingEngine(
            ServingConfig(**{**BASE, "speculative_drafts": 4})
        )
        try:
            # no verdict until the spec window is FULL and a plain
            # (calibration) sample exists — warmup jitter must not flap
            eng._spec_note_step(4, 1.0)
            assert eng._spec_uplift() is None
            assert eng._spec_check_uplift() is False
            for _ in range(eng._spec_window.maxlen):
                eng._spec_note_step(4, 1.0)   # spec: 4 tok/s
            assert eng._spec_uplift() is None  # still no plain sample
            eng._spec_note_plain(8, 1.0)      # plain: 8 tok/s → uplift 0.5
            assert eng._spec_check_uplift() is True
            assert eng._spec_auto_disabled is True
            assert eng._spec_last_uplift == pytest.approx(0.5)
            assert not eng._spec_window and not eng._plain_window
            spec = eng.stats()["speculative"]
            assert spec["auto_disabled"] is True
            assert spec["uplift"] == pytest.approx(0.5)
            assert spec["flips"] == 1
            disable = [
                e for e in eng.flight.recent_events()
                if e["kind"] == "spec-auto-disable"
            ]
            assert len(disable) == 1
            assert disable[0]["uplift"] == pytest.approx(0.5)
            # time-served re-enable: plain decode chunks while disabled
            # count up to the retry budget, then speculation re-auditions
            for _ in range(eng._spec_retry_plain):
                eng._flight_record("decode", 0.001)
            assert eng._spec_auto_disabled is False
            assert eng._spec_cal_due() is True  # recalibrate immediately
            assert any(
                e["kind"] == "spec-auto-enable"
                for e in eng.flight.recent_events()
            )
            assert eng.stats()["speculative"]["flips"] == 2
        finally:
            await eng.close()

    run_async(main())


def test_spec_uplift_at_or_above_one_keeps_speculating(run_async):
    """uplift >= 1 must NOT flip: the verdict records but the windows
    keep rolling (no flip event, no cleared state)."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine

    async def main():
        eng = TpuServingEngine(
            ServingConfig(**{**BASE, "speculative_drafts": 4})
        )
        try:
            for _ in range(eng._spec_window.maxlen):
                eng._spec_note_step(12, 1.0)  # spec: 12 tok/s
            eng._spec_note_plain(8, 1.0)      # plain: 8 tok/s → uplift 1.5
            assert eng._spec_check_uplift() is False
            assert eng._spec_auto_disabled is False
            assert eng._spec_last_uplift == pytest.approx(1.5)
            assert len(eng._spec_window) == eng._spec_window.maxlen
            assert not any(
                e["kind"].startswith("spec-auto")
                for e in eng.flight.recent_events()
            )
        finally:
            await eng.close()

    run_async(main())


# ---------------------------------------------------------------------------
# engine_top: speculation panel + thrash analyze flag
# ---------------------------------------------------------------------------

_SPEC_SECTION = {
    "steps": 40, "drafts_accepted": 90, "rejected": 30,
    "dispatches": 40, "fetches": 40, "uplift": 0.93,
    "auto_disabled": True, "flips": 4, "window_steps": 12,
    "window_plain": 3,
}


def _flip(kind, t_ms, **extra):
    return {"kind": kind, "t_ms": t_ms, "seq": int(t_ms), **extra}


def test_engine_top_speculation_panel_and_json():
    engine_top = _tool("engine_top")
    events = [
        _flip("spec-auto-disable", 100.0, uplift=0.91),
        _flip("spec-auto-enable", 900.0, plain_chunks=256),
    ]
    lines = engine_top._render_speculative(_SPEC_SECTION, events)
    text = "\n".join(lines)
    assert "accepted 90/120 (75.0%)" in text
    assert "dispatch/fetch 40/40" in text
    assert "uplift 0.93x" in text and "auto-DISABLED" in text
    assert "flips 4" in text
    assert "last flip spec-auto-enable" in text
    # absent section renders nothing (the non-speculative pin, panel-side)
    assert engine_top._render_speculative(None, []) == []
    # no uplift verdict yet → calibrating, auto on
    warm = engine_top._render_speculative(
        {**_SPEC_SECTION, "uplift": None, "auto_disabled": False}, []
    )
    assert "calibrating" in "\n".join(warm) and "auto on" in "\n".join(warm)
    # --json mirrors the rendered panel under its own key
    entry = {
        "model": "tiny", "summary": {"totals": {}}, "events": events,
        "speculative": _SPEC_SECTION,
    }
    payload = engine_top.render_json([entry])[0]
    assert payload["panels"]["speculative"]["lines"] == lines
    assert payload["panels"]["speculative"]["section"] is _SPEC_SECTION


def test_engine_top_analyze_flags_spec_thrash():
    engine_top = _tool("engine_top")
    flips = [
        _flip("spec-auto-disable", 100.0, uplift=0.91),
        _flip("spec-auto-enable", 900.0, plain_chunks=256),
        _flip("spec-auto-disable", 1500.0, uplift=0.97),
    ]
    entry = {
        "model": "tiny", "summary": {"totals": {}},
        "events": flips, "speculative": _SPEC_SECTION,
    }
    flags = engine_top._anomalies(entry)
    assert any("speculation thrash: 3" in f for f in flags)
    # two flips is the auto-disable machinery working, not thrash
    quiet = {**entry, "events": flips[:2]}
    assert not any(
        "speculation thrash" in f for f in engine_top._anomalies(quiet)
    )
    # rollup without an event tail: the section's flip counter flags
    rollup = {
        "model": "tiny", "summary": {"totals": {}}, "events": [],
        "speculative": {**_SPEC_SECTION, "flips": 5},
    }
    flags = engine_top._anomalies(rollup)
    assert any("speculation thrash: 5" in f for f in flags)
