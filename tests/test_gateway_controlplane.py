"""Gateway + control plane integration tests (in-process servers on
ephemeral ports, real HTTP/WS clients — the role the reference's
webservice/api-gateway Spring tests play)."""

import asyncio
import json
import socket

import aiohttp
import pytest

from langstream_tpu.controlplane.server import ControlPlaneServer, LocalComputeRuntime
from langstream_tpu.controlplane.stores import (
    FileSystemApplicationStore,
    InMemoryApplicationStore,
    StoredApplication,
)
from langstream_tpu.gateway.server import GatewayRegistry, GatewayServer

PIPELINE = """
topics:
  - name: "input-topic"
    creation-mode: create-if-not-exists
  - name: "output-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "annotate"
    type: "compute"
    input: "input-topic"
    output: "output-topic"
    configuration:
      fields:
        - name: "value.echo"
          expression: "fn:uppercase(value.q)"
"""

GATEWAYS = """
gateways:
  - id: "produce-input"
    type: produce
    topic: "input-topic"
    parameters: [sessionId]
    produce-options:
      headers:
        - key: "langstream-client-session-id"
          value-from-parameters: sessionId
  - id: "consume-output"
    type: consume
    topic: "output-topic"
    parameters: [sessionId]
    consume-options:
      filters:
        headers:
          - key: "langstream-client-session-id"
            value-from-parameters: sessionId
  - id: "chat"
    type: chat
    chat-options:
      questions-topic: "input-topic"
      answers-topic: "output-topic"
      headers:
        - key: "langstream-client-session-id"
          value-from-parameters: sessionId
  - id: "auth-produce"
    type: produce
    topic: "input-topic"
    authentication:
      provider: test
      configuration:
        require-credentials: true
    produce-options:
      headers:
        - key: "user"
          value-from-authentication: subject
"""

INSTANCE = """
instance:
  streamingCluster:
    type: memory
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Servers:
    def __init__(self):
        self.api_port = free_port()
        self.gw_port = free_port()

    async def __aenter__(self):
        self.registry = GatewayRegistry()
        self.compute = LocalComputeRuntime(gateway_registry=self.registry)
        self.store = InMemoryApplicationStore()
        self.control = ControlPlaneServer(
            store=self.store, compute=self.compute, port=self.api_port
        )
        self.gateway = GatewayServer(registry=self.registry, port=self.gw_port)
        await self.control.start()
        await self.gateway.start()
        self.session = aiohttp.ClientSession()
        return self

    async def __aexit__(self, *exc):
        await self.session.close()
        await self.gateway.stop()
        await self.control.stop()

    def api(self, path: str) -> str:
        return f"http://127.0.0.1:{self.api_port}{path}"

    def ws(self, path: str) -> str:
        return f"ws://127.0.0.1:{self.gw_port}{path}"


APP_PAYLOAD = {
    "files": {"pipeline.yaml": PIPELINE, "gateways.yaml": GATEWAYS},
    "instance": INSTANCE,
}


def test_tenant_and_app_lifecycle(run_async):
    async def main():
        async with Servers() as s:
            # tenant CRUD
            async with s.session.put(s.api("/api/tenants/t1")) as r:
                assert r.status == 200
            async with s.session.get(s.api("/api/tenants")) as r:
                assert "t1" in await r.json()
            # deploying to an unknown tenant fails
            async with s.session.post(
                s.api("/api/applications/nope/app1"), json=APP_PAYLOAD
            ) as r:
                assert r.status == 404
            # deploy
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=APP_PAYLOAD
            ) as r:
                assert r.status == 200
                body = await r.json()
                assert body["status"]["status"] == "DEPLOYED"
            # duplicate deploy conflicts
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=APP_PAYLOAD
            ) as r:
                assert r.status == 409
            # invalid app rejected at validation
            bad = {"files": {"p.yaml": "pipeline:\n  - name: x\n    type: compute\n    input: missing\n"}}
            async with s.session.post(
                s.api("/api/applications/t1/bad"), json=bad
            ) as r:
                assert r.status == 400
            # list / get / agents
            async with s.session.get(s.api("/api/applications/t1")) as r:
                assert await r.json() == ["app1"]
            async with s.session.get(s.api("/api/applications/t1/app1/agents")) as r:
                agents = await r.json()
                assert len(agents) == 1 and agents[0]["type"] == "compute"
            # code download: the deployed app dir back as a zip (no
            # instance/secrets in the archive)
            async with s.session.get(s.api("/api/applications/t1/app1/code")) as r:
                assert r.status == 200
                assert r.content_type == "application/zip"
                blob = await r.read()
            import io
            import zipfile

            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                assert sorted(zf.namelist()) == ["gateways.yaml", "pipeline.yaml"]
                assert zf.read("pipeline.yaml").decode() == PIPELINE
            async with s.session.get(s.api("/api/applications/t1/nope/code")) as r:
                assert r.status == 404
            # the CLI's `apps download` lane: AdminClient binary fetch
            from langstream_tpu.admin import AdminClient

            client = AdminClient(f"http://127.0.0.1:{s.api_port}")
            try:
                raw = await client.request(
                    "GET", "/api/applications/t1/app1/code", binary=True
                )
                assert raw == blob
            finally:
                await client.close()
            # delete
            async with s.session.delete(s.api("/api/applications/t1/app1")) as r:
                assert r.status == 200
            async with s.session.get(s.api("/api/applications/t1/app1")) as r:
                assert r.status == 404

    run_async(main())


def test_gateway_produce_consume_roundtrip(run_async):
    async def main():
        async with Servers() as s:
            async with s.session.put(s.api("/api/tenants/t1")):
                pass
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=APP_PAYLOAD
            ) as r:
                assert r.status == 200

            consume_url = s.ws(
                "/v1/consume/t1/app1/consume-output?param:sessionId=s1&option:position=earliest"
            )
            produce_url = s.ws("/v1/produce/t1/app1/produce-input?param:sessionId=s1")
            async with s.session.ws_connect(consume_url) as consumer:
                async with s.session.ws_connect(produce_url) as producer:
                    await producer.send_json({"value": {"q": "hello"}})
                    reply = await producer.receive_json()
                    assert reply["status"] == "OK"
                push = await asyncio.wait_for(consumer.receive_json(), timeout=10)
                assert push["record"]["value"]["echo"] == "HELLO"
                assert (
                    push["record"]["headers"]["langstream-client-session-id"] == "s1"
                )

            # session isolation: another session sees nothing
            other_url = s.ws(
                "/v1/consume/t1/app1/consume-output?param:sessionId=OTHER&option:position=earliest"
            )
            async with s.session.ws_connect(other_url) as other:
                with pytest.raises(asyncio.TimeoutError):
                    await asyncio.wait_for(other.receive_json(), timeout=1.0)

    run_async(main())


def test_gateway_chat(run_async):
    async def main():
        async with Servers() as s:
            async with s.session.put(s.api("/api/tenants/t1")):
                pass
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=APP_PAYLOAD
            ):
                pass
            chat_url = s.ws("/v1/chat/t1/app1/chat?param:sessionId=c1")
            async with s.session.ws_connect(chat_url) as chat:
                await chat.send_json({"value": {"q": "ping"}})
                ack = await chat.receive_json()
                assert ack["status"] == "OK"
                push = await asyncio.wait_for(chat.receive_json(), timeout=10)
                assert push["record"]["value"]["echo"] == "PING"

    run_async(main())


def test_gateway_missing_parameter_and_auth(run_async):
    async def main():
        async with Servers() as s:
            async with s.session.put(s.api("/api/tenants/t1")):
                pass
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=APP_PAYLOAD
            ):
                pass
            # missing declared parameter → 400
            async with s.session.get(
                s.ws("/v1/produce/t1/app1/produce-input")
            ) as resp:
                assert resp.status == 400
            # auth-required gateway without credentials → 401
            async with s.session.get(s.ws("/v1/produce/t1/app1/auth-produce")) as resp:
                assert resp.status == 401
            # with credentials: header injected from principal
            url = s.ws("/v1/produce/t1/app1/auth-produce?credentials=alice")
            async with s.session.ws_connect(url) as producer:
                await producer.send_json({"value": {"q": "x"}})
                assert (await producer.receive_json())["status"] == "OK"

    run_async(main())


def test_http_produce_and_service_gateway(run_async):
    async def main():
        gateways = GATEWAYS + """
  - id: "svc"
    type: service
    service-options:
      input-topic: "input-topic"
      output-topic: "output-topic"
      timeout-seconds: 10
"""
        payload = {
            "files": {"pipeline.yaml": PIPELINE, "gateways.yaml": gateways},
            "instance": INSTANCE,
        }
        async with Servers() as s:
            async with s.session.put(s.api("/api/tenants/t1")):
                pass
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=payload
            ) as r:
                assert r.status == 200
            # HTTP produce
            async with s.session.post(
                f"http://127.0.0.1:{s.gw_port}/api/gateways/produce/t1/app1/produce-input?param:sessionId=h1",
                json={"value": {"q": "via-http"}},
            ) as r:
                assert r.status == 200
            # service gateway: full request/response over the pipeline
            async with s.session.post(
                f"http://127.0.0.1:{s.gw_port}/api/gateways/service/t1/app1/svc/",
                json={"value": {"q": "svc"}},
            ) as r:
                assert r.status == 200
                body = await r.json()
                assert body["record"]["value"]["echo"] == "SVC"

    run_async(main())


def test_deploy_rejects_path_traversal_filenames(run_async):
    async def main():
        async with Servers() as s:
            async with s.session.put(s.api("/api/tenants/t1")):
                pass
            evil = {"files": {"../../evil.yaml": PIPELINE}}
            async with s.session.post(
                s.api("/api/applications/t1/evil"), json=evil
            ) as r:
                assert r.status == 400
            evil2 = {"files": {"sub/dir.yaml": PIPELINE}}
            async with s.session.post(
                s.api("/api/applications/t1/evil2"), json=evil2
            ) as r:
                assert r.status == 400

    run_async(main())


def test_failed_update_leaves_app_running(run_async):
    async def main():
        async with Servers() as s:
            async with s.session.put(s.api("/api/tenants/t1")):
                pass
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=APP_PAYLOAD
            ) as r:
                assert r.status == 200
            # update with a broken pipeline: rejected, old app still live
            bad = {"files": {"pipeline.yaml": "pipeline:\n  - name: x\n    type: compute\n    input: missing\n"}}
            async with s.session.patch(
                s.api("/api/applications/t1/app1"), json=bad
            ) as r:
                assert r.status == 400
            # the original pipeline still serves traffic
            url = s.ws("/v1/chat/t1/app1/chat?param:sessionId=u1")
            async with s.session.ws_connect(url) as chat:
                await chat.send_json({"value": {"q": "alive"}})
                await chat.receive_json()  # ack
                push = await asyncio.wait_for(chat.receive_json(), timeout=10)
                assert push["record"]["value"]["echo"] == "ALIVE"

    run_async(main())


def test_consume_push_carries_offset(run_async):
    async def main():
        async with Servers() as s:
            async with s.session.put(s.api("/api/tenants/t1")):
                pass
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=APP_PAYLOAD
            ):
                pass
            consume_url = s.ws(
                "/v1/consume/t1/app1/consume-output?param:sessionId=s1&option:position=earliest"
            )
            produce_url = s.ws("/v1/produce/t1/app1/produce-input?param:sessionId=s1")
            async with s.session.ws_connect(consume_url) as consumer:
                async with s.session.ws_connect(produce_url) as producer:
                    await producer.send_json({"value": {"q": "o"}})
                    await producer.receive_json()
                push = await asyncio.wait_for(consumer.receive_json(), timeout=10)
                assert push["offset"] is not None
                assert push["offset"].startswith("output-topic:")

    run_async(main())


def test_service_gateway_without_trailing_slash(run_async):
    async def main():
        gateways = GATEWAYS + """
  - id: "svc"
    type: service
    service-options:
      input-topic: "input-topic"
      output-topic: "output-topic"
"""
        payload = {
            "files": {"pipeline.yaml": PIPELINE, "gateways.yaml": gateways},
            "instance": INSTANCE,
        }
        async with Servers() as s:
            async with s.session.put(s.api("/api/tenants/t1")):
                pass
            async with s.session.post(
                s.api("/api/applications/t1/app1"), json=payload
            ):
                pass
            async with s.session.post(
                f"http://127.0.0.1:{s.gw_port}/api/gateways/service/t1/app1/svc",
                json={"value": {"q": "noslash"}},
            ) as r:
                assert r.status == 200
                body = await r.json()
                assert body["record"]["value"]["echo"] == "NOSLASH"

    run_async(main())


def test_ws_url_encoding():
    from langstream_tpu.cli.main import _gw_ws_url

    url = _gw_ws_url(
        "http://h:1", "produce", "t", "a", "g", ("sessionId=a&b=c",), "tok=en%"
    )
    assert "param:sessionId=a%26b%3Dc" in url
    assert "credentials=tok%3Den%25" in url


def test_filesystem_store_roundtrip(tmp_path, run_async):
    async def main():
        store = FileSystemApplicationStore(tmp_path)
        store.put_tenant("t1", {"plan": "dev"})
        stored = StoredApplication(
            tenant="t1",
            name="a1",
            files={"pipeline.yaml": PIPELINE},
            instance=INSTANCE,
            status="DEPLOYED",
        )
        store.put_application(stored)
        # fresh store instance reads back from disk
        store2 = FileSystemApplicationStore(tmp_path)
        assert store2.list_tenants() == {"t1": {"plan": "dev"}}
        loaded = store2.get_application("t1", "a1")
        assert loaded.status == "DEPLOYED"
        assert loaded.files["pipeline.yaml"] == PIPELINE
        assert store2.list_applications("t1") == ["a1"]
        store2.delete_application("t1", "a1")
        assert store2.list_applications("t1") == []

    run_async(main())


def test_cli_dev_mode_smoke(tmp_path, run_async):
    """Drive the CLI's in-process building blocks (the `run` command's guts)."""

    async def main():
        from langstream_tpu.cli.main import _collect_files

        (tmp_path / "pipeline.yaml").write_text(PIPELINE)
        (tmp_path / "gateways.yaml").write_text(GATEWAYS)
        files = _collect_files(tmp_path)
        assert set(files) == {"pipeline.yaml", "gateways.yaml"}

    run_async(main())


def test_service_gateway_agent_proxy_mode(run_async):
    """service gateway with agent-id proxies requests to the agent's
    service URI (parity: GatewayResource.java:235-241) — method, tail path,
    query, body, and response status/headers forwarded."""
    from aiohttp import web as aioweb

    gateways_proxy = """
gateways:
  - id: "svc"
    type: service
    service-options:
      agent-id: "my-service"
"""

    async def main():
        # a fake agent service
        seen = []

        async def agent_handle(request):
            seen.append(
                (request.method, request.path_qs, await request.text())
            )
            return aioweb.json_response(
                {"from": "agent"}, status=201, headers={"X-Agent": "yes"}
            )

        agent_app = aioweb.Application()
        agent_app.router.add_route("*", "/{tail:.*}", agent_handle)
        agent_runner = aioweb.AppRunner(agent_app)
        await agent_runner.setup()
        agent_port = free_port()
        await aioweb.TCPSite(agent_runner, "127.0.0.1", agent_port).start()
        try:
            async with Servers() as s:
                async with s.session.put(s.api("/api/tenants/t1")):
                    pass
                payload = {
                    "files": {
                        "pipeline.yaml": PIPELINE,
                        "gateways.yaml": gateways_proxy,
                    },
                    "instance": INSTANCE,
                }
                async with s.session.post(
                    s.api("/api/applications/t1/app1"), json=payload
                ) as r:
                    assert r.status == 200, await r.text()
                s.registry.register_service_uri(
                    "t1", "app1", "my-service", f"http://127.0.0.1:{agent_port}"
                )
                url = (
                    f"http://127.0.0.1:{s.gw_port}"
                    "/api/gateways/service/t1/app1/svc/v1/predict?x=1"
                )
                async with s.session.post(url, json={"q": "hi"}) as resp:
                    assert resp.status == 201
                    assert resp.headers["X-Agent"] == "yes"
                    assert await resp.json() == {"from": "agent"}
                method, path_qs, body = seen[0]
                assert method == "POST"
                assert path_qs == "/v1/predict?x=1"
                assert "hi" in body
                # GET without a body forwards too (topic mode is POST-only)
                async with s.session.get(url) as resp:
                    assert resp.status == 201
                # unreachable agent → 502, not a hang
                s.registry.register_service_uri(
                    "t1", "app1", "my-service", "http://127.0.0.1:1"
                )
                async with s.session.get(url) as resp:
                    assert resp.status == 502
        finally:
            await agent_runner.cleanup()

    run_async(main())


def test_k8s_compute_runtime_writes_agent_crs(run_async):
    """The in-cluster compute runtime: deploy plans the app and writes
    Agent CRs + config Secrets; undeploy removes them (the role the
    reference's webservice plays against langstream-k8s-deployer)."""
    from langstream_tpu.controlplane.stores import StoredApplication
    from langstream_tpu.k8s.client import InMemoryKubeApi
    from langstream_tpu.k8s.compute import KubernetesComputeRuntime

    async def main():
        api = InMemoryKubeApi()
        compute = KubernetesComputeRuntime(api, image="img:1")
        stored = StoredApplication(
            tenant="t1",
            name="app1",
            files={"pipeline.yaml": PIPELINE},
            instance=INSTANCE,
        )
        await compute.deploy(stored)
        agents = api.list("Agent", "langstream-t1")
        assert len(agents) == 1
        assert agents[0]["spec"]["applicationId"] == "app1"
        secrets = api.list("Secret", "langstream-t1")
        assert any("-config" in s["metadata"]["name"] for s in secrets)
        info = compute.agent_info("t1", "app1")
        assert info and info[0]["agent-id"]
        await compute.undeploy("t1", "app1")
        assert api.list("Agent", "langstream-t1") == []

    run_async(main())


def test_apps_ui_serves_bundled_chat_page():
    """`apps ui` serves the CLI-bundled chat page against a gateway
    (parity: langstream-cli/src/main/resources/app-ui/index.html served by
    `langstream apps ui`; r3 verdict missing #6)."""
    import socket
    import threading
    import time
    import urllib.request

    from click.testing import CliRunner

    from langstream_tpu.cli.main import cli

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    result = {}

    def run():
        result["r"] = CliRunner().invoke(
            cli,
            ["apps", "ui", "myapp", "--port", str(port), "--no-open",
             "--once", "--gateway", "qa", "--gateway-url", "ws://gw:1",
             "--tenant", "acme"],
        )

    t = threading.Thread(target=run, daemon=True)
    t.start()
    body = b""
    for _ in range(100):
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=1
            ).read()
            break
        except OSError:
            time.sleep(0.05)
    t.join(10)
    assert b"langstream-tpu chat" in body
    assert b"/v1/chat/" in body  # speaks the chat gateway protocol
    r = result["r"]
    assert r.exit_code == 0, r.output
    assert "tenant=acme" in r.output and "app=myapp" in r.output
    assert "gw=qa" in r.output
